"""P2E-DV1 agent builder (reference: ``/root/reference/sheeprl/algos/p2e_dv1/agent.py``).

DreamerV1 stack + exploration actor and critic (no target critics in DV1) and a
disagreement ensemble predicting the next **observation embedding** (reference
``agent.py:128-141``)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import gymnasium
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.agent import (
    PlayerState,  # noqa: F401
    build_agent as dv1_build_agent,
    make_player_step,  # noqa: F401
)
from sheeprl_tpu.algos.dreamer_v2.agent import _xavier_normal_init
from sheeprl_tpu.algos.dreamer_v3.agent import parse_actions_dim  # noqa: F401
from sheeprl_tpu.algos.p2e import build_ensembles


def embedding_dim(cfg, obs_space) -> int:
    """Encoder output size: VALID 4-stage CNN trunk + dense trunk (reference derives it
    from the built encoder, ``agent.py:131-136``)."""
    dim = 0
    if cfg.algo.cnn_keys.encoder:
        final = cfg.env.screen_size
        for _ in range(4):
            final = (final - 4) // 2 + 1
        dim += final * final * cfg.algo.world_model.encoder.cnn_channels_multiplier * 8
    if cfg.algo.mlp_keys.encoder:
        dim += cfg.algo.dense_units
    return dim


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    world_model, actor, critic, dv1_params, latent_size = dv1_build_agent(
        ctx, actions_dim, is_continuous, cfg, obs_space
    )

    actor_expl_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    actor_expl_params = {"params": _xavier_normal_init(actor_expl_params["params"], ctx.rng())}
    critic_expl_params = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))
    critic_expl_params = {"params": _xavier_normal_init(critic_expl_params["params"], ctx.rng())}

    wm_cfg = cfg.algo.world_model
    ens_cfg = cfg.algo.ensembles
    ensemble_mlp, ensemble_params = build_ensembles(
        ctx.rng(),
        n=ens_cfg.n,
        input_dim=int(sum(actions_dim)) + wm_cfg.recurrent_model.recurrent_state_size + wm_cfg.stochastic_size,
        output_dim=embedding_dim(cfg, obs_space),
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=False,
        dtype=ctx.compute_dtype,
    )

    params = {
        "world_model": dv1_params["world_model"],
        "actor_task": dv1_params["actor"],
        "critic_task": dv1_params["critic"],
        "actor_exploration": ctx.replicate(actor_expl_params),
        "critic_exploration": ctx.replicate(critic_expl_params),
        "ensembles": ctx.replicate(ensemble_params),
    }
    return world_model, actor, critic, ensemble_mlp, params, latent_size
