"""SAC-AE evaluation entry (reference: ``algos/sac_ae/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.sac_ae import test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["sac_ae"])
def evaluate_sac_ae(ctx, cfg: Dict[str, Any], ckpt_path: str) -> float:
    log_dir = get_log_dir(cfg)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()
    cnn_keys = list(cfg.algo.cnn_keys.encoder)

    encoder, decoder, critic, actor, params = build_agent(ctx, act_space, obs_space, cfg)
    state = CheckpointManager.load(ckpt_path, templates={"params": jax.device_get(params)})
    params = ctx.replicate(state["params"])

    @jax.jit
    def greedy_fn(p, img):
        z = encoder.apply(p["encoder"], img)
        mean, _ = actor.apply(p["actor"], z)
        return jnp.tanh(mean)

    def img_fn(o):
        parts = []
        for k in cnn_keys:
            v = np.asarray(o[k])
            parts.append(v.reshape(v.shape[0], -1, *v.shape[-2:]))
        return np.concatenate(parts, axis=1).astype(np.float32)

    reward = test(greedy_fn, params, ctx, cfg, log_dir, img_fn)
    print(f"Test/cumulative_reward: {reward}")
    return reward
