"""SAC-AE agent (reference: ``/root/reference/sheeprl/algos/sac_ae/agent.py``).

Pixel SAC with a convolutional autoencoder (Yarats et al., arXiv:1910.01741):

* encoder: conv trunk → dense latent → LayerNorm → tanh (shared by the critics;
  the actor uses stop-gradient features, reference ``sac_ae.py:80-84``);
* decoder mirrors the encoder; trained with bit-depth-reduced MSE + an L2 latent
  penalty (``sac_ae.py:100-115``);
* EMA targets for both the encoder (tau 0.05) and the critics (tau 0.01).

Convolutions run NHWC with SAME padding (exact halving/doubling) instead of the
reference's VALID+output-padding arithmetic — architecturally equivalent, cleaner on
the MXU."""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.models.blocks import MLP


class AEEncoder(nn.Module):
    latent_dim: int = 50
    channels: int = 32
    screen_size: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, detach: bool = False) -> jax.Array:
        # x: [B, C, H, W] float in [0, 1] → NHWC
        x = jnp.moveaxis(x, -3, -1).astype(self.dtype)
        strides = (2, 1, 1, 1)
        for s in strides:
            x = nn.relu(nn.Conv(self.channels, (3, 3), strides=(s, s), padding="SAME", dtype=self.dtype)(x))
        x = x.reshape(*x.shape[:-3], -1)
        z = nn.Dense(self.latent_dim, dtype=self.dtype)(x)
        z = nn.LayerNorm(dtype=self.dtype)(z)
        z = jnp.tanh(z).astype(jnp.float32)
        if detach:
            z = jax.lax.stop_gradient(z)
        return z


class AEDecoder(nn.Module):
    output_channels: int
    latent_dim: int = 50
    channels: int = 32
    screen_size: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        h = self.screen_size // 2
        x = nn.Dense(h * h * self.channels, dtype=self.dtype)(z.astype(self.dtype))
        x = nn.relu(x)
        lead = x.shape[:-1]
        x = x.reshape(-1, h, h, self.channels)
        for s in (1, 1, 1):
            x = nn.relu(nn.ConvTranspose(self.channels, (3, 3), strides=(s, s), padding="SAME", dtype=self.dtype)(x))
        x = nn.ConvTranspose(self.output_channels, (3, 3), strides=(2, 2), padding="SAME", dtype=self.dtype)(x)
        x = jnp.moveaxis(x, -1, -3).astype(jnp.float32)  # back to [.., C, H, W]
        return x.reshape(*lead, *x.shape[-3:])


class AECriticEnsemble(nn.Module):
    """Q heads over [latent, action] (the encoder is applied by the caller)."""

    n_critics: int = 2
    hidden_size: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([z, action], -1)
        ensemble = nn.vmap(
            MLP,
            in_axes=None,
            out_axes=0,
            axis_size=self.n_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        return ensemble(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=1,
            activation="relu",
            dtype=self.dtype,
        )(x).astype(jnp.float32)


def preprocess_obs(obs: jax.Array, bits: int = 5) -> jax.Array:
    """Bit-depth reduction (reference ``sac_ae/utils.py preprocess_obs``)."""
    bins = 2**bits
    obs = obs.astype(jnp.float32)
    obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jnp.zeros_like(obs)  # no dither (deterministic path)
    return obs - 0.5


def build_agent(
    ctx,
    action_space: gymnasium.spaces.Space,
    obs_space: gymnasium.spaces.Dict,
    cfg: Dict[str, Any],
):
    if not isinstance(action_space, gymnasium.spaces.Box):
        raise ValueError("SAC-AE supports continuous (Box) action spaces only")
    act_dim = int(np.prod(action_space.shape))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    if not cnn_keys:
        raise ValueError("SAC-AE requires at least one cnn key")
    total_c = int(sum(np.prod(obs_space[k].shape[:-2]) for k in cnn_keys))

    encoder = AEEncoder(
        latent_dim=cfg.algo.encoder.features_dim,
        channels=cfg.algo.encoder.channels,
        screen_size=cfg.env.screen_size,
        dtype=ctx.compute_dtype,
    )
    decoder = AEDecoder(
        output_channels=total_c,
        latent_dim=cfg.algo.encoder.features_dim,
        channels=cfg.algo.encoder.channels,
        screen_size=cfg.env.screen_size,
        dtype=ctx.compute_dtype,
    )
    critic = AECriticEnsemble(
        n_critics=cfg.algo.critic.n, hidden_size=cfg.algo.critic.dense_units, dtype=ctx.compute_dtype
    )
    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.actor.dense_units, dtype=ctx.compute_dtype)

    dummy_img = jnp.zeros((1, total_c, cfg.env.screen_size, cfg.env.screen_size))
    enc_params = encoder.init(ctx.rng(), dummy_img)
    z = encoder.apply(enc_params, dummy_img)
    params = {
        "encoder": enc_params,
        "decoder": decoder.init(ctx.rng(), z),
        "critic": critic.init(ctx.rng(), z, jnp.zeros((1, act_dim))),
        "actor": actor.init(ctx.rng(), z),
        "log_alpha": jnp.asarray(jnp.log(cfg.algo.alpha.alpha), dtype=jnp.float32),
    }
    params["target_encoder"] = jax.tree.map(lambda x: x, params["encoder"])
    params["target_critic"] = jax.tree.map(lambda x: x, params["critic"])
    return encoder, decoder, critic, actor, ctx.replicate(params)
