"""SAC-AE training loop (reference: ``/root/reference/sheeprl/algos/sac_ae/sac_ae.py``).

Update cadence preserved from the reference (``sac_ae.py:62-115``): critic every step
(gradients flow into the encoder), actor+α every ``actor.per_rank_update_freq`` steps on
stop-gradient features, encoder+decoder reconstruction every
``decoder.per_rank_update_freq`` steps, EMA targets (encoder AND critic) every
``critic.per_rank_target_network_update_freq`` steps.  All G gradient steps of an
iteration run in one ``lax.scan`` with the step counter in the carry driving the
frequency conditionals."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled, strict_guard
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss, critic_loss
from sheeprl_tpu.algos.sac_ae.agent import build_agent, preprocess_obs
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import make_transition_ring
from sheeprl_tpu.data.prefetch import maybe_prefetcher
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.rollout import rollout_metrics
from sheeprl_tpu.utils.blocks import FusedRingDispatcher, WindowedFutures
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}


def make_sac_ae_train_fn(encoder, decoder, critic, actor, cfg, act_space):
    """Optimizers + the jitted scanned SAC-AE update over ``[G, B]`` batch blocks
    (critic every step with encoder gradients, actor/alpha and encoder+decoder
    reconstruction on their own cadences, EMA targets fused in).

    Module-level (rather than a closure in ``main``) so the IR audit
    (``sheeprl_tpu.analysis.ir``) can AOT-lower the exact update the entry point
    jits; the fused device-ring block inlines the same function."""
    act_dim = int(np.prod(act_space.shape))
    target_entropy = -act_dim
    gamma = cfg.algo.gamma
    health = health_enabled(cfg)  # trace-time constant (obs/health.py)
    critic_tau = cfg.algo.critic.tau
    encoder_tau = cfg.algo.encoder.tau
    actor_freq = cfg.algo.actor.per_rank_update_freq
    decoder_freq = cfg.algo.decoder.per_rank_update_freq
    target_freq = cfg.algo.critic.per_rank_target_network_update_freq
    l2_lambda = cfg.algo.decoder.l2_lambda

    actor_opt = make_optimizer(cfg.algo.actor.optimizer, 0.0)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, 0.0)  # covers encoder+critic
    alpha_opt = make_optimizer(cfg.algo.alpha.optimizer, 0.0)
    enc_opt = make_optimizer(cfg.algo.encoder.optimizer, 0.0)
    dec_opt = make_optimizer(cfg.algo.decoder.optimizer, 0.0)

    def _encode(enc_params, img, detach=False):
        return encoder.apply(enc_params, img, detach)

    @jax.jit
    def train_fn(p, o_state, batches, key, step0):
        def step(carry, batch):
            p, o_state, gstep = carry
            k_next, k_new, k_drop = jax.random.split(batch.pop("_key"), 3)
            alpha = jnp.exp(p["log_alpha"])
            obs = batch["obs"] / 255.0
            next_obs = batch["next_obs"] / 255.0

            # --- critic (encoder gradients flow)
            z_next_t = _encode(p["target_encoder"], next_obs)
            next_mean, next_log_std = actor.apply(p["actor"], z_next_t)
            next_act, next_logp = actor.dist(next_mean, next_log_std).sample_and_log_prob(k_next)
            next_logp = next_logp.sum(-1, keepdims=True)
            q_next = critic.apply(p["target_critic"], z_next_t, next_act).min(axis=0)
            target = jax.lax.stop_gradient(
                batch["rewards"] + (1 - batch["dones"]) * gamma * (q_next - alpha * next_logp)
            )

            def c_loss(enc_crit):
                z = _encode(enc_crit["encoder"], obs)
                qs = critic.apply(enc_crit["critic"], z, batch["actions"])
                return critic_loss(qs, target)

            cl, c_grads = jax.value_and_grad(c_loss)({"encoder": p["encoder"], "critic": p["critic"]})
            c_updates, new_c_state = critic_opt.update(
                c_grads, o_state["critic"], {"encoder": p["encoder"], "critic": p["critic"]}
            )
            new_ec = optax.apply_updates({"encoder": p["encoder"], "critic": p["critic"]}, c_updates)
            p = {**p, "encoder": new_ec["encoder"], "critic": new_ec["critic"]}
            o_state = {**o_state, "critic": new_c_state}

            # --- EMA targets
            def do_targets(p):
                return {
                    **p,
                    "target_critic": jax.tree.map(
                        lambda tp, cp: (1 - critic_tau) * tp + critic_tau * cp, p["target_critic"], p["critic"]
                    ),
                    "target_encoder": jax.tree.map(
                        lambda tp, cp: (1 - encoder_tau) * tp + encoder_tau * cp, p["target_encoder"], p["encoder"]
                    ),
                }

            p = jax.lax.cond(gstep % target_freq == 0, do_targets, lambda p: p, p)

            # --- actor + alpha (stop-gradient encoder features)
            def do_actor(operand):
                p, o_state = operand
                z = jax.lax.stop_gradient(_encode(p["encoder"], obs))

                def a_loss(ap):
                    mean, log_std = actor.apply(ap, z)
                    new_act, logp = actor.dist(mean, log_std).sample_and_log_prob(k_new)
                    logp = logp.sum(-1, keepdims=True)
                    min_q = critic.apply(p["critic"], z, new_act).min(axis=0)
                    return actor_loss(jnp.exp(p["log_alpha"]), logp, min_q), logp

                (al, logp), a_grads = jax.value_and_grad(a_loss, has_aux=True)(p["actor"])
                a_updates, new_a_state = actor_opt.update(a_grads, o_state["actor"], p["actor"])
                p = {**p, "actor": optax.apply_updates(p["actor"], a_updates)}
                tl, t_grads = jax.value_and_grad(lambda la: alpha_loss(la, logp, target_entropy))(p["log_alpha"])
                t_updates, new_t_state = alpha_opt.update(t_grads, o_state["alpha"], p["log_alpha"])
                p = {**p, "log_alpha": optax.apply_updates(p["log_alpha"], t_updates)}
                return (p, {**o_state, "actor": new_a_state, "alpha": new_t_state}), al, tl

            (p, o_state), al, tl = jax.lax.cond(
                gstep % actor_freq == 0,
                do_actor,
                lambda operand: (operand, jnp.zeros(()), jnp.zeros(())),
                (p, o_state),
            )

            # --- autoencoder
            def do_decoder(operand):
                p, o_state = operand

                def r_loss(enc_dec):
                    z = _encode(enc_dec["encoder"], obs)
                    recon = decoder.apply(enc_dec["decoder"], z)
                    target = preprocess_obs(batch["obs"], bits=5)
                    mse = ((recon - target) ** 2).mean()
                    l2 = (0.5 * (z**2).sum(-1)).mean()
                    return mse + l2_lambda * l2

                rl, grads = jax.value_and_grad(r_loss)({"encoder": p["encoder"], "decoder": p["decoder"]})
                e_updates, new_e_state = enc_opt.update(grads["encoder"], o_state["encoder"], p["encoder"])
                d_updates, new_d_state = dec_opt.update(grads["decoder"], o_state["decoder"], p["decoder"])
                p = {
                    **p,
                    "encoder": optax.apply_updates(p["encoder"], e_updates),
                    "decoder": optax.apply_updates(p["decoder"], d_updates),
                }
                return (p, {**o_state, "encoder": new_e_state, "decoder": new_d_state}), rl

            (p, o_state), rl = jax.lax.cond(
                gstep % decoder_freq == 0, do_decoder, lambda operand: (operand, jnp.zeros(())), (p, o_state)
            )
            metrics = {
                "Loss/value_loss": cl,
                "Loss/policy_loss": al,
                "Loss/alpha_loss": tl,
                "Loss/reconstruction_loss": rl,
            }
            if health:
                # Critic-path grads/updates are unconditional; the actor/decoder
                # branches live inside lax.cond and keep their own cadence.
                metrics.update(
                    diagnostics(
                        grads={"critic": c_grads},
                        params=p,
                        updates={"critic": c_updates},
                        aux={"target_q_mean": target.mean()},
                    )
                )
            return (p, o_state, gstep + 1), metrics

        g = batches["obs"].shape[0]
        batches["_key"] = jax.random.split(key, g)
        (p, o_state, _), metrics = jax.lax.scan(step, (p, o_state, step0), batches)
        metrics = jax.tree.map(jnp.mean, metrics)
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict_enabled(cfg):  # trace-time constant
            nan_scan(metrics, "sac_ae/train_fn")
        return p, o_state, metrics

    return actor_opt, critic_opt, alpha_opt, enc_opt, dec_opt, train_fn


@register_algorithm(name="sac_ae")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    act_low, act_high = act_space.low, act_space.high
    rescale = np.isfinite(act_low).all() and np.isfinite(act_high).all()
    act_dim = int(np.prod(act_space.shape))
    target_entropy = -act_dim

    encoder, decoder, critic, actor, params = build_agent(ctx, act_space, obs_space, cfg)

    actor_opt, critic_opt, alpha_opt, enc_opt, dec_opt, raw_train_fn = make_sac_ae_train_fn(
        encoder, decoder, critic, actor, cfg, act_space
    )
    opt_state = ctx.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init({"encoder": params["encoder"], "critic": params["critic"]}),
            "alpha": alpha_opt.init(params["log_alpha"]),
            "encoder": enc_opt.init(params["encoder"]),
            "decoder": dec_opt.init(params["decoder"]),
        }
    )

    num_envs = cfg.env.num_envs
    world = jax.process_count()
    rb = ReplayBuffer(
        max(int(cfg.buffer.size) // max(num_envs * world, 1), 1),
        num_envs,
        obs_keys=cnn_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)

    # Device-resident replay (buffer.device=True): SAC-AE rows carry BOTH obs and
    # next-obs pixels, so the host path ships ~2× the Dreamer volume per batch —
    # the HBM transition ring removes that entirely, and the fused scanned block
    # samples its indices IN-JIT from the carried PRNG key (one donated dispatch
    # per gradient block, zero per-step host work).  The ring is not shard_map'd,
    # so the shared gate runs with allow_dp=False (DP falls back to the host
    # prefetcher) inside make_transition_ring.
    h, w = obs_space[cnn_keys[0]].shape[-2:]
    c_total = sum(int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys)
    ring = make_transition_ring(
        ctx,
        cfg,
        rb,
        {
            "obs": ((c_total, h, w), jnp.uint8),
            "next_obs": ((c_total, h, w), jnp.uint8),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size

    def _encode(enc_params, img, detach=False):
        return encoder.apply(enc_params, img, detach)

    @jax.jit
    def act_fn(p, img, key):
        z = _encode(p["encoder"], img)
        mean, log_std = actor.apply(p["actor"], z)
        return actor.dist(mean, log_std).sample(key)

    @jax.jit
    def greedy_fn(p, img):
        z = _encode(p["encoder"], img)
        mean, _ = actor.apply(p["actor"], z)
        return jnp.tanh(mean)

    # analysis.strict: signature guard on the jitted update (drift -> hard error).
    # The fused ring block below inlines the RAW update (its outer jit carries the
    # guard semantics via the dispatcher's fixed signature).
    train_fn = obs_perf.instrument(cfg, "sac_ae/train_fn", strict_guard(cfg, "sac_ae/train_fn", raw_train_fn))

    futures = WindowedFutures()
    fused = None
    if ring is not None:
        sample_gather = ring.make_sample_gather(batch_size)

        def fused_builder(k, last):
            def block(carry, arrays, filled, rows_added, base_key, start_count):
                # Draw the whole [k, B] block IN-JIT (uniform index sampling off
                # the carried key, HBM gather), then run the exact scanned update
                # the host path jits — one donated dispatch either way.
                counts = jnp.asarray(start_count, jnp.int32) + jnp.arange(k, dtype=jnp.int32)

                def draw(count):
                    return sample_gather(arrays, filled, rows_added, jax.random.fold_in(base_key, count))

                batches, ages = jax.vmap(draw)(counts)
                p, o_state, metrics = raw_train_fn(
                    carry["params"],
                    carry["opt_state"],
                    batches,
                    jax.random.fold_in(base_key, start_count),
                    jnp.asarray(start_count, jnp.int32),
                )
                if health_enabled(cfg):  # staleness rides the deferred-metrics tree
                    metrics = {
                        **metrics,
                        "Health/replay_age_mean": ages["Health/replay_age_mean"].mean(),
                        "Health/replay_age_max": ages["Health/replay_age_max"].max(),
                    }
                return {"params": p, "opt_state": o_state}, metrics

            return block

        fused = FusedRingDispatcher(
            fused_builder, base_key=ctx.rng(), futures=futures, cfg=cfg, perf_name="sac_ae/fused_block"
        )
        # Donation safety: the target networks alias their online buffers at init
        # (identity tree.map in build_agent) — a donated carry must not contain
        # the same buffer twice.
        params = jax.tree.map(jnp.copy, params)
        opt_state = jax.tree.map(jnp.copy, opt_state)

    policy_steps_per_iter = num_envs * world
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_iters = max(learning_starts - 1, 0)

    start_iter, policy_step, last_log, last_checkpoint, cumulative_grad_steps = 1, 0, 0, 0, 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if ring is not None and len(rb) > 0:
                # The host buffer stays the source of truth: rebuild the HBM ring
                # (and its staleness stamps) from the restored rows.
                ring.load_from_transitions(
                    {
                        "obs": np.concatenate([rb[k] for k in cnn_keys], axis=2),
                        "next_obs": np.concatenate([rb[f"next_{k}"] for k in cnn_keys], axis=2),
                        "actions": rb["actions"],
                        "rewards": rb["rewards"],
                        "dones": rb["dones"],
                    },
                    stamps=rb.row_stamps,
                )

    def _img(o, idxs=None):
        parts = []
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            parts.append(v.reshape(v.shape[0], -1, *v.shape[-2:]))
        return np.concatenate(parts, axis=1).astype(np.float32)

    obs, _ = envs.reset(seed=cfg.seed + rank)
    step_data: Dict[str, np.ndarray] = {}

    # Async host-side sampling + deferred metrics (see sac.py / utils/blocks.py).
    def _sample_block(n: int):
        sample = rb.sample(batch_size * n)

        def cat_imgs(prefix=""):
            return np.concatenate(
                [
                    sample[f"{prefix}{k}"].reshape(n, batch_size, -1, *sample[f"{prefix}{k}"].shape[-2:])
                    for k in cnn_keys
                ],
                axis=2,
            )

        return ctx.put_batch(
            {
                "obs": cat_imgs(),
                "next_obs": cat_imgs("next_"),
                "actions": sample["actions"].reshape(n, batch_size, -1),
                "rewards": sample["rewards"].reshape(n, batch_size, 1),
                "dones": sample["dones"].reshape(n, batch_size, 1),
            },
            batch_axis=1,
        )

    prefetcher, rb_lock = maybe_prefetcher(cfg, _sample_block, enabled=ring is None)

    recorder = flight_recorder.get_active()

    def _dispatch_train(grad_steps: int, stage_next: bool) -> None:
        nonlocal params, opt_state, cumulative_grad_steps
        if ring is not None:
            # Fused device-ring block: ONE donated dispatch; even the index
            # sampling runs in-jit off the carried key.
            carry = fused.dispatch(
                {"params": params, "opt_state": opt_state},
                ring.arrays,
                len(rb),
                rb.rows_added,
                grad_steps,
                cumulative_grad_steps,
            )
            params, opt_state = carry["params"], carry["opt_state"]
            cumulative_grad_steps += grad_steps
            if recorder is not None:
                # The pre-step state was DONATED into the block; re-stage
                # post-dispatch with a device-side copy (async, no host sync).
                recorder.stage_step(
                    carry=jax.tree.map(jnp.copy, carry),
                    scalars={
                        "grad_step0": int(cumulative_grad_steps),
                        "filled": len(rb),
                        "rows_added": rb.rows_added,
                    },
                )
            return
        batches = (
            prefetcher.get(grad_steps, stage_next=stage_next)
            if prefetcher is not None
            else _sample_block(grad_steps)
        )
        key = ctx.rng()
        if recorder is not None:  # device-array references only: no host sync
            recorder.stage_step(
                batch=batches,
                carry={"params": params, "opt_state": opt_state},
                key=key,
                scalars={"grad_step0": int(cumulative_grad_steps)},
            )
        params, opt_state, train_metrics = train_fn(
            params, opt_state, batches, key, jnp.asarray(cumulative_grad_steps)
        )
        futures.track(train_metrics, grad_steps)
        cumulative_grad_steps += grad_steps

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = np.stack([act_space.sample() for _ in range(num_envs)])
                tanh_actions = 2 * (actions - act_low) / (act_high - act_low) - 1 if rescale else actions
            else:
                img = jnp.asarray(_img(obs) / 255.0)
                tanh_actions = np.asarray(jax.device_get(act_fn(params, img, ctx.local_rng())))
                actions = act_low + (tanh_actions + 1) * 0.5 * (act_high - act_low) if rescale else tanh_actions
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs so the
        # device trains while the host walks the environments; the first training
        # iteration (empty buffer — rows carry next_obs) defers until the row lands.
        grad_steps = 0
        deferred_dispatch = False
        if iter_num >= learning_starts:
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                if rb.empty:
                    deferred_dispatch = True
                else:
                    _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            next_obs, reward, terminated, truncated, info = envs.step(actions)
            done = np.logical_or(terminated, truncated)
            real_next = {k: np.asarray(next_obs[k]).copy() for k in cnn_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in cnn_keys:
                            real_next[k][i] = np.asarray(info["final_obs"][i][k])
            for k in cnn_keys:
                v = np.asarray(obs[k])
                step_data[k] = v.reshape(1, num_envs, -1, *v.shape[-2:])
                nv = real_next[k]
                step_data[f"next_{k}"] = nv.reshape(1, num_envs, -1, *nv.shape[-2:])
            step_data["actions"] = tanh_actions.astype(np.float32)[None]
            step_data["rewards"] = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)[None]
            step_data["dones"] = terminated.astype(np.float32).reshape(num_envs, 1)[None]
            if ring is not None:  # donated scatter at the host cursor, pre-add
                ring.add_step(
                    {
                        "obs": np.concatenate([step_data[k] for k in cnn_keys], axis=2),
                        "next_obs": np.concatenate([step_data[f"next_{k}"] for k in cnn_keys], axis=2),
                        "actions": step_data["actions"],
                        "rewards": step_data["rewards"],
                        "dones": step_data["dones"],
                    },
                    rb._pos,
                    rb.rows_added,
                )
            with rb_lock:
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if deferred_dispatch:
            _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            futures.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            window_sps = futures.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            metrics["Params/replay_ratio"] = cumulative_grad_steps * world / policy_step if policy_step else 0.0
            metrics.update(replay_age_metrics(rb))
            metrics.update(rollout_metrics(envs))
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            if cfg.buffer.checkpoint:
                state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(greedy_fn, params, ctx, cfg, log_dir, _img)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def test(greedy_fn, params, ctx, cfg, log_dir: str, img_fn) -> float:
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    obs, _ = env.reset(seed=cfg.seed)
    done, cum_reward = False, 0.0
    while not done:
        img = jnp.asarray(img_fn({k: np.asarray(v)[None] for k, v in obs.items()}) / 255.0)
        act = np.asarray(jax.device_get(greedy_fn(params, img)))[0]
        low, high = env.action_space.low, env.action_space.high
        if np.isfinite(low).all() and np.isfinite(high).all():
            act = low + (act + 1) * 0.5 * (high - low)
        obs, reward, terminated, truncated, _ = env.step(act)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    return cum_reward


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the scanned SAC-AE
    update (critic/actor/decoder cadences + EMA targets) at tiny synthetic pixel
    shapes, through ``make_sac_ae_train_fn``."""
    from sheeprl_tpu.analysis.ir.synth import box_act_space, compose_tiny, pixel_space, tiny_ctx, zeros
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=sac_ae",
            "env=continuous_dummy",
            "env.screen_size=32",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.encoder.features_dim=8",
            "algo.encoder.channels=4",
            "algo.actor.dense_units=8",
            "algo.critic.dense_units=8",
            "algo.per_rank_batch_size=2",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space = pixel_space(size=32)
    act_space = box_act_space()
    encoder, decoder, critic, actor, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, enc_opt, dec_opt, train_fn = make_sac_ae_train_fn(
        encoder, decoder, critic, actor, cfg, act_space
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init({"encoder": params["encoder"], "critic": params["critic"]}),
        "alpha": alpha_opt.init(params["log_alpha"]),
        "encoder": enc_opt.init(params["encoder"]),
        "decoder": dec_opt.init(params["decoder"]),
    }
    G, B = 2, 2
    batches = {
        "obs": zeros((G, B, 3, 32, 32), "uint8"),
        "next_obs": zeros((G, B, 3, 32, 32), "uint8"),
        "actions": zeros((G, B, 2)),
        "rewards": zeros((G, B, 1)),
        "dones": zeros((G, B, 1)),
    }
    return [
        AuditEntry(
            name="sac_ae/train_fn",
            fn=train_fn,
            args=(params, opt_state, batches, jax.random.PRNGKey(0), jnp.zeros((), jnp.int32)),
            covers=("sac_ae",),
            precision=str(cfg.mesh.precision),
        )
    ]
