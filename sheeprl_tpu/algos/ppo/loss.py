"""PPO losses as pure functions (reference: ``/root/reference/sheeprl/algos/ppo/loss.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def policy_loss(
    new_logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    clip_coef: jax.Array,
    reduction: str = "mean",
) -> jax.Array:
    """Clipped surrogate objective (reference ``loss.py:6-42``)."""
    ratio = jnp.exp(new_logprobs - old_logprobs)
    surr1 = advantages * ratio
    surr2 = advantages * jnp.clip(ratio, 1.0 - clip_coef, 1.0 + clip_coef)
    obj = jnp.minimum(surr1, surr2)
    return -(obj.mean() if reduction == "mean" else obj.sum())


def value_loss(
    new_values: jax.Array,
    old_values: jax.Array,
    returns: jax.Array,
    clip_coef: jax.Array,
    clip_vloss: bool,
    reduction: str = "mean",
) -> jax.Array:
    """MSE value loss, optionally clipped around the old values (reference ``:46-63``)."""
    if not clip_vloss:
        err = (new_values - returns) ** 2
        return err.mean() if reduction == "mean" else err.sum()
    clipped = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    err = jnp.maximum((new_values - returns) ** 2, (clipped - returns) ** 2)
    return 0.5 * (err.mean() if reduction == "mean" else err.sum())


def entropy_loss(entropy: jax.Array, reduction: str = "mean") -> jax.Array:
    """Negative mean entropy (reference ``:66-75``)."""
    return -(entropy.mean() if reduction == "mean" else entropy.sum())
