"""PPO training loop (reference: ``/root/reference/sheeprl/algos/ppo/ppo.py:105-…``).

TPU-first structure:

* rollout: host loop over the vectorized envs; actions sampled by one jitted policy call
  per step (HOST→DEVICE obs copy at the boundary, like the reference's ``prepare_obs``);
* GAE: computed on device as a reverse ``lax.scan`` over the whole rollout;
* update: the ENTIRE optimisation (``update_epochs`` × minibatch sweep with fresh
  per-epoch permutations) is ONE jitted call built from nested ``lax.scan`` —
  vs the reference's python-loop-per-minibatch with a DDP all-reduce per backward
  (``ppo.py:40-50`` + Fabric).  Gradient sync over the ``data`` mesh axis is inserted by
  GSPMD: the batch is sharded, params replicated, loss is a global mean.
* annealing (lr / clip / entropy coefficients) stays on host and enters the jitted step
  as traced scalars (no recompilation), mirroring ``polynomial_decay`` semantics.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import (
    assert_finite,
    maybe_inject_nonfinite,
    nan_scan,
    strict_enabled,
    strict_guard,
)
from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import (
    AGGREGATOR_KEYS,
    log_prob_and_entropy,
    prepare_obs,
    sample_actions,
    test,
)
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled
from sheeprl_tpu.precision import train_policy
from sheeprl_tpu.rollout import PipelinedPlayer, rollout_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import gae, polynomial_decay


def make_optimizer(
    opt_cfg: Dict[str, Any], max_grad_norm: float, lr_schedule=None, inject_lr: bool = False
) -> optax.GradientTransformation:
    """``inject_lr=True`` builds the same optimizer through
    ``optax.inject_hyperparams`` so the learning rate lives in the OPTIMIZER
    STATE instead of the update closure — the population engine's
    vmapped-by-hyperparameter init (``engine/population.py``) then stamps a
    per-member rate into each member's state while every member runs the
    identical update program.  Incompatible with a schedule (a swept rate is a
    per-member constant)."""
    lr = lr_schedule if lr_schedule is not None else opt_cfg.get("lr", 1e-3)
    if inject_lr and lr_schedule is not None:
        raise ValueError("inject_lr (population lr sweep) and a lr schedule are mutually exclusive")
    name = opt_cfg.get("name", "adam")
    if name == "adam":
        wd = opt_cfg.get("weight_decay", 0.0)

        def base(learning_rate):
            o = optax.adam(learning_rate, eps=opt_cfg.get("eps", 1e-8), b1=opt_cfg.get("betas", [0.9, 0.999])[0])
            if wd:
                # torch.optim.Adam weight_decay is L2-into-gradient, i.e. the decay
                # is added BEFORE the Adam scaling (unlike decoupled AdamW).
                o = optax.chain(optax.add_decayed_weights(wd), o)
            return o

    elif name == "adamw":

        def base(learning_rate):
            return optax.adamw(
                learning_rate, eps=opt_cfg.get("eps", 1e-8), weight_decay=opt_cfg.get("weight_decay", 0.0)
            )

    elif name == "sgd":

        def base(learning_rate):
            return optax.sgd(learning_rate, momentum=opt_cfg.get("momentum", 0.0))

    elif name == "rmsprop_tf":
        # TF-style RMSProp: eps inside the sqrt (reference optim/rmsprop_tf.py:14-156).
        # optax moved the eps placement behind an ``eps_in_sqrt`` kwarg whose default
        # is deprecating (>=0.2.4); pin the TF behavior explicitly where the kwarg
        # exists, and fall back cleanly on older optax whose rmsprop ALWAYS put the
        # eps inside the sqrt — both paths compute the same update.
        import inspect

        rmsprop_kwargs = dict(
            decay=opt_cfg.get("alpha", 0.99), eps=opt_cfg.get("eps", 1e-8),
            centered=opt_cfg.get("centered", False), momentum=opt_cfg.get("momentum", 0.0),
        )
        if "eps_in_sqrt" in inspect.signature(optax.rmsprop).parameters:
            rmsprop_kwargs["eps_in_sqrt"] = True

        def base(learning_rate):
            return optax.rmsprop(learning_rate, **rmsprop_kwargs)

    else:
        raise ValueError(f"Unknown optimizer: {name}")
    opt = optax.inject_hyperparams(base)(learning_rate=lr) if inject_lr else base(lr)
    if max_grad_norm and max_grad_norm > 0:
        return optax.chain(optax.clip_by_global_norm(max_grad_norm), opt)
    return opt


class PPOTrainFns:
    """Jitted PPO functions shared by the coupled and decoupled entry points."""

    def __init__(self, ctx, agent, cfg, obs_keys, num_updates, inject_lr: bool = False):
        if cfg.algo.per_rank_batch_size <= 0:
            raise ValueError("algo.per_rank_batch_size must be positive")
        num_envs = cfg.env.num_envs
        rollout_steps = cfg.algo.rollout_steps
        batch_n = rollout_steps * num_envs
        if batch_n % cfg.algo.per_rank_batch_size != 0:
            raise ValueError(
                f"algo.rollout_steps*env.num_envs ({batch_n}) must be divisible by "
                f"algo.per_rank_batch_size ({cfg.algo.per_rank_batch_size}): static shapes "
                "inside the jitted update require equal minibatches."
            )
        self.batch_n = batch_n
        self.num_minibatches = batch_n // cfg.algo.per_rank_batch_size
        self.grad_steps_per_update = cfg.algo.update_epochs * self.num_minibatches
        self.lr_schedule = None
        if cfg.algo.anneal_lr:
            if inject_lr:
                raise ValueError(
                    "algo.anneal_lr=True cannot combine with a population learning-rate "
                    "sweep (the swept rate is a per-member constant in the optimizer state)"
                )
            self.lr_schedule = optax.polynomial_schedule(
                init_value=cfg.algo.optimizer.lr,
                end_value=1e-8,
                power=1.0,
                transition_steps=num_updates * self.grad_steps_per_update,
            )
        self.opt = make_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm, self.lr_schedule, inject_lr=inject_lr)

        is_continuous = agent.is_continuous
        batch_sharding = ctx.batch_sharding()
        gamma, gae_lambda = cfg.algo.gamma, cfg.algo.gae_lambda
        loss_reduction = cfg.algo.loss_reduction
        mb_size = cfg.algo.per_rank_batch_size
        num_minibatches = self.num_minibatches
        opt = self.opt
        strict = strict_enabled(cfg)
        health = health_enabled(cfg)  # trace-time constant (obs/health.py)
        # Precision boundary (howto/precision.md): float observation batches are
        # cast to the policy's compute dtype BEFORE the first matmul, so under
        # bf16 the whole forward runs low-precision; heads cast back to f32.
        precision = train_policy(cfg, ctx)

        def cast_obs(obs):
            return precision.cast_to_compute(obs)

        @jax.jit
        def act_fn(p, obs, key):
            actor_out, value = agent.apply(p, cast_obs(obs))
            env_act, stored_act, logprob = sample_actions(key, actor_out, is_continuous)
            return env_act, stored_act, logprob, value[..., 0]

        @jax.jit
        def values_fn(p, obs):
            _, value = agent.apply(p, cast_obs(obs))
            return value[..., 0]

        def loss_fn(p, mb, clip_coef, ent_coef):
            actor_out, new_values = agent.apply(p, cast_obs({k: mb[k] for k in obs_keys}))
            new_logprob, entropy = log_prob_and_entropy(actor_out, mb["actions"], is_continuous)
            adv = mb["advantages"]
            if cfg.algo.normalize_advantages:
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg = policy_loss(new_logprob, mb["logprobs"], adv, clip_coef, loss_reduction)
            vf = value_loss(
                new_values[..., 0], mb["values"], mb["returns"], clip_coef, cfg.algo.clip_vloss, loss_reduction
            )
            ent = entropy_loss(entropy, loss_reduction)
            total = pg + cfg.algo.vf_coef * vf + ent_coef * ent
            aux = {"Loss/policy_loss": pg, "Loss/value_loss": vf, "Loss/entropy_loss": -ent}
            if health:
                aux["Health/policy_entropy"] = entropy.mean()
                aux["Health/value_mean"] = new_values.mean()
                aux["Health/value_std"] = new_values.std()
            return total, aux

        @jax.jit
        def train_fn(p, o_state, data, key, clip_coef, ent_coef):
            n = data["actions"].shape[0]

            def mb_step(carry, idx):
                p, o_state = carry
                mb = jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x[idx], batch_sharding), data)
                (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, mb, clip_coef, ent_coef)
                updates, o_state = opt.update(grads, o_state, p)
                p = optax.apply_updates(p, updates)
                if health:  # per-module norms/ratios, averaged by the scans below
                    aux = {**aux, **diagnostics(grads=grads, params=p, updates=updates)}
                return (p, o_state), aux

            def epoch_step(carry, ekey):
                perm = jax.random.permutation(ekey, n)
                idxs = perm.reshape(num_minibatches, mb_size)
                carry, auxs = jax.lax.scan(mb_step, carry, idxs)
                return carry, jax.tree.map(jnp.mean, auxs)

            keys = jax.random.split(key, cfg.algo.update_epochs)
            (p, o_state), metrics = jax.lax.scan(epoch_step, (p, o_state), keys)
            metrics = jax.tree.map(jnp.mean, metrics)
            metrics = maybe_inject_nonfinite(cfg, metrics)
            if strict:  # trace-time constant: the callback only exists in strict runs
                nan_scan(metrics, "ppo/train_fn")
            return p, o_state, metrics

        self.act_fn = act_fn
        self.values_fn = values_fn
        self.train_fn = train_fn
        self.gae_fn = jax.jit(
            lambda rew, vals, dones, next_v: gae(rew, vals, dones, next_v, rollout_steps, gamma, gae_lambda)
        )


@register_algorithm(name="ppo")
def main(ctx, cfg) -> None:
    if cfg.algo.anakin:
        # Anakin mode (howto/anakin.md): on-device jax envs, acting and the SAME
        # jitted update fused into one donated scan — the engine owns the loop.
        from sheeprl_tpu.engine.anakin import ppo_anakin

        return ppo_anakin(ctx, cfg)
    rank = ctx.process_index
    if cfg.algo.per_rank_batch_size <= 0:
        raise ValueError("algo.per_rank_batch_size must be positive")

    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    is_continuous = agent.is_continuous

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    world = jax.process_count()
    policy_steps_per_iter = int(num_envs * rollout_steps * world)
    total_steps = int(cfg.algo.total_steps)
    num_updates = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    fns = PPOTrainFns(ctx, agent, cfg, obs_keys, num_updates)
    batch_n = fns.batch_n
    grad_steps_per_update = fns.grad_steps_per_update
    lr_schedule = fns.lr_schedule
    opt_state = ctx.replicate(fns.opt.init(params))

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    act_fn, values_fn, train_fn, gae_fn = fns.act_fn, fns.values_fn, fns.train_fn, fns.gae_fn
    # analysis.strict: signature guard on the jitted update (drift -> hard error)
    train_fn = obs_perf.instrument(cfg, "ppo/train_fn", strict_guard(cfg, "ppo/train_fn", train_fn))
    gamma = cfg.algo.gamma

    # Flight recorder (obs/flight_recorder.py): the replay builder rebuilds this
    # exact update from the dumped config + these statics.
    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.ppo.ppo:replay_update",
            act_space=act_space,
            obs_space=obs_space,
            num_updates=num_updates,
        )

    # ------------------------------------------------------------------ resume
    start_update = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from, templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)}
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        start_update = state["update"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)

    # ------------------------------------------------------------------ loop
    obs, _ = envs.reset(seed=cfg.seed + rank)
    step_data: Dict[str, np.ndarray] = {}
    start_time = time.perf_counter()

    # Acting pipeline (sheeprl_tpu/rollout).  depth 0 reproduces the historical
    # synchronous path exactly; depth>=1 overlaps the policy jit with the env
    # workers at the cost of a policy lag — note PPO's loss then trains on
    # slightly stale log-probs/values (see howto/async_rollout.md).
    def _pipeline_policy(cur_obs):
        obs_t = prepare_obs(cur_obs, cnn_keys, mlp_keys)
        return act_fn(params, obs_t, ctx.local_rng())

    def _pipeline_post(fetched):
        env_act_np, _, logprob_np, value_np = (np.asarray(x) for x in fetched)
        if is_continuous:
            low, high = act_space.low, act_space.high
            env_actions = np.clip(env_act_np, low, high) if np.isfinite(low).all() else env_act_np
        elif len(agent.action_dims) == 1:
            env_actions = env_act_np[..., 0]
        else:
            env_actions = env_act_np
        return env_actions, (env_act_np, logprob_np, value_np)

    rollout_player = PipelinedPlayer(
        envs, _pipeline_policy, _pipeline_post, depth=int((cfg.get("rollout") or {}).get("pipeline_depth", 0))
    )

    for update in range(start_update, num_updates + 1):
        monitor.advance()
        train_time = 0.0
        env_time_start = time.perf_counter()
        with timer("Time/env_interaction_time"):
            for _ in range(rollout_steps):
                with monitor.phase("player"):
                    env_actions, (env_act_np, logprob_np, value_np) = rollout_player.act(obs)
                with monitor.phase("env_step"):
                    next_obs, reward, terminated, truncated, info = rollout_player.env_step(env_actions)
                if cfg.env.clip_rewards:
                    reward = np.clip(reward, -1, 1)
                done = np.logical_or(terminated, truncated)
                reward = np.asarray(reward, dtype=np.float32).reshape(num_envs)

                # Bootstrap truncated episodes: V(final_obs) folds into the reward
                # before storage (reference ``ppo.py:287-306``).
                if truncated.any() and "final_obs" in info:
                    trunc_idx = np.nonzero(truncated)[0]
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][i][k]) for i in trunc_idx])
                        for k in obs_keys
                    }
                    v_final = np.asarray(
                        jax.device_get(values_fn(params, prepare_obs(final_obs, cnn_keys, mlp_keys)))
                    )
                    reward[trunc_idx] += gamma * v_final

                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[None]
                step_data["actions"] = env_act_np.reshape(num_envs, -1).astype(np.float32)[None]
                step_data["logprobs"] = logprob_np.reshape(num_envs, 1)[None]
                step_data["values"] = value_np.reshape(num_envs, 1)[None]
                step_data["rewards"] = reward.reshape(num_envs, 1)[None]
                step_data["dones"] = done.astype(np.float32).reshape(num_envs, 1)[None]
                with monitor.phase("buffer_add"):
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)

                obs = next_obs
                policy_step += num_envs * world

                record_episode_stats(aggregator, info)
        env_time = time.perf_counter() - env_time_start

        # Bootstrap + GAE on device.
        local = rb.to_tensor()
        next_value = values_fn(params, prepare_obs(obs, cnn_keys, mlp_keys))[:, None]
        returns, advantages = gae_fn(local["rewards"], local["values"], local["dones"], next_value)
        data = {
            **{k: local[k] for k in obs_keys},
            "actions": local["actions"],
            "logprobs": local["logprobs"][..., 0],
            "values": local["values"][..., 0],
            "returns": returns[..., 0],
            "advantages": advantages[..., 0],
        }
        data = jax.tree.map(lambda x: x.reshape(batch_n, *x.shape[2:]), data)

        # Annealed coefficients (host-side; traced scalars on device).
        clip_coef = cfg.algo.clip_coef
        ent_coef = cfg.algo.ent_coef
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(update, initial=clip_coef, final=0.0, max_decay_steps=num_updates)
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(update, initial=ent_coef, final=0.0, max_decay_steps=num_updates)

        # Stage this update's exact inputs on the flight recorder: device-array
        # references only (no sync, no copy) — fetched solely if the run crashes.
        key = ctx.rng()
        if recorder is not None:
            recorder.stage_step(
                batch=data,
                carry={"params": params, "opt_state": opt_state},
                key=key,
                scalars={"clip_coef": float(clip_coef), "ent_coef": float(ent_coef), "update": update},
            )
        with timer("Time/train_time"), monitor.phase("dispatch"):
            t0 = time.perf_counter()
            params, opt_state, train_metrics = train_fn(params, opt_state, data, key, clip_coef, ent_coef)
            train_metrics = jax.device_get(train_metrics)
            train_time = time.perf_counter() - t0
        assert_finite(cfg, train_metrics, "ppo/update")
        for k, v in train_metrics.items():
            aggregator.update(k, float(v))

        # Logging cadence (reference ``ppo.py`` metric flush per log_every).
        if logger is not None and (policy_step - last_log >= cfg.metric.log_every or update == num_updates or cfg.dry_run):
            metrics = aggregator.compute()
            metrics["Time/sps_train"] = grad_steps_per_update / train_time if train_time > 0 else 0.0
            metrics["Time/sps_env_interaction"] = (
                policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            )
            grad_step_count = update * grad_steps_per_update
            metrics["Params/lr"] = (
                float(lr_schedule(grad_step_count)) if lr_schedule is not None else float(cfg.algo.optimizer.lr)
            )
            metrics.update(rollout_metrics(envs))
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            with monitor.phase("checkpoint"):
                path = ckpt_manager.save(
                    policy_step,
                    {
                        "params": params,
                        "opt_state": opt_state,
                        "update": update,
                        "policy_step": policy_step,
                        "last_log": last_log,
                        "last_checkpoint": policy_step,
                    },
                )
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or update == num_updates
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(agent, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if not cfg.get("model_manager", {}).get("disabled", True) and ctx.is_global_zero:
        from sheeprl_tpu.utils.model_manager import maybe_register_models

        maybe_register_models(cfg, log_dir)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): AOT-lower the shared
    ``PPOTrainFns.train_fn`` — the jitted update of BOTH the coupled and decoupled
    entry points — at tiny synthetic shapes, through the exact builder the
    training loops use."""
    from sheeprl_tpu.analysis.ir.synth import (
        compose_tiny,
        discrete_act_space,
        tiny_ctx,
        vector_space,
        zeros,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=ppo",
            "env=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    act_space = discrete_act_space()
    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    fns = PPOTrainFns(ctx, agent, cfg, ["state"], num_updates=4)
    opt_state = fns.opt.init(params)
    n = int(cfg.algo.rollout_steps * cfg.env.num_envs)
    data = {
        "state": zeros((n, 5)),
        "actions": zeros((n, 1)),
        "logprobs": zeros((n,)),
        "values": zeros((n,)),
        "returns": zeros((n,)),
        "advantages": zeros((n,)),
    }
    return [
        AuditEntry(
            name="ppo/train_fn",
            fn=fns.train_fn,
            args=(params, opt_state, data, jax.random.PRNGKey(0), 0.2, 0.0),
            covers=("ppo", "ppo_decoupled"),
            precision=str(cfg.mesh.precision),
        )
    ]


def replay_update(cfg, dump_dir):
    """Flight-recorder replay builder (``python -m sheeprl_tpu.obs.replay_blackbox``):
    rebuild the PPO jitted update from a blackbox dump's config + statics, restore
    the dumped params/optimizer state/batch, and re-execute the single failing
    update step.  Shared by the coupled and decoupled entry points (same
    ``PPOTrainFns.train_fn``).  Returns the update's host-fetched outputs."""
    from sheeprl_tpu.obs import replay_blackbox
    from sheeprl_tpu.parallel.mesh import make_mesh_context

    ctx = make_mesh_context(cfg)
    raw = replay_blackbox.load_state(dump_dir)
    statics = raw["statics"]
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    agent, params0 = build_agent(ctx, statics["act_space"], statics["obs_space"], cfg)
    fns = PPOTrainFns(ctx, agent, cfg, obs_keys, statics["num_updates"])
    templates = {"carry": jax.device_get({"params": params0, "opt_state": fns.opt.init(params0)})}
    state = replay_blackbox.load_state(dump_dir, templates)
    carry, scalars = state["carry"], state["scalars"]
    new_params, _, metrics = fns.train_fn(
        ctx.replicate(carry["params"]),
        ctx.replicate(carry["opt_state"]),
        state["batch"],
        jnp.asarray(state["key"]),
        scalars["clip_coef"],
        scalars["ent_coef"],
    )
    return {
        "metrics": jax.device_get(metrics),
        "new_param_norm": float(jax.device_get(optax.global_norm(new_params))),
    }
