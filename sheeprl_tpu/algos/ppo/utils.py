"""PPO helpers (reference: ``/root/reference/sheeprl/algos/ppo/utils.py``)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.distributions import Categorical, Normal
from sheeprl_tpu.obs.tracer import trace_span

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


@trace_span("Time/h2d_transfer")
def prepare_obs(obs: Dict[str, np.ndarray], cnn_keys: Sequence[str], mlp_keys: Sequence[str]) -> Dict[str, jax.Array]:
    """numpy env observations → device arrays (uint8 images stay uint8; the encoder
    normalises on device, reference ``utils.py:…prepare_obs``)."""
    out: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        out[k] = jnp.asarray(obs[k])
    for k in mlp_keys:
        out[k] = jnp.asarray(obs[k], dtype=jnp.float32)
    return out


def actions_as_dist(actor_out: Sequence[jax.Array], is_continuous: bool):
    if is_continuous:
        mean, log_std = jnp.split(actor_out[0], 2, axis=-1)
        return Normal(mean, jnp.exp(log_std))
    return [Categorical(logits) for logits in actor_out]


def sample_actions(key: jax.Array, actor_out: Sequence[jax.Array], is_continuous: bool, greedy: bool = False):
    """Returns (env_actions, stored_actions, logprob)."""
    if is_continuous:
        dist = actions_as_dist(actor_out, True)
        act = dist.mode if greedy else dist.sample(key)
        logprob = dist.log_prob(act).sum(-1)
        return act, act, logprob
    dists = actions_as_dist(actor_out, False)
    keys = jax.random.split(key, len(dists))
    acts = [d.mode if greedy else d.sample(k) for d, k in zip(dists, keys)]
    logprob = sum(d.log_prob(a) for d, a in zip(dists, acts))
    stacked = jnp.stack(acts, axis=-1)
    return stacked, stacked, logprob


def log_prob_and_entropy(actor_out: Sequence[jax.Array], actions: jax.Array, is_continuous: bool):
    if is_continuous:
        dist = actions_as_dist(actor_out, True)
        return dist.log_prob(actions).sum(-1), dist.entropy().sum(-1)
    dists = actions_as_dist(actor_out, False)
    logprob = sum(d.log_prob(actions[..., i]) for i, d in enumerate(dists))
    entropy = sum(d.entropy() for d in dists)
    return logprob, entropy


def test(agent, params, ctx, cfg, log_dir: str, greedy: bool = True) -> float:
    """Greedy single-env evaluation episode (reference ``utils.py:test``)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    @jax.jit
    def policy(p, obs, key):
        actor_out, _ = agent.apply(p, obs)
        env_act, _, _ = sample_actions(key, actor_out, agent.is_continuous, greedy=greedy)
        return env_act

    obs, _ = env.reset(seed=cfg.seed)
    done = False
    cum_reward = 0.0
    while not done:
        obs_t = prepare_obs({k: np.asarray(v)[None] for k, v in obs.items()}, cnn_keys, mlp_keys)
        act = np.asarray(jax.device_get(policy(params, obs_t, ctx.rng())))[0]
        if not agent.is_continuous and len(agent.action_dims) == 1:
            act = act.item()
        obs, reward, terminated, truncated, _ = env.step(act)
        done = bool(terminated or truncated)
        cum_reward += float(reward)
    env.close()
    return cum_reward
