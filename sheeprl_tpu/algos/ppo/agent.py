"""PPO agent (reference: ``/root/reference/sheeprl/algos/ppo/agent.py:91-369``).

TPU-native design: one flax module holding the shared ``MultiEncoder`` plus actor/critic
MLP heads; there is no separate ``PPOPlayer`` — acting and training use the same pure
``apply`` with the same replicated params (the reference ties weights between a
DDP-wrapped trainer module and a single-device player, ``agent.py:363-368``; with pjit
that duplication disappears)."""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.blocks import MLP, MultiEncoder
from sheeprl_tpu.precision import train_policy


def parse_action_space(action_space: gymnasium.spaces.Space) -> Tuple[bool, Tuple[int, ...]]:
    """Return (is_continuous, dims). For discrete spaces dims are per-component
    cardinalities; for Box it is the action dimensionality."""
    if isinstance(action_space, gymnasium.spaces.Box):
        return True, (int(np.prod(action_space.shape)),)
    if isinstance(action_space, gymnasium.spaces.Discrete):
        return False, (int(action_space.n),)
    if isinstance(action_space, gymnasium.spaces.MultiDiscrete):
        return False, tuple(int(n) for n in action_space.nvec)
    raise ValueError(f"Unsupported action space: {type(action_space)}")


class PPOAgent(nn.Module):
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    action_dims: Sequence[int]
    is_continuous: bool
    cnn_stacked: bool = False
    screen_size: int = 64
    cnn_features_dim: int = 512
    mlp_features_dim: int = 64
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "tanh"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> Tuple[List[jax.Array], jax.Array]:
        feat = MultiEncoder(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_stacked=self.cnn_stacked,
            cnn_features_dim=self.cnn_features_dim,
            mlp_hidden_sizes=(self.dense_units,) * self.mlp_layers,
            mlp_features_dim=self.mlp_features_dim,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="feature_extractor",
        )(obs)
        pre_actor = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="actor_backbone",
        )(feat)
        critic = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=1,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="critic",
        )(feat)
        if self.is_continuous:
            # A single head emitting [mean, log_std] (reference agent.py:157-162).
            out = nn.Dense(2 * self.action_dims[0], dtype=self.dtype, name="actor_head")(pre_actor)
            actor_out = [out.astype(jnp.float32)]
        else:
            actor_out = [
                nn.Dense(d, dtype=self.dtype, name=f"actor_head_{i}")(pre_actor).astype(jnp.float32)
                for i, d in enumerate(self.action_dims)
            ]
        return actor_out, critic.astype(jnp.float32)


def build_agent(
    ctx,
    action_space: gymnasium.spaces.Space,
    obs_space: gymnasium.spaces.Dict,
    cfg: Dict[str, Any],
) -> Tuple[PPOAgent, Any]:
    """Construct the module and initialise replicated params on the mesh."""
    is_continuous, dims = parse_action_space(action_space)
    agent = PPOAgent(
        cnn_keys=list(cfg.algo.cnn_keys.encoder),
        mlp_keys=list(cfg.algo.mlp_keys.encoder),
        action_dims=dims,
        is_continuous=is_continuous,
        cnn_stacked=any(len(obs_space[k].shape) == 4 for k in cfg.algo.cnn_keys.encoder),
        screen_size=cfg.env.screen_size,
        cnn_features_dim=cfg.algo.encoder.cnn_features_dim,
        mlp_features_dim=cfg.algo.encoder.mlp_features_dim,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        dense_act=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        # algo.precision resolves the compute dtype ("mesh" inherits
        # ctx.compute_dtype); flax param_dtype stays f32 so params/optimizer
        # state are full precision under every mixed policy (howto/precision.md).
        dtype=train_policy(cfg, ctx).compute_dtype,
    )
    dummy_obs = {}
    for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder):
        space = obs_space[k]
        dummy_obs[k] = jnp.zeros((1, *space.shape), dtype=space.dtype)
    params = agent.init(ctx.rng(), dummy_obs)
    params = ctx.replicate(params)
    return agent, params
