"""Decoupled PPO — player/learner split (reference: ``/root/reference/sheeprl/algos/ppo/ppo_decoupled.py``).

The reference decouples by spawning one *process* per role and moving data with torch
collectives: rank-0 player scatters rollout shards to N DDP trainer ranks and receives
flattened parameters back over NCCL/Gloo (``ppo_decoupled.py:294-305, 645-666``).

**TPU-native redesign** (SURVEY §7 explicitly flags "don't mimic the torch
collectives"): JAX is single-controller — ONE process already drives every local device.
The roles become *threads* sharing the process:

* the **player** thread owns the envs and a jitted single-device policy, collects a
  rollout, computes GAE, and hands the finished batch to the learner over a bounded
  queue (the host-side analogue of the reference's ``scatter_object_list``);
* the **learner** (main thread) runs the jitted data-parallel update over the mesh —
  GSPMD shards the batch over the ``data`` axis and inserts the gradient reductions —
  then *publishes* the fresh params back through a second queue (the analogue of the
  flattened-parameter broadcast, ``ppo_decoupled.py:302-305``, at zero copy cost:
  device buffers are immutable, publication is a reference hand-off).
* termination mirrors the reference's sentinel (``:344,463``): the player propagates
  exceptions through the data queue, and a stop event prevents either side from
  blocking forever if its peer dies.

The env never waits on the optimizer's dispatch (rollout t+1 overlaps update t's
device execution), which is the whole point of the decoupled mode.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict

import jax
import numpy as np

from sheeprl_tpu.analysis.strict import assert_finite, strict_guard
from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.distributed.placement import placement_from_cfg
from sheeprl_tpu.distributed.publish import evict_and_put, make_stamp, staleness_steps
from sheeprl_tpu.distributed.transport import maybe_digest
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay


@register_algorithm(name="ppo_decoupled", decoupled=True)
def main(ctx, cfg) -> None:
    # Sebulba (distributed.mode=sebulba): the player/learner threads below become
    # placed processes — children land in sebulba.run, the launcher role places
    # them (howto/sebulba.md).
    spec = placement_from_cfg(cfg)
    if spec.is_sebulba:
        if spec.role == "launcher":
            from sheeprl_tpu.distributed import launcher

            raise SystemExit(launcher.launch(sys.argv[1:]))
        from sheeprl_tpu.distributed import sebulba

        return sebulba.run(ctx, cfg, spec, algo="ppo")

    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    is_continuous = agent.is_continuous

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    world = jax.process_count()
    policy_steps_per_iter = int(num_envs * rollout_steps * world)
    total_steps = int(cfg.algo.total_steps)
    num_updates = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    fns = PPOTrainFns(ctx, agent, cfg, obs_keys, num_updates)
    batch_n = fns.batch_n
    grad_steps_per_update = fns.grad_steps_per_update
    opt_state = ctx.replicate(fns.opt.init(params))
    act_fn, values_fn, train_fn, gae_fn = fns.act_fn, fns.values_fn, fns.train_fn, fns.gae_fn
    train_fn = obs_perf.instrument(cfg, "ppo_decoupled/train_fn", strict_guard(cfg, "ppo_decoupled/train_fn", train_fn))
    gamma = cfg.algo.gamma

    # Flight recorder: the coupled entry point's replay builder rebuilds this same
    # PPOTrainFns.train_fn, so decoupled dumps replay through it too.
    from sheeprl_tpu.obs import flight_recorder

    recorder = flight_recorder.get_active()
    if recorder is not None:
        recorder.arm_replay(
            "sheeprl_tpu.algos.ppo.ppo:replay_update",
            act_space=act_space,
            obs_space=obs_space,
            num_updates=num_updates,
        )

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    # The aggregator is written by the player (episode stats) and read/reset by the
    # learner (logging flush) — one lock covers both sides.
    agg_lock = threading.Lock()
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    # ------------------------------------------------------------------ resume
    start_update = 1
    policy_step0 = 0
    last_log = 0
    last_checkpoint = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        start_update = state["update"] + 1
        policy_step0 = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)

    # ------------------------------------------------------------------ roles
    rollout_q: "queue.Queue[Any]" = queue.Queue(maxsize=2)
    param_q: "queue.Queue[Any]" = queue.Queue(maxsize=2)
    stop = threading.Event()

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)

    def player() -> None:
        """Env-facing role (reference ``player()``, ``ppo_decoupled.py:32-365``)."""
        # Own PRNG chain: ctx.rng() is not thread-safe and belongs to the learner.
        key = jax.random.PRNGKey(cfg.seed + 10_000 + rank)
        local_params = params
        param_stamp: Dict[str, Any] = {}
        policy_step = policy_step0
        try:
            obs, _ = envs.reset(seed=cfg.seed + rank)
            step_data: Dict[str, np.ndarray] = {}
            for update in range(start_update, num_updates + 1):
                env_t0 = time.perf_counter()
                with timer("Time/env_interaction_time"):
                    for _ in range(rollout_steps):
                        if stop.is_set():
                            return
                        key, sub = jax.random.split(key)
                        obs_t = prepare_obs(obs, cnn_keys, mlp_keys)
                        env_act, stored_act, logprob, value = act_fn(local_params, obs_t, sub)
                        env_act_np = np.asarray(jax.device_get(env_act))
                        if is_continuous:
                            low, high = act_space.low, act_space.high
                            env_actions = np.clip(env_act_np, low, high) if np.isfinite(low).all() else env_act_np
                        elif len(agent.action_dims) == 1:
                            env_actions = env_act_np[..., 0]
                        else:
                            env_actions = env_act_np
                        next_obs, reward, terminated, truncated, info = envs.step(env_actions)
                        if cfg.env.clip_rewards:
                            reward = np.clip(reward, -1, 1)
                        done = np.logical_or(terminated, truncated)
                        reward = np.asarray(reward, dtype=np.float32).reshape(num_envs)

                        if truncated.any() and "final_obs" in info:
                            trunc_idx = np.nonzero(truncated)[0]
                            final_obs = {
                                k: np.stack([np.asarray(info["final_obs"][i][k]) for i in trunc_idx])
                                for k in obs_keys
                            }
                            v_final = np.asarray(
                                jax.device_get(values_fn(local_params, prepare_obs(final_obs, cnn_keys, mlp_keys)))
                            )
                            reward[trunc_idx] += gamma * v_final

                        for k in obs_keys:
                            step_data[k] = np.asarray(obs[k])[None]
                        step_data["actions"] = env_act_np.reshape(num_envs, -1).astype(np.float32)[None]
                        step_data["logprobs"] = np.asarray(jax.device_get(logprob)).reshape(num_envs, 1)[None]
                        step_data["values"] = np.asarray(jax.device_get(value)).reshape(num_envs, 1)[None]
                        step_data["rewards"] = reward.reshape(num_envs, 1)[None]
                        step_data["dones"] = done.astype(np.float32).reshape(num_envs, 1)[None]
                        rb.add(step_data, validate_args=cfg.buffer.validate_args)

                        obs = next_obs
                        policy_step += num_envs * world
                        with agg_lock:
                            record_episode_stats(aggregator, info)
                env_time = time.perf_counter() - env_t0

                local = rb.to_tensor()
                next_value = values_fn(local_params, prepare_obs(obs, cnn_keys, mlp_keys))[:, None]
                returns, advantages = gae_fn(local["rewards"], local["values"], local["dones"], next_value)
                data = {
                    **{k: local[k] for k in obs_keys},
                    "actions": local["actions"],
                    "logprobs": local["logprobs"][..., 0],
                    "values": local["values"][..., 0],
                    "returns": returns[..., 0],
                    "advantages": advantages[..., 0],
                }
                data = jax.tree.map(lambda x: x.reshape(batch_n, *x.shape[2:]), data)
                item = {
                    "update": update,
                    "data": data,
                    "policy_step": policy_step,
                    "env_time": env_time,
                    # Policy-step age of the params this rollout acted with —
                    # the learner logs it as Sebulba/param_staleness_steps.
                    "staleness": staleness_steps(param_stamp, policy_step),
                }
                while not stop.is_set():
                    try:
                        rollout_q.put(item, timeout=1.0)
                        break
                    except queue.Full:
                        continue

                # Wait for the learner's parameter publication (reference :302-305).
                while not stop.is_set():
                    try:
                        local_params, param_stamp = param_q.get(timeout=1.0)
                        break
                    except queue.Empty:
                        continue
        except Exception as exc:  # propagate into the learner
            rollout_q.put(exc)

    player_thread = threading.Thread(target=player, name="ppo-player", daemon=True)
    player_thread.start()

    # ------------------------------------------------------------------ learner
    policy_step = policy_step0
    try:
        for update in range(start_update, num_updates + 1):
            monitor.advance()
            item = rollout_q.get()
            if isinstance(item, Exception):
                raise item
            data = item["data"]
            policy_step = item["policy_step"]
            env_time = item["env_time"]
            maybe_digest(f"ppo:{item['update']}", data)
            if item.get("staleness") is not None:
                with agg_lock:
                    aggregator.update("Sebulba/param_staleness_steps", float(item["staleness"]))

            clip_coef = cfg.algo.clip_coef
            ent_coef = cfg.algo.ent_coef
            if cfg.algo.anneal_clip_coef:
                clip_coef = polynomial_decay(update, initial=clip_coef, final=0.0, max_decay_steps=num_updates)
            if cfg.algo.anneal_ent_coef:
                ent_coef = polynomial_decay(update, initial=ent_coef, final=0.0, max_decay_steps=num_updates)

            key = ctx.rng()
            if recorder is not None:  # device-array references only: no host sync
                recorder.stage_step(
                    batch=data,
                    carry={"params": params, "opt_state": opt_state},
                    key=key,
                    scalars={"clip_coef": float(clip_coef), "ent_coef": float(ent_coef), "update": update},
                )
            with timer("Time/train_time"), monitor.phase("dispatch"):
                t0 = time.perf_counter()
                params, opt_state, train_metrics = train_fn(params, opt_state, data, key, clip_coef, ent_coef)
                # Publish the (asynchronously dispatched) params immediately — the
                # player's next rollout overlaps this update's device execution.
                # Freshest-wins + stamped (seq/grad_step/policy_step) so pickup
                # staleness is measurable.
                evict_and_put(
                    param_q,
                    (params, make_stamp(update, update * grad_steps_per_update, policy_step)),
                )
                train_metrics = jax.device_get(train_metrics)
                train_time = time.perf_counter() - t0
            assert_finite(cfg, train_metrics, "ppo_decoupled/update")
            with agg_lock:
                for k, v in train_metrics.items():
                    aggregator.update(k, float(v))

            if logger is not None and (
                policy_step - last_log >= cfg.metric.log_every or update == num_updates or cfg.dry_run
            ):
                with agg_lock:
                    metrics = aggregator.compute()
                    aggregator.reset()
                metrics["Time/sps_train"] = grad_steps_per_update / train_time if train_time > 0 else 0.0
                metrics["Time/sps_env_interaction"] = (
                    policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
                )
                grad_step_count = update * grad_steps_per_update
                metrics["Params/lr"] = (
                    float(fns.lr_schedule(grad_step_count))
                    if fns.lr_schedule is not None
                    else float(cfg.algo.optimizer.lr)
                )
                monitor.log_metrics(logger, metrics, policy_step)
                last_log = policy_step

            def save_ckpt():
                nonlocal last_checkpoint
                path = ckpt_manager.save(
                    policy_step,
                    {
                        "params": params,
                        "opt_state": opt_state,
                        "update": update,
                        "policy_step": policy_step,
                        "last_log": last_log,
                        "last_checkpoint": policy_step,
                    },
                )
                last_checkpoint = policy_step
                return path

            if (
                cfg.checkpoint.every > 0
                and (policy_step - last_checkpoint) >= cfg.checkpoint.every
                or update == num_updates
                and cfg.checkpoint.save_last
            ):
                save_ckpt()
            guard.boundary(policy_step, save_ckpt)
    finally:
        stop.set()
        player_thread.join(timeout=30)
        monitor.close()

    if player_thread.is_alive():
        # The player is stuck inside envs.step(); closing the envs under it would
        # raise a secondary error that masks the original one.
        raise RuntimeError("decoupled player thread did not shut down cleanly")
    envs.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(agent, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()
