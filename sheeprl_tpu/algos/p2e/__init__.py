"""Plan2Explore shared machinery (reference: ``/root/reference/sheeprl/algos/p2e_dv{1,2,3}``).

The reference builds its disagreement ensemble as a python list of N independent MLPs
iterated one-by-one (``p2e_dv3/agent.py:175-204``, ``p2e_dv3_exploration.py:208-230``).
TPU-native version: ONE MLP definition with N **stacked** parameter pytrees driven by
``jax.vmap`` — every ensemble member's matmul fuses into a single batched MXU op, for
both the training loss and the intrinsic-reward variance, instead of N small kernels.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.models.blocks import MLP


def build_ensembles(
    rng_key: jax.Array,
    n: int,
    input_dim: int,
    output_dim: int,
    dense_units: int,
    mlp_layers: int,
    activation: str,
    layer_norm: bool,
    dtype: Any,
) -> Tuple[MLP, Any]:
    """N ensemble members as one module + stacked params (reference seeds each member
    differently, ``p2e_dv3/agent.py:178-199``; here each member gets its own PRNG key)."""
    mlp = MLP(
        hidden_sizes=(dense_units,) * mlp_layers,
        output_dim=output_dim,
        activation=activation,
        layer_norm=layer_norm,
        dtype=dtype,
    )
    keys = jax.random.split(rng_key, n)
    dummy = jnp.zeros((1, input_dim))
    stacked = jax.vmap(lambda k: mlp.init(k, dummy))(keys)
    return mlp, stacked


def ensemble_apply(mlp: MLP, stacked_params: Any, x: jax.Array) -> jax.Array:
    """[N, ...] predictions from all members in one vmapped (batched-matmul) pass."""
    return jax.vmap(lambda p: mlp.apply(p, x))(stacked_params)


def ensemble_loss(mlp: MLP, stacked_params: Any, inputs: jax.Array, targets: jax.Array) -> jax.Array:
    """Sum over members of the per-member MSE 'log-prob' loss (reference
    ``p2e_dv3_exploration.py:206-221``: ``-MSEDistribution(out[:-1], 1).log_prob(next)``)."""
    preds = ensemble_apply(mlp, stacked_params, inputs)[:, :-1]  # [N, T-1, B, D]
    sq = jnp.sum((preds - targets[None]) ** 2, -1)  # MSEDistribution dims=1 log_prob = -Σ(err²)
    return jnp.mean(sq, axis=(1, 2)).sum()


def ensemble_loss_normal(mlp: MLP, stacked_params: Any, inputs: jax.Array, targets: jax.Array) -> jax.Array:
    """DV1/DV2 variant: unit-variance Gaussian NLL instead of raw MSE (reference
    ``p2e_dv2_exploration.py:198-210``, ``p2e_dv1_exploration.py:168-174``)."""
    preds = ensemble_apply(mlp, stacked_params, inputs)[:, :-1]  # [N, T-1, B, D]
    dim = targets.shape[-1]
    log_norm = 0.5 * dim * jnp.log(2 * jnp.pi)
    nll = 0.5 * jnp.sum((preds - targets[None]) ** 2, -1) + log_norm
    return jnp.mean(nll, axis=(1, 2)).sum()


def intrinsic_reward(
    mlp: MLP, stacked_params: Any, inputs: jax.Array, multiplier: float
) -> jax.Array:
    """Ensemble-disagreement intrinsic reward (reference ``p2e_dv3_exploration.py:270-287``):
    variance across members of the predicted next-state embedding, mean over features."""
    preds = ensemble_apply(mlp, stacked_params, jax.lax.stop_gradient(inputs))  # [N, H+1, TB, D]
    return preds.var(0).mean(-1, keepdims=True) * multiplier


def load_exploration_config(cfg) -> Any:
    """Load + validate the exploration run's config for finetuning
    (reference ``cli.py:117-148``)."""
    from pathlib import Path

    from sheeprl_tpu.config.core import load_config

    ckpt_path = Path(cfg.checkpoint.exploration_ckpt_path)
    run_dir = ckpt_path.parent.parent if ckpt_path.is_dir() else ckpt_path.parent
    cfg_path = run_dir / "config.yaml"
    if not cfg_path.is_file():
        cfg_path = ckpt_path.parent / "config.yaml"
    if not cfg_path.is_file():
        raise FileNotFoundError(f"No config.yaml found alongside exploration checkpoint {ckpt_path}")
    exploration_cfg = load_config(cfg_path)
    if exploration_cfg.env.id != cfg.env.id:
        raise ValueError(
            "This experiment is run with a different environment from the one of the "
            f"exploration you want to finetune. Got '{cfg.env.id}', but the environment "
            f"used during exploration was {exploration_cfg.env.id}."
        )
    # Environment geometry must match the exploration world model.
    for key in (
        "frame_stack",
        "screen_size",
        "action_repeat",
        "grayscale",
        "clip_rewards",
        "frame_stack_dilation",
        "max_episode_steps",
        "reward_as_observation",
        # Minecraft adapters (reference cli.py:139-145)
        "max_pitch",
        "min_pitch",
        "sticky_jump",
        "sticky_attack",
        "break_speed_multiplier",
    ):
        if key in exploration_cfg.env:
            cfg.env[key] = exploration_cfg.env[key]
    # The finetuned models must be built exactly like the exploration ones, or the
    # checkpoint cannot be loaded (reference p2e_dv3_finetuning.py:46-69).
    for key in (
        "gamma",
        "lmbda",
        "horizon",
        "layer_norm",
        "dense_units",
        "mlp_layers",
        "dense_act",
        "cnn_act",
        "unimix",
        "hafner_initialization",
        "world_model",
        "actor",
        "critic",
        "critics_exploration",
        "ensembles",
        "cnn_keys",
        "mlp_keys",
        "intrinsic_reward_multiplier",
    ):
        if key in exploration_cfg.algo:
            cfg.algo[key] = exploration_cfg.algo[key]
    # Reusing the exploration buffer requires the same env count (see reference note).
    if cfg.buffer.get("load_from_exploration") and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs
    return exploration_cfg
