"""DroQ (reference: ``/root/reference/sheeprl/algos/droq/droq.py``).

SAC with Dropout+LayerNorm critics at a high replay ratio (arXiv:2110.02034).
Reference semantics preserved: per minibatch, a shared TD target (min over EMA target
critics − α·logp') trains every critic, each followed by its EMA update
(``droq.py:95-122``); the actor trains on the MEAN of the Q-ensemble on a separate
batch (``:124-130``).  The per-critic sequential gradient steps collapse into one joint
step over the vmapped ensemble — the losses are parameter-disjoint, so the gradients are
identical and the MXU sees one batched matmul instead of N small ones.  All G gradient
steps of an iteration run in one ``lax.scan`` under jit."""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled, strict_guard
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.algos.sac.loss import actor_loss, alpha_loss
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_buffer import make_transition_ring
from sheeprl_tpu.data.prefetch import maybe_prefetcher
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import TrainingMonitor, flight_recorder
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.utils.blocks import FusedRingDispatcher, WindowedFutures
from sheeprl_tpu.models.blocks import MLP
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


class DroQCriticEnsemble(nn.Module):
    """Dropout+LayerNorm critic ensemble (reference ``droq/agent.py:20-60``),
    vmapped over the ensemble axis."""

    n_critics: int = 2
    hidden_size: int = 256
    dropout: float = 0.01
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, deterministic: bool = True) -> jax.Array:
        x = jnp.concatenate([obs, action], -1)

        class _Critic(nn.Module):
            hidden_size: int
            dropout: float
            dtype: Any

            @nn.compact
            def __call__(self, x, deterministic):
                for _ in range(2):
                    x = nn.Dense(self.hidden_size, dtype=self.dtype)(x)
                    if self.dropout > 0:
                        x = nn.Dropout(rate=self.dropout, deterministic=deterministic)(x)
                    x = nn.LayerNorm(dtype=self.dtype)(x)
                    x = nn.relu(x)
                return nn.Dense(1, dtype=self.dtype)(x)

        ensemble = nn.vmap(
            _Critic,
            in_axes=(None, None),
            out_axes=0,
            axis_size=self.n_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )
        return ensemble(self.hidden_size, self.dropout, self.dtype)(x, deterministic).astype(jnp.float32)


def make_droq_step_fns(actor, critic, cfg, act_space):
    """Optimizers + the per-gradient-step DroQ updates as pure functions, shared by
    the host-batch scans (:func:`make_droq_train_fns`) and the fused device-ring
    block (:func:`make_droq_fused_builder`):

    * ``critic_step(p, o_state, gstep, batch, key)`` — one shared-target ensemble
      critic update followed by its EMA (``gstep`` is the cumulative count BEFORE
      the step; the EMA cadence tests it post-increment);
    * ``actor_step(p, o_state, obs, key)`` — the once-per-iteration actor + alpha
      update on the mean of the Q-ensemble.
    """
    act_dim = int(np.prod(act_space.shape))
    target_entropy = -act_dim
    tau, gamma = cfg.algo.tau, cfg.algo.gamma
    health = health_enabled(cfg)  # trace-time constant (obs/health.py)
    target_update_freq = max(int(cfg.algo.critic.get("target_network_frequency", 1)), 1)
    actor_opt = make_optimizer(cfg.algo.actor.optimizer, 0.0)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, 0.0)
    alpha_opt = make_optimizer(cfg.algo.alpha.optimizer, 0.0)

    def critic_step(p, o_state, gstep, batch, key):
        k_next, k_drop = jax.random.split(key)
        alpha = jnp.exp(p["log_alpha"])
        next_mean, next_log_std = actor.apply(p["actor"], batch["next_obs"])
        next_act, next_logp = actor.dist(next_mean, next_log_std).sample_and_log_prob(k_next)
        next_logp = next_logp.sum(-1, keepdims=True)
        q_next = critic.apply(p["critic_target"], batch["next_obs"], next_act, True).min(axis=0)
        target = jax.lax.stop_gradient(
            batch["rewards"] + (1 - batch["dones"]) * gamma * (q_next - alpha * next_logp)
        )

        def c_loss(cp):
            qs = critic.apply(cp, batch["obs"], batch["actions"], False, rngs={"dropout": k_drop})
            return ((qs - target[None]) ** 2).mean(axis=(1, 2)).sum()

        cl, grads = jax.value_and_grad(c_loss)(p["critic"])
        updates, new_c_state = critic_opt.update(grads, o_state["critic"], p["critic"])
        p = {**p, "critic": optax.apply_updates(p["critic"], updates)}
        do_update = ((gstep + 1) % target_update_freq) == 0
        p = {
            **p,
            "critic_target": jax.tree.map(
                lambda tp, cp: jnp.where(do_update, (1 - tau) * tp + tau * cp, tp),
                p["critic_target"],
                p["critic"],
            ),
        }
        metrics = {"Loss/value_loss": cl}
        if health:
            metrics.update(
                diagnostics(
                    grads={"critic": grads},
                    params=p,
                    updates={"critic": updates},
                    aux={"target_q_mean": target.mean()},
                )
            )
        return p, {**o_state, "critic": new_c_state}, metrics

    def actor_step(p, o_state, obs, key):
        k_act, k_drop = jax.random.split(key)
        alpha = jnp.exp(p["log_alpha"])

        def a_loss(ap):
            mean, log_std = actor.apply(ap, obs)
            new_act, logp = actor.dist(mean, log_std).sample_and_log_prob(k_act)
            logp = logp.sum(-1, keepdims=True)
            # DroQ uses the ensemble MEAN, not the min (reference droq.py:126).
            mean_q = critic.apply(p["critic"], obs, new_act, False, rngs={"dropout": k_drop}).mean(axis=0)
            return actor_loss(alpha, logp, mean_q), logp

        (al, logp), grads = jax.value_and_grad(a_loss, has_aux=True)(p["actor"])
        updates, new_a_state = actor_opt.update(grads, o_state["actor"], p["actor"])
        p = {**p, "actor": optax.apply_updates(p["actor"], updates)}

        tl, t_grads = jax.value_and_grad(lambda la: alpha_loss(la, logp, target_entropy))(p["log_alpha"])
        t_updates, new_t_state = alpha_opt.update(t_grads, o_state["alpha"], p["log_alpha"])
        p = {**p, "log_alpha": optax.apply_updates(p["log_alpha"], t_updates)}
        metrics = {"Loss/policy_loss": al, "Loss/alpha_loss": tl}
        if health:
            metrics.update(
                diagnostics(
                    grads={"actor": grads, "alpha": t_grads},
                    params=p,
                    updates={"actor": updates, "alpha": t_updates},
                    aux={"policy_entropy": -logp.mean()},
                )
            )
        return p, {**o_state, "actor": new_a_state, "alpha": new_t_state}, metrics

    return actor_opt, critic_opt, alpha_opt, critic_step, actor_step


def make_droq_train_fns(actor, critic, cfg, act_space):
    """Host-replay-path jitted updates (the pre-ring dispatch shape): a scanned
    ``[G, B]`` critic block plus a separate actor dispatch."""
    strict = strict_enabled(cfg)
    actor_opt, critic_opt, alpha_opt, critic_step, actor_step = make_droq_step_fns(actor, critic, cfg, act_space)

    @jax.jit
    def train_critics_fn(p, o_state, batches, key, grad_step0):
        """G scanned critic updates with per-minibatch shared targets + EMA."""

        def step(carry, batch):
            p, o_state, gstep = carry
            p, o_state, step_metrics = critic_step(p, o_state, gstep, batch, batch.pop("_key"))
            return (p, o_state, gstep + 1), step_metrics

        g = batches["obs"].shape[0]
        batches["_key"] = jax.random.split(key, g)
        (p, o_state, _), metrics = jax.lax.scan(step, (p, o_state, grad_step0), batches)
        metrics = jax.tree.map(jnp.mean, metrics)
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict:  # trace-time constant: the callback only exists in strict runs
            nan_scan(metrics, "droq/train_critics_fn")
        return p, o_state, metrics

    @jax.jit
    def train_actor_fn(p, o_state, batch, key):
        return actor_step(p, o_state, batch["obs"], key)

    return actor_opt, critic_opt, alpha_opt, train_critics_fn, train_actor_fn


def make_droq_fused_builder(actor, critic, cfg, act_space, ring, batch_size: int):
    """Block builder for :class:`~sheeprl_tpu.utils.blocks.FusedRingDispatcher`:
    DroQ's whole UTD block — K scanned critic updates (each sampling its minibatch
    in-jit from the carried key) AND the once-per-iteration actor+alpha update on
    its own in-jit-sampled batch — as ONE donated jit dispatch.

    ``last`` gates the actor tail so a chunk-decomposed block still runs the actor
    exactly once per iteration (the dispatcher passes ``last=True`` only on the
    closing chunk; build it with ``last_sensitive=True``).  Critic keys derive
    from ``fold_in(critic_base, cumulative_step)`` and the actor key from the
    separate ``actor_base`` stream, so chunked and fused dispatches are
    bit-identical.
    """
    strict = strict_enabled(cfg)
    health = health_enabled(cfg)
    actor_opt, critic_opt, alpha_opt, critic_step, actor_step = make_droq_step_fns(actor, critic, cfg, act_space)
    sample_gather = ring.make_sample_gather(batch_size)

    def builder(k, last):
        def block(carry, arrays, filled, rows_added, base_key, start_count):
            c_base, a_base = jax.random.split(base_key)

            def step(c, count):
                p, o_state = c
                k_sample, k_update = jax.random.split(jax.random.fold_in(c_base, count))
                batch, age_metrics = sample_gather(arrays, filled, rows_added, k_sample)
                p, o_state, metrics = critic_step(p, o_state, count, batch, k_update)
                if health:  # replay staleness rides the same deferred-metrics tree
                    metrics = {**metrics, **age_metrics}
                return (p, o_state), metrics

            p, o_state = carry["params"], carry["opt_state"]
            metrics = {}
            if k > 0:
                counts = jnp.asarray(start_count, jnp.int32) + jnp.arange(k, dtype=jnp.int32)
                (p, o_state), critic_metrics = jax.lax.scan(step, (p, o_state), counts)
                metrics = jax.tree.map(jnp.mean, critic_metrics)
            if last:
                # The barrier stops XLA from fusing actor-tail ops into the critic
                # scan (including re-deciding the ring buffers' loop handling
                # because they are consumed again after it): without it the scan
                # body compiles (one ulp) differently than the critic-only
                # program, breaking the bit-identity contract between fused and
                # chunk-decomposed dispatches.
                p, o_state, tail_arrays = jax.lax.optimization_barrier((p, o_state, arrays))
                # Iteration-unique actor key: start_count + k is the cumulative
                # count closing this block, never reused by critic keys (own stream).
                k_sample, k_update = jax.random.split(
                    jax.random.fold_in(a_base, jnp.asarray(start_count, jnp.int32) + k)
                )
                abatch, _ = sample_gather(tail_arrays, filled, rows_added, k_sample)
                p, o_state, actor_metrics = actor_step(p, o_state, abatch["obs"], k_update)
                metrics = {**metrics, **actor_metrics}
            metrics = maybe_inject_nonfinite(cfg, metrics)
            if strict:  # trace-time constant: the callback only exists in strict runs
                nan_scan(metrics, "droq/fused_block")
            return {"params": p, "opt_state": o_state}, metrics

        return block

    return actor_opt, critic_opt, alpha_opt, builder


@register_algorithm(name="droq")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    import gymnasium

    if not isinstance(act_space, gymnasium.spaces.Box):
        raise ValueError("DroQ supports continuous (Box) action spaces only (reference parity)")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_low, act_high = act_space.low, act_space.high
    rescale = np.isfinite(act_low).all() and np.isfinite(act_high).all()
    act_dim = int(np.prod(act_space.shape))
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))

    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size, dtype=ctx.compute_dtype)
    critic = DroQCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
        dtype=ctx.compute_dtype,
    )
    dummy_obs, dummy_act = jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim))
    params = {
        "actor": actor.init(ctx.rng(), dummy_obs),
        "critic": critic.init({"params": ctx.rng(), "dropout": ctx.rng()}, dummy_obs, dummy_act),
        "log_alpha": jnp.asarray(jnp.log(cfg.algo.alpha.alpha), dtype=jnp.float32),
    }
    params["critic_target"] = jax.tree.map(lambda x: x, params["critic"])
    params = ctx.replicate(params)

    actor_opt, critic_opt, alpha_opt, train_critics_fn, train_actor_fn = make_droq_train_fns(
        actor, critic, cfg, act_space
    )
    # analysis.strict: signature guards on the jitted host-path updates
    train_critics_fn = obs_perf.instrument(cfg, "droq/train_critics_fn", strict_guard(cfg, "droq/train_critics_fn", train_critics_fn))
    train_actor_fn = obs_perf.instrument(cfg, "droq/train_actor_fn", strict_guard(cfg, "droq/train_actor_fn", train_actor_fn))
    opt_state = ctx.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    num_envs = cfg.env.num_envs
    world = jax.process_count()
    rb = ReplayBuffer(
        max(int(cfg.buffer.size) // max(num_envs * world, 1), 1),
        num_envs,
        obs_keys=mlp_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None,
    )
    rb.seed(cfg.seed + rank)
    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    futures = WindowedFutures()

    @jax.jit
    def act_fn(p, obs, key):
        mean, log_std = actor.apply(p, obs)
        return actor.dist(mean, log_std).sample(key)

    # Device-resident replay (buffer.device=True, data/device_buffer.py): the
    # transition ring lives in HBM and DroQ's whole UTD block — 20 critic updates
    # plus the actor update at replay_ratio=20 — fuses into ONE donated jit
    # dispatch with in-jit index sampling from the carried PRNG key.
    ring = make_transition_ring(
        ctx,
        cfg,
        rb,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    fused = None
    if ring is not None:
        _, _, _, fused_builder = make_droq_fused_builder(actor, critic, cfg, act_space, ring, batch_size)
        fused = FusedRingDispatcher(
            fused_builder,
            base_key=ctx.rng(),
            futures=futures,
            last_sensitive=True,
            cfg=cfg,
            perf_name="droq/fused_block",
        )
        # Donation safety: critic_target aliases critic's buffers at init — a
        # donated carry must not contain the same buffer twice.
        params = jax.tree.map(jnp.copy, params)
        opt_state = jax.tree.map(jnp.copy, opt_state)

    policy_steps_per_iter = num_envs * world
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_iters = max(learning_starts - 1, 0)

    start_iter, policy_step, last_log, last_checkpoint, cumulative_grad_steps = 1, 0, 0, 0, 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if ring is not None and len(rb) > 0:
                # The host buffer stays the source of truth: rebuild the HBM ring
                # (and its staleness stamps) from the restored rows.
                ring.load_from_transitions(
                    {
                        "obs": np.concatenate(
                            [rb[k].reshape(rb.buffer_size, num_envs, -1) for k in mlp_keys], -1
                        ),
                        "next_obs": np.concatenate(
                            [rb[f"next_{k}"].reshape(rb.buffer_size, num_envs, -1) for k in mlp_keys], -1
                        ),
                        "actions": rb["actions"],
                        "rewards": rb["rewards"],
                        "dones": rb["dones"],
                    },
                    stamps=rb.row_stamps,
                )

    obs, _ = envs.reset(seed=cfg.seed + rank)
    step_data: Dict[str, np.ndarray] = {}

    # Async host-side sampling + deferred metrics (see sac.py / utils/blocks.py):
    # the worker ships the next [G, B] critic block and the actor batch while the
    # device executes the current one; ``rb.add`` holds the sampler's lock.
    def _sample_block(n: int):
        sample = rb.sample(batch_size * n)
        batches = {
            "obs": np.concatenate([sample[k].reshape(n, batch_size, -1) for k in mlp_keys], -1),
            "next_obs": np.concatenate(
                [sample[f"next_{k}"].reshape(n, batch_size, -1) for k in mlp_keys], -1
            ),
            "actions": sample["actions"].reshape(n, batch_size, -1),
            "rewards": sample["rewards"].reshape(n, batch_size, 1),
            "dones": sample["dones"].reshape(n, batch_size, 1),
        }
        actor_sample = rb.sample(batch_size)
        actor_batch = {
            "obs": np.concatenate([actor_sample[k].reshape(batch_size, -1) for k in mlp_keys], -1)
        }
        return ctx.put_batch(batches, batch_axis=1), ctx.put_batch(actor_batch, batch_axis=0)

    # Slice only the per-step critic block when reusing a staged bigger block;
    # the actor batch has no step axis.
    prefetcher, rb_lock = maybe_prefetcher(
        cfg,
        _sample_block,
        slice_fn=lambda block, n: (jax.tree.map(lambda x: x[:n], block[0]), block[1]),
        enabled=ring is None,
    )

    recorder = flight_recorder.get_active()

    def _dispatch_train(grad_steps: int, stage_next: bool) -> None:
        nonlocal params, opt_state, cumulative_grad_steps
        if ring is not None:
            # Fused device-ring block: the K critic updates AND the actor update
            # land in one donated dispatch (the host path below pays two).
            carry = fused.dispatch(
                {"params": params, "opt_state": opt_state},
                ring.arrays,
                len(rb),
                rb.rows_added,
                grad_steps,
                cumulative_grad_steps,
            )
            params, opt_state = carry["params"], carry["opt_state"]
            cumulative_grad_steps += grad_steps
            if recorder is not None:
                # The pre-step state was DONATED into the block; re-stage
                # post-dispatch with a device-side copy (async, no host sync).
                recorder.stage_step(
                    carry=jax.tree.map(jnp.copy, carry),
                    scalars={
                        "grad_step0": int(cumulative_grad_steps),
                        "filled": len(rb),
                        "rows_added": rb.rows_added,
                    },
                )
            return
        batches, actor_batch = (
            prefetcher.get(grad_steps, stage_next=stage_next)
            if prefetcher is not None
            else _sample_block(grad_steps)
        )
        key = ctx.rng()
        if recorder is not None:  # device-array references only: no host sync
            recorder.stage_step(
                batch=batches,
                actor_batch=actor_batch,
                carry={"params": params, "opt_state": opt_state},
                key=key,
                scalars={"grad_step0": int(cumulative_grad_steps)},
            )
        params, opt_state, critic_metrics = train_critics_fn(
            params, opt_state, batches, key, jnp.asarray(cumulative_grad_steps)
        )
        params, opt_state, actor_metrics = train_actor_fn(
            params, opt_state, actor_batch, ctx.rng()
        )
        futures.track(
            {**critic_metrics, **actor_metrics},
            grad_steps,
        )
        cumulative_grad_steps += grad_steps

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            # Don't replay the random prefill after resume (see sac.py).
            if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from"):
                actions = np.stack([act_space.sample() for _ in range(num_envs)])
                tanh_actions = 2 * (actions - act_low) / (act_high - act_low) - 1 if rescale else actions
            else:
                obs_t = prepare_obs(obs, mlp_keys)
                tanh_actions = np.asarray(jax.device_get(act_fn(params["actor"], obs_t, ctx.local_rng())))
                actions = act_low + (tanh_actions + 1) * 0.5 * (act_high - act_low) if rescale else tanh_actions
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient work BEFORE stepping the envs so the
        # device trains while the host walks the environments; the first training
        # iteration (empty buffer — rows carry next_obs) defers until the row lands.
        grad_steps = 0
        deferred_dispatch = False
        if iter_num >= learning_starts:
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                if rb.empty:
                    deferred_dispatch = True
                else:
                    with monitor.phase("dispatch"):
                        _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            with monitor.phase("env_step"):
                next_obs, reward, terminated, truncated, info = envs.step(actions)
            done = np.logical_or(terminated, truncated)
            real_next = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in mlp_keys:
                            real_next[k][i] = np.asarray(info["final_obs"][i][k])
            for k in mlp_keys:
                step_data[k] = np.asarray(obs[k])[None]
                step_data[f"next_{k}"] = real_next[k][None]
            step_data["actions"] = tanh_actions.astype(np.float32)[None]
            step_data["rewards"] = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)[None]
            step_data["dones"] = terminated.astype(np.float32).reshape(num_envs, 1)[None]
            with monitor.phase("buffer_add"), rb_lock:
                if ring is not None:  # donated scatter at the host cursor, pre-add
                    ring.add_step(
                        {
                            "obs": np.concatenate(
                                [step_data[k].reshape(1, num_envs, -1) for k in mlp_keys], -1
                            ),
                            "next_obs": np.concatenate(
                                [step_data[f"next_{k}"].reshape(1, num_envs, -1) for k in mlp_keys], -1
                            ),
                            "actions": step_data["actions"],
                            "rewards": step_data["rewards"],
                            "dones": step_data["dones"],
                        },
                        rb._pos,
                        rb.rows_added,
                    )
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if deferred_dispatch:
            with monitor.phase("dispatch"):
                _dispatch_train(grad_steps, stage_next=iter_num < num_iters)

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            futures.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            metrics.update(replay_age_metrics(rb))
            window_sps = futures.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            metrics["Params/replay_ratio"] = cumulative_grad_steps * world / policy_step if policy_step else 0.0
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            if cfg.buffer.checkpoint:
                state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(actor, params, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): DroQ's whole fused
    UTD block — K scanned critic updates plus the once-per-iteration actor tail —
    as ONE donated jit, the exact program ``FusedRingDispatcher`` dispatches."""
    from sheeprl_tpu.analysis.ir.synth import box_act_space, compose_tiny, tiny_ctx, transition_ring
    from sheeprl_tpu.analysis.ir.types import AuditEntry

    cfg = compose_tiny(
        [
            "exp=droq",
            "env=continuous_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=8",
            "algo.per_rank_batch_size=4",
            "env.num_envs=2",
        ]
    )
    ctx = tiny_ctx(cfg)
    act_space = box_act_space()
    obs_dim, act_dim, batch_size = 5, 2, 4
    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size, dtype=ctx.compute_dtype)
    critic = DroQCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
        dtype=ctx.compute_dtype,
    )
    dummy_obs, dummy_act = jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim))
    params = {
        "actor": actor.init(ctx.rng(), dummy_obs),
        "critic": critic.init({"params": ctx.rng(), "dropout": ctx.rng()}, dummy_obs, dummy_act),
        "log_alpha": jnp.asarray(jnp.log(cfg.algo.alpha.alpha), dtype=jnp.float32),
    }
    params["critic_target"] = jax.tree.map(jnp.copy, params["critic"])

    ring, filled, rows_added = transition_ring(obs_dim=obs_dim, act_dim=act_dim)
    actor_opt, critic_opt, alpha_opt, builder = make_droq_fused_builder(
        actor, critic, cfg, act_space, ring, batch_size
    )
    opt_state = {
        "actor": actor_opt.init(params["actor"]),
        "critic": critic_opt.init(params["critic"]),
        "alpha": alpha_opt.init(params["log_alpha"]),
    }
    block = jax.jit(builder(2, True), donate_argnums=(0,))
    carry = {"params": params, "opt_state": opt_state}
    return [
        AuditEntry(
            name="droq/fused_block",
            fn=block,
            args=(carry, ring.arrays, filled, rows_added, jax.random.PRNGKey(0), 0),
            covers=("droq",),
            precision=str(cfg.mesh.precision),
        )
    ]
