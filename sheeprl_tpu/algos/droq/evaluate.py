"""DroQ evaluation entry (reference: ``algos/droq/evaluate.py``).

Rebuilds the DroQ-specific param tree (Dropout+LayerNorm critic ensemble) so the
checkpoint template matches; evaluation itself only uses the actor."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.droq.droq import DroQCriticEnsemble
from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["droq"])
def evaluate_droq(ctx, cfg: Dict[str, Any], ckpt_path: str) -> float:
    log_dir = get_log_dir(cfg)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    obs_space = env.observation_space
    act_space = env.action_space
    env.close()
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_dim = int(np.prod(act_space.shape))
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))

    actor = SACActor(act_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size, dtype=ctx.compute_dtype)
    critic = DroQCriticEnsemble(
        n_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
        dtype=ctx.compute_dtype,
    )
    dummy_obs, dummy_act = jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim))
    params = {
        "actor": actor.init(ctx.rng(), dummy_obs),
        "critic": critic.init({"params": ctx.rng(), "dropout": ctx.rng()}, dummy_obs, dummy_act),
        "log_alpha": jnp.zeros(()),
    }
    params["critic_target"] = jax.tree.map(lambda x: x, params["critic"])
    state = CheckpointManager.load(ckpt_path, templates={"params": jax.device_get(params)})
    params = ctx.replicate(state["params"])
    reward = test(actor, params, ctx, cfg, log_dir)
    print(f"Test/cumulative_reward: {reward}")
    return reward
