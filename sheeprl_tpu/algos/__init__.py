"""Algorithm registry population (reference: ``sheeprl/__init__.py:18-47``)."""

from sheeprl_tpu.algos.ppo import ppo as _ppo  # noqa: F401
from sheeprl_tpu.algos.ppo import ppo_decoupled as _ppo_dec  # noqa: F401
from sheeprl_tpu.algos.ppo import evaluate as _ppo_eval  # noqa: F401
from sheeprl_tpu.algos.sac import sac as _sac  # noqa: F401
from sheeprl_tpu.algos.sac import sac_decoupled as _sac_dec  # noqa: F401
from sheeprl_tpu.algos.sac import evaluate as _sac_eval  # noqa: F401
from sheeprl_tpu.algos.dreamer_v3 import dreamer_v3 as _dv3  # noqa: F401
from sheeprl_tpu.algos.dreamer_v3 import evaluate as _dv3_eval  # noqa: F401
from sheeprl_tpu.algos.dreamer_v2 import dreamer_v2 as _dv2  # noqa: F401
from sheeprl_tpu.algos.dreamer_v2 import evaluate as _dv2_eval  # noqa: F401
from sheeprl_tpu.algos.dreamer_v1 import dreamer_v1 as _dv1  # noqa: F401
from sheeprl_tpu.algos.dreamer_v1 import evaluate as _dv1_eval  # noqa: F401
from sheeprl_tpu.algos.p2e_dv3 import p2e_dv3_exploration as _p2e_dv3_expl  # noqa: F401
from sheeprl_tpu.algos.p2e_dv3 import p2e_dv3_finetuning as _p2e_dv3_fntn  # noqa: F401
from sheeprl_tpu.algos.p2e_dv3 import evaluate as _p2e_dv3_eval  # noqa: F401
from sheeprl_tpu.algos.p2e_dv2 import p2e_dv2_exploration as _p2e_dv2_expl  # noqa: F401
from sheeprl_tpu.algos.p2e_dv2 import p2e_dv2_finetuning as _p2e_dv2_fntn  # noqa: F401
from sheeprl_tpu.algos.p2e_dv2 import evaluate as _p2e_dv2_eval  # noqa: F401
from sheeprl_tpu.algos.p2e_dv1 import p2e_dv1_exploration as _p2e_dv1_expl  # noqa: F401
from sheeprl_tpu.algos.p2e_dv1 import p2e_dv1_finetuning as _p2e_dv1_fntn  # noqa: F401
from sheeprl_tpu.algos.p2e_dv1 import evaluate as _p2e_dv1_eval  # noqa: F401
from sheeprl_tpu.algos.a2c import a2c as _a2c  # noqa: F401
from sheeprl_tpu.algos.droq import droq as _droq  # noqa: F401
from sheeprl_tpu.algos.ppo_recurrent import ppo_recurrent as _ppo_rec  # noqa: F401
from sheeprl_tpu.algos.sac_ae import sac_ae as _sac_ae  # noqa: F401
