"""Algorithm registry population (reference: ``sheeprl/__init__.py:18-47``)."""

from sheeprl_tpu.algos.ppo import ppo as _ppo  # noqa: F401
from sheeprl_tpu.algos.ppo import evaluate as _ppo_eval  # noqa: F401
