"""DreamerV2 world-model loss (reference: ``/root/reference/sheeprl/algos/dreamer_v2/loss.py``).

KL balancing (Eq. 2 of the DV2 paper, reference ``loss.py:60-79``): the KL between the
posterior and prior categorical latents is computed twice — once with the posterior
stopped (training the prior toward the posterior, weight ``alpha``) and once with the
prior stopped (regularizing the posterior, weight ``1 - alpha``) — each clipped below by
``kl_free_nats``.  ``kl_free_avg`` selects whether the free-nats clip is applied to the
batch mean (reference default) or per-element before averaging.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def categorical_kl(post_logits: jax.Array, prior_logits: jax.Array) -> jax.Array:
    """KL( Cat(post) || Cat(prior) ) summed over the stochastic dimension.

    Inputs are ``[..., stoch, discrete]`` logits; output is ``[...]``.
    """
    post_logp = jax.nn.log_softmax(post_logits, -1)
    prior_logp = jax.nn.log_softmax(prior_logits, -1)
    post_p = jnp.exp(post_logp)
    return jnp.sum(post_p * (post_logp - prior_logp), axis=(-2, -1))


def reconstruction_loss(
    observation_lp: jax.Array,  # [T, B] summed log-prob of all decoded obs
    reward_lp: jax.Array,  # [T, B]
    prior_logits: jax.Array,  # [T, B, stoch, discrete]
    posterior_logits: jax.Array,  # [T, B, stoch, discrete]
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    continue_lp: Optional[jax.Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    observation_loss = -observation_lp.mean()
    reward_loss = -reward_lp.mean()
    sg = jax.lax.stop_gradient
    lhs = categorical_kl(sg(posterior_logits), prior_logits)
    rhs = categorical_kl(posterior_logits, sg(prior_logits))
    kl = lhs
    if kl_free_avg:
        loss_lhs = jnp.maximum(lhs.mean(), kl_free_nats)
        loss_rhs = jnp.maximum(rhs.mean(), kl_free_nats)
    else:
        loss_lhs = jnp.maximum(lhs, kl_free_nats).mean()
        loss_rhs = jnp.maximum(rhs, kl_free_nats).mean()
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    if continue_lp is not None:
        continue_loss = discount_scale_factor * -continue_lp.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    total = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    metrics = {
        "Loss/world_model_loss": total,
        "Loss/observation_loss": observation_loss,
        "Loss/reward_loss": reward_loss,
        "Loss/state_loss": kl_loss,
        "Loss/continue_loss": continue_loss,
        "State/kl": kl.mean(),
    }
    return total, metrics
