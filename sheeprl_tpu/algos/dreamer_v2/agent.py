"""DreamerV2 agent modules (reference: ``/root/reference/sheeprl/algos/dreamer_v2/agent.py``).

Differences from the DreamerV3 family (``sheeprl_tpu/algos/dreamer_v3/agent.py``) that
this module encodes, matching the reference:

* ELU activations and *optional* LayerNorm (reference defaults ``layer_norm=False``,
  ``agent.py:56,108``) instead of always-on LN+SiLU;
* VALID-padding conv stages in the encoder (k=4, s=2, ``agent.py:62-74``) and the
  Hafner DV2 decoder geometry (1×1 → k=5,5,6,6 s=2 → 64×64, ``agent.py:166-187``);
* no unimix on the categorical latents (``agent.py:383,395``);
* zero (not learned) initial recurrent/posterior state — ``is_first`` masking multiplies
  the carried state by ``(1 - is_first)`` (``agent.py:362-365``);
* actor with ``trunc_normal`` default for continuous actions (``agent.py:472-476``) and
  train-time exploration noise (``agent.py:558-574``);
* critic/reward heads emit a single Gaussian mean (no two-hot).

All recurrent unrolls happen in ``lax.scan`` inside the jitted train step — the modules
expose pure single-step methods for the scan bodies, like the DV3 agent.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerState, parse_actions_dim
from sheeprl_tpu.distributions import (
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
)
from sheeprl_tpu.models.blocks import MLP, LayerNormGRUCell

Dtype = Any


def compute_stochastic_state(key: Optional[jax.Array], logits: jax.Array, discrete: int = 32, sample: bool = True) -> jax.Array:
    """One-hot straight-through sample WITHOUT unimix (reference ``dreamer_v2/utils.py:80-96``)."""
    shaped = logits.reshape(*logits.shape[:-1], -1, discrete)
    dist = OneHotCategoricalStraightThrough(shaped)
    return dist.rsample(key) if sample else dist.mode


class CNNEncoderV2(nn.Module):
    """4× (conv k=4 s=2 VALID → [LN] → act); 64×64 → 2×2×8m (reference ``agent.py:62-76``)."""

    channels_multiplier: int = 48
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from sheeprl_tpu.models.blocks import _activation

        act = _activation(self.activation)
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:]).astype(self.dtype)
        for i in range(4):
            ch = self.channels_multiplier * (2**i)
            x = nn.Conv(ch, (4, 4), strides=(2, 2), padding="VALID", use_bias=not self.layer_norm, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x)
            x = act(x)
        return x.reshape(*lead, -1)


class MLPEncoderV2(nn.Module):
    """Plain dense stack, no symlog (reference ``agent.py:102-126``)."""

    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)


class EncoderV2(nn.Module):
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels_multiplier: int = 48
    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_keys:
            imgs = []
            for k in self.cnn_keys:
                img = obs[k]
                if img.dtype == jnp.uint8:
                    img = img.astype(jnp.float32) / 255.0 - 0.5
                imgs.append(jnp.moveaxis(img, -3, -1))
            x = jnp.concatenate(imgs, axis=-1)
            feats.append(
                CNNEncoderV2(
                    channels_multiplier=self.cnn_channels_multiplier,
                    activation=self.activation,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="cnn_encoder",
                )(x)
            )
        if self.mlp_keys:
            vec = jnp.concatenate([obs[k].astype(jnp.float32) for k in self.mlp_keys], axis=-1)
            feats.append(
                MLPEncoderV2(
                    dense_units=self.dense_units,
                    mlp_layers=self.mlp_layers,
                    activation=self.activation,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                    name="mlp_encoder",
                )(vec)
            )
        return jnp.concatenate(feats, axis=-1).astype(jnp.float32)


class CNNDecoderV2(nn.Module):
    """latent → dense → 1×1 feature map → 4 VALID deconvs (k=5,5,6,6 s=2) → 64×64
    channel-first reconstruction (reference ``agent.py:166-195``)."""

    output_shapes: Dict[str, Tuple[int, ...]]  # per-key [C, H, W]
    cnn_encoder_output_dim: int
    channels_multiplier: int = 48
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> Dict[str, jax.Array]:
        from sheeprl_tpu.models.blocks import _activation

        act = _activation(self.activation)
        total_c = sum(s[0] for s in self.output_shapes.values())
        x = nn.Dense(self.cnn_encoder_output_dim, dtype=self.dtype, name="latent_proj")(z.astype(self.dtype))
        lead = x.shape[:-1]
        x = x.reshape(-1, 1, 1, self.cnn_encoder_output_dim)
        channels = [self.channels_multiplier * 4, self.channels_multiplier * 2, self.channels_multiplier]
        kernels = [5, 5, 6, 6]
        for i, ch in enumerate(channels):
            x = nn.ConvTranspose(
                ch, (kernels[i], kernels[i]), strides=(2, 2), padding="VALID",
                use_bias=not self.layer_norm, dtype=self.dtype,
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype)(x)
            x = act(x)
        x = nn.ConvTranspose(total_c, (kernels[-1], kernels[-1]), strides=(2, 2), padding="VALID", dtype=self.dtype, name="head")(x)
        x = jnp.moveaxis(x, -1, -3).astype(jnp.float32)
        x = x.reshape(*lead, *x.shape[-3:])
        out, offset = {}, 0
        for k, shape in self.output_shapes.items():
            out[k] = x[..., offset : offset + shape[0], :, :]
            offset += shape[0]
        return out


class MLPDecoderV2(nn.Module):
    output_shapes: Dict[str, Tuple[int, ...]]
    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(z)
        return {
            k: nn.Dense(int(np.prod(shape)), dtype=self.dtype, name=f"head_{k}")(x).astype(jnp.float32)
            for k, shape in self.output_shapes.items()
        }


class RecurrentModelV2(nn.Module):
    """Dense(+LN)+act → LayerNormGRUCell (reference ``agent.py:264-298``)."""

    recurrent_state_size: int
    dense_units: int = 400
    activation: str = "elu"
    layer_norm: bool = True  # the GRU projection LN (reference config recurrent_model.layer_norm)
    dtype: Dtype = jnp.float32

    def setup(self):
        self.mlp = MLP(
            hidden_sizes=(self.dense_units,),
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            name="input_proj",
        )
        self.rnn = LayerNormGRUCell(hidden_size=self.recurrent_state_size, layer_norm=True, dtype=self.dtype)

    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = self.mlp(x)
        h, _ = self.rnn(recurrent_state, feat)
        return h.astype(jnp.float32)


class RSSMV2(nn.Module):
    """Discrete RSSM, no unimix, zero initial state (reference ``agent.py:301-413``)."""

    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 600
    dense_units: int = 400
    transition_hidden_size: int = 600
    representation_hidden_size: int = 600
    activation: str = "elu"
    layer_norm: bool = False
    recurrent_layer_norm: bool = True
    dtype: Dtype = jnp.float32

    def setup(self):
        stoch_out = self.stochastic_size * self.discrete_size
        self.recurrent_model = RecurrentModelV2(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            activation=self.activation,
            layer_norm=self.recurrent_layer_norm,
            dtype=self.dtype,
        )
        self.representation_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.representation_hidden_size,),
                    activation=self.activation,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                ),
                nn.Dense(stoch_out, dtype=self.dtype, name="repr_logits"),
            ]
        )
        self.transition_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.transition_hidden_size,),
                    activation=self.activation,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                ),
                nn.Dense(stoch_out, dtype=self.dtype, name="trans_logits"),
            ]
        )

    def _representation(self, recurrent_state: jax.Array, embedded_obs: jax.Array, key: Optional[jax.Array], sample: bool = True):
        logits = self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1)).astype(jnp.float32)
        return logits, compute_stochastic_state(key, logits, self.discrete_size, sample)

    def _transition(self, recurrent_state: jax.Array, key: Optional[jax.Array], sample: bool = True):
        logits = self.transition_model(recurrent_state).astype(jnp.float32)
        return logits, compute_stochastic_state(key, logits, self.discrete_size, sample)

    def dynamic(
        self,
        posterior: jax.Array,  # [B, stoch*discrete] flattened
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ):
        """One posterior step with zero-resets on ``is_first`` (reference ``agent.py:333-368``)."""
        action = (1 - is_first) * action
        posterior = (1 - is_first) * posterior
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(jnp.concatenate([posterior, action], -1), recurrent_state)
        k1, k2 = jax.random.split(key)
        prior_logits, prior = self._transition(recurrent_state, k1)
        posterior_logits, posterior_sample = self._representation(recurrent_state, embedded_obs, k2)
        posterior_flat = posterior_sample.reshape(*posterior_sample.shape[:-2], -1)
        return recurrent_state, posterior_flat, prior, posterior_logits, prior_logits

    def imagination(self, prior: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key: jax.Array):
        recurrent_state = self.recurrent_model(jnp.concatenate([prior, actions], -1), recurrent_state)
        _, imagined = self._transition(recurrent_state, key)
        return imagined.reshape(*imagined.shape[:-2], -1), recurrent_state


class WorldModelV2(nn.Module):
    """Encoder + RSSM + decoders + Gaussian reward head + optional continue head
    (reference ``build_agent``, ``agent.py:673-…``)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_shapes: Dict[str, Tuple[int, ...]]
    mlp_shapes: Dict[str, Tuple[int, ...]]
    cnn_channels_multiplier: int = 48
    dense_units: int = 400
    mlp_layers: int = 4
    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 600
    transition_hidden_size: int = 600
    representation_hidden_size: int = 600
    activation: str = "elu"
    layer_norm: bool = False
    recurrent_layer_norm: bool = True
    use_continues: bool = False
    image_size: int = 64
    dtype: Dtype = jnp.float32

    def setup(self):
        self.encoder = EncoderV2(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            cnn_channels_multiplier=self.cnn_channels_multiplier,
            dense_units=self.dense_units,
            mlp_layers=self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )
        self.rssm = RSSMV2(
            stochastic_size=self.stochastic_size,
            discrete_size=self.discrete_size,
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            transition_hidden_size=self.transition_hidden_size,
            representation_hidden_size=self.representation_hidden_size,
            activation=self.activation,
            layer_norm=self.layer_norm,
            recurrent_layer_norm=self.recurrent_layer_norm,
            dtype=self.dtype,
        )
        if self.cnn_keys:
            # VALID 4-stage encoder on a 64×64 input ends at 2×2×8m.
            final = (self.image_size - 4) // 2 + 1
            for _ in range(3):
                final = (final - 4) // 2 + 1
            self.observation_model_cnn = CNNDecoderV2(
                output_shapes=self.cnn_shapes,
                cnn_encoder_output_dim=final * final * self.cnn_channels_multiplier * 8,
                channels_multiplier=self.cnn_channels_multiplier,
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        if self.mlp_keys:
            self.observation_model_mlp = MLPDecoderV2(
                output_shapes=self.mlp_shapes,
                dense_units=self.dense_units,
                mlp_layers=self.mlp_layers,
                activation=self.activation,
                layer_norm=self.layer_norm,
                dtype=self.dtype,
            )
        self.reward_model = nn.Sequential(
            [
                MLP(
                    hidden_sizes=(self.dense_units,) * self.mlp_layers,
                    activation=self.activation,
                    layer_norm=self.layer_norm,
                    dtype=self.dtype,
                ),
                nn.Dense(1, dtype=self.dtype, name="reward_head"),
            ]
        )
        if self.use_continues:
            self.continue_model = nn.Sequential(
                [
                    MLP(
                        hidden_sizes=(self.dense_units,) * self.mlp_layers,
                        activation=self.activation,
                        layer_norm=self.layer_norm,
                        dtype=self.dtype,
                    ),
                    nn.Dense(1, dtype=self.dtype, name="continue_head"),
                ]
            )

    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.encoder(obs)

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            out.update(self.observation_model_cnn(latent))
        if self.mlp_keys:
            out.update(self.observation_model_mlp(latent))
        return out

    def reward(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent).astype(jnp.float32)

    def continues(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent).astype(jnp.float32)

    def dynamic(self, *args, **kwargs):
        return self.rssm.dynamic(*args, **kwargs)

    def imagination(self, *args, **kwargs):
        return self.rssm.imagination(*args, **kwargs)

    def representation(self, recurrent_state, embedded_obs, key, sample=True):
        return self.rssm._representation(recurrent_state, embedded_obs, key, sample)

    def __call__(self, obs: Dict[str, jax.Array], action: jax.Array, key: jax.Array):
        embed = self.encoder(obs)
        batch_shape = embed.shape[:-1]
        h0 = jnp.zeros((*batch_shape, self.recurrent_state_size))
        z0 = jnp.zeros((*batch_shape, self.stochastic_size * self.discrete_size))
        h, z, prior, post_logits, prior_logits = self.rssm.dynamic(
            z0, h0, action, embed, jnp.ones((*batch_shape, 1)), key
        )
        latent = jnp.concatenate([z, h], -1)
        recon = self.decode(latent)
        out = self.reward(latent)
        if self.use_continues:
            out = out + 0.0 * self.continues(latent)
        return out, recon


class ActorV2(nn.Module):
    """DV2 policy head (reference ``agent.py:416-574``): ``trunc_normal`` default for
    continuous actions, one-hot straight-through (no unimix) for discrete."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    init_std: float = 0.0
    min_std: float = 0.1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False, mask=None):
        dist_type = self.distribution
        if dist_type == "auto":
            dist_type = "trunc_normal" if self.is_continuous else "discrete"
        supported = ("discrete",) if not self.is_continuous else ("tanh_normal", "normal", "trunc_normal")
        if dist_type not in supported:
            raise ValueError(f"distribution.type={dist_type!r} not supported for this action space; use one of {supported}")
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(state)
        if self.is_continuous:
            out = nn.Dense(2 * sum(self.actions_dim), dtype=self.dtype, name="head")(x).astype(jnp.float32)
            mean, std = jnp.split(out, 2, -1)
            if dist_type == "tanh_normal":
                mean = 5 * jnp.tanh(mean / 5)
                std = jax.nn.softplus(std + self.init_std) + self.min_std
                dist = TanhNormal(mean, std)
            elif dist_type == "normal":
                dist = Normal(mean, std)
            else:  # trunc_normal
                std = 2 * jax.nn.sigmoid((std + self.init_std) / 2) + self.min_std
                dist = TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0)
            actions = dist.mode if (greedy or key is None) else dist.rsample(key)
            return (actions,), (dist,)
        heads = [nn.Dense(d, dtype=self.dtype, name=f"head_{i}")(x).astype(jnp.float32) for i, d in enumerate(self.actions_dim)]
        actions, dists = [], []
        keys = jax.random.split(key, len(heads)) if key is not None else [None] * len(heads)
        for logits, k in zip(heads, keys):
            d = OneHotCategoricalStraightThrough(logits)
            dists.append(d)
            actions.append(d.mode if (greedy or k is None) else d.rsample(k))
        return tuple(actions), tuple(dists)


class MinedojoActorV2(nn.Module):
    """Hierarchical masked MineDojo actor for the DV1/DV2 families (reference
    ``dreamer_v2/agent.py:577-…``; DV1 reuses it via ``dreamer_v1/agent.py:16-27``).
    Same conditional-mask scheme as the DV3 ``MinedojoActor`` — vectorized
    ``jnp.where`` selects instead of the reference's [T, B] python loops — with the
    family's ELU trunk and no unimix."""

    actions_dim: Sequence[int]  # (action-type, craft-arg, item-arg)
    is_continuous: bool = False
    distribution: str = "auto"
    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    init_std: float = 0.0
    min_std: float = 0.1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False, mask=None):
        if self.is_continuous:
            raise ValueError("MinedojoActorV2 only supports the functional MultiDiscrete action space")
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(state)
        heads = [nn.Dense(d, dtype=self.dtype, name=f"head_{i}")(x).astype(jnp.float32) for i, d in enumerate(self.actions_dim)]
        keys = jax.random.split(key, len(heads)) if key is not None else [None] * len(heads)
        neg_inf = jnp.finfo(jnp.float32).min

        actions, dists = [], []
        functional_action = None
        for i, logits in enumerate(heads):
            if mask is not None:
                if i == 0:
                    logits = jnp.where(mask["mask_action_type"], logits, neg_inf)
                elif i == 1:
                    is_craft = (functional_action == 15)[..., None]
                    allowed = jnp.where(is_craft, mask["mask_craft_smelt"], True)
                    logits = jnp.where(allowed, logits, neg_inf)
                elif i == 2:
                    is_equip_place = jnp.logical_or(functional_action == 16, functional_action == 17)[..., None]
                    is_destroy = (functional_action == 18)[..., None]
                    allowed = jnp.where(is_equip_place, mask["mask_equip_place"], True)
                    allowed = jnp.where(is_destroy, mask["mask_destroy"], allowed)
                    logits = jnp.where(allowed, logits, neg_inf)
            d = OneHotCategoricalStraightThrough(logits)
            dists.append(d)
            actions.append(d.mode if (greedy or keys[i] is None) else d.rsample(keys[i]))
            if functional_action is None:
                functional_action = actions[0].argmax(-1)
        return tuple(actions), tuple(dists)


class CriticV2(nn.Module):
    """Single Gaussian-mean value head (reference ``build_agent`` critic)."""

    dense_units: int = 400
    mlp_layers: int = 4
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array) -> jax.Array:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(state)
        return nn.Dense(1, dtype=self.dtype, name="head")(x).astype(jnp.float32)


def exploration_amount(expl_amount: float, expl_decay: float, expl_min: float, step: int) -> float:
    """Exploration schedule (reference ``agent.py:499-503``; the reference expression
    ``amount *= 0.5 ** float(step) / decay`` is an operator-precedence slip — the
    intended Hafner schedule is ``amount * 0.5 ** (step / decay)``, used here)."""
    amount = expl_amount
    if expl_decay:
        amount *= 0.5 ** (float(step) / expl_decay)
    return max(amount, expl_min)


def add_exploration_noise(
    actions: Tuple[jax.Array, ...],
    expl_amount: jax.Array,
    key: jax.Array,
    is_continuous: bool,
) -> Tuple[jax.Array, ...]:
    """Pure-JAX exploration noise (reference ``agent.py:558-574``): Gaussian jitter
    clipped to [-1, 1] for continuous actions; ε-uniform resampling for discrete."""
    if is_continuous:
        cat = jnp.concatenate(actions, -1)
        noisy = jnp.clip(cat + expl_amount * jax.random.normal(key, cat.shape), -1.0, 1.0)
        out = jnp.where(expl_amount > 0.0, noisy, cat)
        return (out,)
    noisy_actions = []
    for act in actions:
        key, k_sample, k_mask = jax.random.split(key, 3)
        rand = OneHotCategorical(jnp.zeros_like(act)).sample(k_sample)
        take_random = jax.random.uniform(k_mask, act.shape[:1]) < expl_amount
        noisy_actions.append(jnp.where(take_random[..., None], rand, act))
    return tuple(noisy_actions)


def _xavier_normal_init(params: Dict[str, Any], key: jax.Array) -> Dict[str, Any]:
    """Xavier-normal re-init of all kernels, zero biases (reference
    ``dreamer_v2/utils.py:101-118`` ``init_weights``, applied in ``build_agent``)."""
    import flax

    flat = flax.traverse_util.flatten_dict(params)
    keys = jax.random.split(key, len(flat))
    new = {}
    for i, (path, value) in enumerate(flat.items()):
        leaf = str(path[-1])
        if leaf == "kernel" and value.ndim >= 2:
            # torch.nn.init.xavier_normal_ counts the conv receptive field in BOTH
            # fans (kernel layout here is [*rf, in, out]).
            receptive_field = int(np.prod(value.shape[:-2])) if value.ndim > 2 else 1
            fan_in = receptive_field * int(value.shape[-2])
            fan_out = receptive_field * int(value.shape[-1])
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            new[path] = std * jax.random.normal(keys[i], value.shape, value.dtype)
        elif leaf == "bias":
            new[path] = jnp.zeros_like(value)
        else:
            new[path] = value
    return flax.traverse_util.unflatten_dict(new)


def build_agent(
    ctx,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
):
    """Construct DV2 world model / actor / critic (+ target critic) and params."""
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_shapes = {k: tuple(obs_space[k].shape) for k in cnn_keys}
    mlp_shapes = {k: tuple(obs_space[k].shape) for k in mlp_keys}
    wm_cfg = cfg.algo.world_model

    world_model = WorldModelV2(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_shapes=cnn_shapes,
        mlp_shapes=mlp_shapes,
        cnn_channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.dense_units,
        mlp_layers=cfg.algo.mlp_layers,
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        transition_hidden_size=wm_cfg.transition_model.hidden_size,
        representation_hidden_size=wm_cfg.representation_model.hidden_size,
        activation=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        recurrent_layer_norm=wm_cfg.recurrent_model.get("layer_norm", True),
        use_continues=wm_cfg.use_continues,
        image_size=cfg.env.screen_size,
        dtype=ctx.compute_dtype,
    )
    latent_size = wm_cfg.stochastic_size * wm_cfg.discrete_size + wm_cfg.recurrent_model.recurrent_state_size
    is_minedojo = "minedojo" in str(cfg.env.get("wrapper", {}).get("_target_", "")).lower()
    actor_cls = MinedojoActorV2 if is_minedojo else ActorV2
    actor = actor_cls(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        dtype=ctx.compute_dtype,
    )
    critic = CriticV2(
        dense_units=cfg.algo.critic.dense_units,
        mlp_layers=cfg.algo.critic.mlp_layers,
        activation=cfg.algo.dense_act,
        layer_norm=cfg.algo.layer_norm,
        dtype=ctx.compute_dtype,
    )

    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *cnn_shapes[k]), dtype=jnp.uint8)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *mlp_shapes[k]), dtype=jnp.float32)
    act_dim_sum = int(sum(actions_dim))
    wm_params = world_model.init(ctx.rng(), dummy_obs, jnp.zeros((1, act_dim_sum)), ctx.rng())
    actor_params = actor.init(ctx.rng(), jnp.zeros((1, latent_size)), ctx.rng())
    critic_params = critic.init(ctx.rng(), jnp.zeros((1, latent_size)))

    wm_params = {"params": _xavier_normal_init(wm_params["params"], ctx.rng())}
    actor_params = {"params": _xavier_normal_init(actor_params["params"], ctx.rng())}
    critic_params = {"params": _xavier_normal_init(critic_params["params"], ctx.rng())}
    target_critic_params = jax.tree.map(lambda x: x, critic_params)

    params = {
        "world_model": ctx.replicate(wm_params),
        "actor": ctx.replicate(actor_params),
        "critic": ctx.replicate(critic_params),
        "target_critic": ctx.replicate(target_critic_params),
    }
    return world_model, actor, critic, params, latent_size


def make_player_step(world_model: WorldModelV2, actor: ActorV2, actions_dim: Sequence[int], is_continuous: bool):
    """Pure player step with zero-resets and optional exploration noise
    (reference ``PlayerDV2``, ``agent.py:735-…``)."""

    def player_step(params, state: PlayerState, obs, is_first, key, expl_amount=0.0, greedy: bool = False):
        k_repr, k_act, k_expl = jax.random.split(key, 3)
        wm, ap = params["world_model"], params["actor"]
        mask = {k: v for k, v in obs.items() if k.startswith("mask")} or None
        embed = world_model.apply(wm, obs, method=WorldModelV2.encode)
        recurrent = (1 - is_first) * state.recurrent_state
        stoch = (1 - is_first) * state.stochastic_state
        prev_actions = (1 - is_first) * state.actions
        recurrent = world_model.apply(
            wm,
            jnp.concatenate([stoch, prev_actions], -1),
            recurrent,
            method=lambda m, x, h: m.rssm.recurrent_model(x, h),
        )
        _, stoch_sample = world_model.apply(wm, recurrent, embed, k_repr, method=WorldModelV2.representation)
        stoch = stoch_sample.reshape(*stoch_sample.shape[:-2], -1)
        latent = jnp.concatenate([stoch, recurrent], -1)
        actions, _ = actor.apply(ap, latent, k_act, greedy, mask)
        if not greedy:
            actions = add_exploration_noise(actions, jnp.asarray(expl_amount), k_expl, is_continuous)
        stored = jnp.concatenate(actions, -1)
        return actions, stored, PlayerState(recurrent, stoch, stored)

    return player_step
