"""DreamerV2 helpers (reference: ``/root/reference/sheeprl/algos/dreamer_v2/utils.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401  (shared host-side helpers)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "State/prior_entropy",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,  # [H, N, 1]
    values: jax.Array,  # [H, N, 1]
    continues: jax.Array,  # [H, N, 1] (already γ-scaled)
    bootstrap: jax.Array,  # [1, N, 1]
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(λ) targets over an imagined trajectory (reference ``utils.py:121-141``):
    ``λ[i] = r[i] + c[i]·((1-λ)·V[i+1] + λ·λ[i+1])`` with ``λ[H] = V[H]`` (bootstrap),
    computed as a reverse ``lax.scan``."""
    next_values = jnp.concatenate([values[1:], bootstrap], 0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def step(agg, x):
        inp, cont = x
        agg = inp + cont * lmbda * agg
        return agg, agg

    _, lv = jax.lax.scan(step, bootstrap[0], (inputs, continues), reverse=True)
    return lv
