"""DreamerV2 training loop (reference: ``/root/reference/sheeprl/algos/dreamer_v2/dreamer_v2.py``).

Same single-jit structure as the DV3 loop (RSSM unroll + imagination as ``lax.scan``,
three optimizer steps fused, GSPMD data parallelism via sharded batches); the DV2
specifics it encodes from the reference:

* KL balancing with ``kl_balancing_alpha`` (reference ``dreamer_v2.py:185-199``);
* Gaussian (unit variance) observation/reward/value likelihoods — no symlog/two-hot;
* hard target-critic copy every ``per_rank_target_network_update_freq`` gradient steps,
  applied *before* the update (reference ``dreamer_v2.py:696-701``);
* actor objective = ``objective_mix``·REINFORCE + (1-mix)·dynamics-backprop
  (reference ``dreamer_v2.py:308-330``);
* replay buffer type ∈ {sequential, episode} (reference ``dreamer_v2.py:496-517``) —
  the EpisodeBuffer's only consumer, with ``prioritize_ends`` sampling;
* tanh reward clipping (reference ``dreamer_v2.py:434``).
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite, nan_scan, strict_enabled
from sheeprl_tpu.algos.dreamer_v2.agent import (
    PlayerState,
    WorldModelV2,
    build_agent,
    exploration_amount,
    make_player_step,
    parse_actions_dim,
)
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.utils import (
    AGGREGATOR_KEYS,
    compute_lambda_values,
    prepare_obs,
    test,
)
from sheeprl_tpu.algos.ppo.ppo import make_optimizer
from sheeprl_tpu.checkpoint.manager import CheckpointManager
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.config.core import save_config
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import make_device_replay
from sheeprl_tpu.distributions import BernoulliSafeMode, Independent, Normal, OneHotCategorical
from sheeprl_tpu.obs import TrainingMonitor
from sheeprl_tpu.obs.health import diagnostics, health_enabled, replay_age_metrics
from sheeprl_tpu.rollout import rollout_metrics
from sheeprl_tpu.utils.env import make_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, record_episode_stats
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio


def make_train_step(world_model, actor, critic, cfg, cnn_keys, mlp_keys):
    wm_cfg = cfg.algo.world_model
    stoch = wm_cfg.stochastic_size
    discrete = wm_cfg.discrete_size
    stoch_size = stoch * discrete
    rec_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    lmbda = cfg.algo.lmbda
    ent_coef = cfg.algo.actor.ent_coef
    objective_mix = cfg.algo.actor.objective_mix
    is_continuous = actor.is_continuous
    actions_dim = tuple(actor.actions_dim)
    use_continues = wm_cfg.use_continues

    wm_opt = make_optimizer(wm_cfg.optimizer, wm_cfg.clip_gradients)
    actor_opt = make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_opt = make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)

    def init_opt_states(params):
        return {
            "world_model": wm_opt.init(params["world_model"]),
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
        }

    def train_step(params, opt_states, data, key, update_target: jax.Array):
        T, B = data["rewards"].shape[:2]
        k_wm, k_img, k_a0 = jax.random.split(key, 3)
        sg = jax.lax.stop_gradient

        # Hard target-critic copy BEFORE the update (reference dreamer_v2.py:696-701).
        target_params = jax.lax.cond(
            update_target,
            lambda: jax.tree.map(lambda x: x, params["critic"]),
            lambda: params["target_critic"],
        )

        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        is_first = data["is_first"].at[0].set(1.0)
        # Rows store the action taken FROM their observation; the RSSM consumes the
        # action leading TO it — shift right with a zero first action.
        batch_actions = jnp.concatenate([jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], 0)

        # ------------------------------------------------ world model update
        def wm_loss_fn(wm_params):
            embed = world_model.apply(wm_params, batch_obs, method=WorldModelV2.encode)

            def step(carry, x):
                post, rec = carry
                action, emb, first, k = x
                rec, post, _, post_logits, prior_logits = world_model.apply(
                    wm_params, post, rec, action, emb, first, k, method=WorldModelV2.dynamic
                )
                return (post, rec), (rec, post, post_logits, prior_logits)

            keys = jax.random.split(k_wm, T)
            init = (jnp.zeros((B, stoch_size)), jnp.zeros((B, rec_size)))
            _, (recs, posts, post_logits, prior_logits) = jax.lax.scan(
                step, init, (batch_actions, embed, is_first, keys), unroll=8
            )
            latents = jnp.concatenate([posts, recs], -1)
            recon = world_model.apply(wm_params, latents, method=WorldModelV2.decode)

            # Unit-variance Gaussian likelihoods (reference dreamer_v2.py:167-170).
            obs_lp = 0.0
            for k in cnn_keys:
                target = data[k].astype(jnp.float32) / 255.0 - 0.5
                target = target.reshape(T, B, -1, *target.shape[-2:])
                obs_lp = obs_lp + Independent(Normal(recon[k], jnp.ones_like(recon[k])), 3).log_prob(target)
            for k in mlp_keys:
                obs_lp = obs_lp + Independent(Normal(recon[k], jnp.ones_like(recon[k])), 1).log_prob(data[k])

            reward_lp = Independent(
                Normal(world_model.apply(wm_params, latents, method=WorldModelV2.reward), 1.0), 1
            ).log_prob(data["rewards"])
            continue_lp = None
            if use_continues:
                continue_lp = Independent(
                    BernoulliSafeMode(world_model.apply(wm_params, latents, method=WorldModelV2.continues)), 1
                ).log_prob((1.0 - data["terminated"]) * gamma)

            post_logits_s = post_logits.reshape(T, B, stoch, discrete)
            prior_logits_s = prior_logits.reshape(T, B, stoch, discrete)
            rec_loss, metrics = reconstruction_loss(
                obs_lp,
                reward_lp,
                prior_logits_s,
                post_logits_s,
                wm_cfg.kl_balancing_alpha,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_free_avg,
                wm_cfg.kl_regularizer,
                continue_lp,
                wm_cfg.discount_scale_factor,
            )
            metrics["State/post_entropy"] = Independent(OneHotCategorical(post_logits_s), 1).entropy().mean()
            metrics["State/prior_entropy"] = Independent(OneHotCategorical(prior_logits_s), 1).entropy().mean()
            return rec_loss, (posts, recs, metrics)

        (rec_loss, (posts, recs, wm_metrics)), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(
            params["world_model"]
        )
        wm_updates, new_wm_opt = wm_opt.update(wm_grads, opt_states["world_model"], params["world_model"])
        new_wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # ------------------------------------------------ imagination + actor
        prior0 = sg(posts).reshape(T * B, stoch_size)
        rec0 = sg(recs).reshape(T * B, rec_size)
        latent0 = jnp.concatenate([prior0, rec0], -1)
        true_continue0 = (1.0 - data["terminated"]).reshape(T * B, 1) * gamma

        def actor_loss_fn(actor_params):
            def img_step(carry, k):
                prior, rec, latent = carry
                k_act, k_dyn = jax.random.split(k)
                acts, _ = actor.apply(actor_params, sg(latent), k_act)
                action = jnp.concatenate(acts, -1)
                prior, rec = world_model.apply(new_wm_params, prior, rec, action, k_dyn, method=WorldModelV2.imagination)
                new_latent = jnp.concatenate([prior, rec], -1)
                return (prior, rec, new_latent), (new_latent, action)

            keys = jax.random.split(k_img, horizon)
            _, (latents_img, actions_img) = jax.lax.scan(img_step, (prior0, rec0, latent0), keys, unroll=5)
            traj = jnp.concatenate([latent0[None], latents_img], 0)  # [H+1, N, L]
            imagined_actions = jnp.concatenate(
                [jnp.zeros_like(actions_img[:1]), actions_img], 0
            )  # [H+1, N, A]; index 0 is the zero action (reference dreamer_v2.py:237)

            target_values = critic.apply(target_params, traj)  # [H+1, N, 1]
            rewards_img = world_model.apply(new_wm_params, traj, method=WorldModelV2.reward)
            if use_continues:
                probs = jax.nn.sigmoid(world_model.apply(new_wm_params, traj, method=WorldModelV2.continues))
                continues = jnp.concatenate([true_continue0[None], probs[1:]], 0)
            else:
                continues = jnp.ones_like(rewards_img) * gamma

            lambda_values = compute_lambda_values(
                rewards_img[:-1], target_values[:-1], continues[:-1], target_values[-1:], lmbda
            )  # [H, N, 1]
            discount = sg(jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], 0), 0))

            _, dists = actor.apply(actor_params, sg(traj[:-2]), None)
            dynamics = lambda_values[1:]
            advantage = sg(lambda_values[1:] - target_values[:-2])
            if is_continuous:
                logpi = dists[0].log_prob(sg(imagined_actions[1:-1])).sum(-1, keepdims=True)
                reinforce = logpi * advantage
                entropy = dists[0].entropy().sum(-1)
            else:
                logpis = []
                ent = 0.0
                offset_a = 0
                for i, d in enumerate(dists):
                    act_i = sg(imagined_actions[1:-1, ..., offset_a : offset_a + actions_dim[i]])
                    logpis.append(d.log_prob(act_i))
                    ent = ent + d.entropy()
                    offset_a += actions_dim[i]
                reinforce = sum(logpis)[..., None] * advantage
                entropy = ent
            objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
            policy_loss = -jnp.mean(discount[:-2] * (objective + ent_coef * entropy[..., None]))
            aux = {"traj": sg(traj), "lambda_values": sg(lambda_values), "discount": discount}
            return policy_loss, aux

        (policy_loss, actor_aux), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_updates, new_actor_opt = actor_opt.update(actor_grads, opt_states["actor"], params["actor"])
        new_actor_params = optax.apply_updates(params["actor"], actor_updates)

        # ------------------------------------------------ critic
        traj = actor_aux["traj"]
        lambda_values = actor_aux["lambda_values"]
        discount = actor_aux["discount"]

        def critic_loss_fn(critic_params):
            qv = Independent(Normal(critic.apply(critic_params, traj[:-1]), 1.0), 1)
            return -jnp.mean(discount[:-1, ..., 0] * qv.log_prob(lambda_values))

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_updates, new_critic_opt = critic_opt.update(critic_grads, opt_states["critic"], params["critic"])
        new_critic_params = optax.apply_updates(params["critic"], critic_updates)

        new_params = {
            "world_model": new_wm_params,
            "actor": new_actor_params,
            "critic": new_critic_params,
            "target_critic": target_params,
        }
        new_opt_states = {"world_model": new_wm_opt, "actor": new_actor_opt, "critic": new_critic_opt}
        metrics = dict(wm_metrics)
        metrics["Loss/policy_loss"] = policy_loss
        metrics["Loss/value_loss"] = value_loss
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/actor"] = optax.global_norm(actor_grads)
        metrics["Grads/critic"] = optax.global_norm(critic_grads)
        if health_enabled(cfg):  # trace-time constant (obs/health.py)
            metrics.update(
                diagnostics(
                    grads={"world_model": wm_grads, "actor": actor_grads, "critic": critic_grads},
                    params=new_params,
                    updates={"world_model": wm_updates, "actor": actor_updates, "critic": critic_updates},
                )
            )
        metrics = maybe_inject_nonfinite(cfg, metrics)
        if strict_enabled(cfg):  # trace-time constant: callback exists only in strict runs
            nan_scan(metrics, "dreamer_v2/train_step")
        return new_params, new_opt_states, metrics

    return train_step, init_opt_states


def make_buffer(cfg, num_envs, obs_keys, log_dir, rank, world):
    """sequential | episode buffer switch (reference ``dreamer_v2.py:496-517``)."""
    buffer_size = max(int(cfg.buffer.size) // max(num_envs * world, 1), 1)
    buffer_type = str(cfg.buffer.get("type", "sequential")).lower()
    memmap_dir = os.path.join(log_dir, "memmap_buffer", f"rank_{rank}") if cfg.buffer.memmap else None
    if buffer_type == "sequential":
        return EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=obs_keys,
            memmap=cfg.buffer.memmap,
            memmap_dir=memmap_dir,
            buffer_cls=SequentialReplayBuffer,
        )
    if buffer_type == "episode":
        return EpisodeBuffer(
            buffer_size,
            minimum_episode_length=1 if cfg.dry_run else cfg.algo.per_rank_sequence_length,
            n_envs=num_envs,
            obs_keys=obs_keys,
            prioritize_ends=cfg.buffer.get("prioritize_ends", False),
            memmap=cfg.buffer.memmap,
            memmap_dir=memmap_dir,
        )
    raise ValueError(f"Unrecognized buffer type: must be one of `sequential` or `episode`, received: {buffer_type}")


@register_algorithm(name="dreamer_v2")
def main(ctx, cfg) -> None:
    rank = ctx.process_index
    log_dir = get_log_dir(cfg)
    if ctx.is_global_zero:
        save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)

    envs = make_vector_env(cfg, cfg.seed, rank, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    is_continuous, actions_dim = parse_actions_dim(act_space)
    act_dim_sum = int(sum(actions_dim))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    num_envs = cfg.env.num_envs
    world = jax.process_count()

    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, is_continuous, cfg, obs_space)
    train_step, init_opt_states = make_train_step(world_model, actor, critic, cfg, cnn_keys, mlp_keys)
    opt_states = ctx.replicate(init_opt_states(params))
    target_update_freq = cfg.algo.critic.per_rank_target_network_update_freq

    # One jitted scan per iteration's gradient block (utils/blocks.py); DV2's hard
    # target copy tests the count BEFORE the increment (fires on the first step).
    def _block_step(carry, batch, key, update_target):
        params, opt_states = carry
        params, opt_states, metrics = train_step(params, opt_states, batch, key, update_target)
        return (params, opt_states), metrics


    player_step = make_player_step(world_model, actor, actions_dim, is_continuous)
    player_jit = jax.jit(player_step, static_argnames=("greedy",))
    stoch_size = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
    rec_size = cfg.algo.world_model.recurrent_model.recurrent_state_size

    def player_state_init(n: int) -> PlayerState:
        return PlayerState(
            recurrent_state=jnp.zeros((n, rec_size)),
            stochastic_state=jnp.zeros((n, stoch_size)),
            actions=jnp.zeros((n, act_dim_sum)),
        )

    rb = make_buffer(cfg, num_envs, obs_keys, log_dir, rank, world)
    rb.seed(cfg.seed + rank)
    # Device-vs-host replay data path, one shared implementation
    # (data/device_buffer.py); DV2's episode buffer stays on host.
    dispatcher, mirror, prefetcher, _run_block, rb_add = make_device_replay(
        ctx,
        cfg,
        rb,
        cnn_keys,
        mlp_keys,
        obs_space,
        act_dim_sum,
        _block_step,
        dispatcher_kwargs=dict(target_update_freq=target_update_freq, count_offset=0),
        require_sequential=True,
    )
    is_episode_buffer = isinstance(rb, EpisodeBuffer)

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)

    batch_size = cfg.algo.per_rank_batch_size
    seq_len = cfg.algo.per_rank_sequence_length
    policy_steps_per_iter = num_envs * world * cfg.env.action_repeat
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    expl_cfg = cfg.algo.actor

    start_iter = 1
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    cumulative_grad_steps = 0
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_states": jax.device_get(opt_states)},
        )
        params = ctx.replicate(state["params"])
        opt_states = ctx.replicate(state["opt_states"])
        ratio.load_state_dict(state["ratio"])
        start_iter = state["iter_num"] + 1
        policy_step = state["policy_step"]
        last_log = state.get("last_log", 0)
        last_checkpoint = state.get("last_checkpoint", 0)
        cumulative_grad_steps = state.get("cumulative_grad_steps", 0)
        learning_starts += start_iter
        if cfg.buffer.checkpoint and "rb" in state:
            rb.load_state_dict(state["rb"])
            if mirror is not None:
                mirror.load_from(rb)

    def _obs_row(o, idxs=None):
        row = {}
        for k in cnn_keys:
            v = np.asarray(o[k]) if idxs is None else np.asarray(o[k])[idxs]
            row[k] = v.reshape(1, v.shape[0], -1, *v.shape[-2:])
        for k in mlp_keys:
            v = np.asarray(o[k], dtype=np.float32) if idxs is None else np.asarray(o[k], dtype=np.float32)[idxs]
            row[k] = v.reshape(1, v.shape[0], -1)
        return row

    obs, _ = envs.reset(seed=cfg.seed + rank)
    player_state = player_state_init(num_envs)
    step_data: Dict[str, np.ndarray] = _obs_row(obs)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)
    is_first_np = np.ones((num_envs, 1), dtype=np.float32)
    prefill_iters = max(learning_starts - 1, 0)
    is_minedojo = "minedojo" in str(cfg.env.get("wrapper", {}).get("_target_", "")).lower()

    for iter_num in range(start_iter, num_iters + 1):
        monitor.advance()
        env_t0 = time.perf_counter()
        expl_amount = exploration_amount(
            expl_cfg.get("expl_amount", 0.0), expl_cfg.get("expl_decay", 0.0), expl_cfg.get("expl_min", 0.0), policy_step
        )
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and not cfg.checkpoint.get("resume_from") and not is_minedojo:
                if is_continuous:
                    stored_actions = np.stack([act_space.sample() for _ in range(num_envs)]).astype(np.float32)
                    env_actions = stored_actions
                else:
                    sampled = np.stack([act_space.sample() for _ in range(num_envs)]).reshape(num_envs, -1)
                    onehots = []
                    for i, d in enumerate(actions_dim):
                        oh = np.zeros((num_envs, d), dtype=np.float32)
                        oh[np.arange(num_envs), sampled[:, i]] = 1.0
                        onehots.append(oh)
                    stored_actions = np.concatenate(onehots, -1)
                    env_actions = sampled.squeeze(-1) if len(actions_dim) == 1 else sampled
                player_state = player_state._replace(actions=jnp.asarray(stored_actions))
            else:
                obs_t = prepare_obs(obs, cnn_keys, mlp_keys, num_envs)
                actions, stored, player_state = player_jit(
                    params, player_state, obs_t, jnp.asarray(is_first_np), ctx.local_rng(), jnp.asarray(expl_amount)
                )
                # ONE device_get for everything the host needs (per-array fetches
                # would each pay a transfer round trip on a remote accelerator).
                stored_np, acts_list = jax.device_get((stored, list(actions)))
                stored_actions = np.asarray(stored_np)
                acts_np = [np.asarray(a) for a in acts_list]
                if is_continuous:
                    env_actions = acts_np[0]
                elif len(actions_dim) == 1:
                    env_actions = acts_np[0].argmax(-1)
                else:
                    env_actions = np.stack([a.argmax(-1) for a in acts_np], -1)

            step_data["actions"] = stored_actions.reshape(1, num_envs, -1)
            rb_add(step_data, validate_args=cfg.buffer.validate_args)
        env_time = time.perf_counter() - env_t0

        # Dispatch this iteration's gradient block BEFORE stepping the envs: the
        # device trains while the host walks the environments below (acting above
        # used the previous iteration's params, exactly as the eager ordering did).
        grad_steps = 0
        if iter_num >= learning_starts:
            grad_steps = ratio(
                (policy_step + policy_steps_per_iter - prefill_iters * policy_steps_per_iter) / world
            )
            if grad_steps > 0:
                params, opt_states = _run_block(
                    (params, opt_states), grad_steps, cumulative_grad_steps, stage_next=iter_num < num_iters
                )
                cumulative_grad_steps += grad_steps

        env_t0 = time.perf_counter()
        with timer("Time/env_interaction_time"):
            next_obs, reward, terminated, truncated, info = envs.step(env_actions)
            if cfg.env.clip_rewards:
                reward = np.tanh(reward)
            done = np.logical_or(terminated, truncated)
            reward = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)

            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in obs_keys:
                            real_next_obs[k][i] = np.asarray(info["final_obs"][i][k])

            step_data = _obs_row(next_obs)
            step_data["rewards"] = reward.reshape(1, num_envs, 1).copy()
            step_data["terminated"] = terminated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["truncated"] = truncated.astype(np.float32).reshape(1, num_envs, 1)
            step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

            done_idxs = np.nonzero(done)[0].tolist()
            if done_idxs:
                reset_data = _obs_row(real_next_obs, idxs=done_idxs)
                reset_data["rewards"] = step_data["rewards"][:, done_idxs]
                reset_data["terminated"] = step_data["terminated"][:, done_idxs]
                reset_data["truncated"] = step_data["truncated"][:, done_idxs]
                reset_data["actions"] = np.zeros((1, len(done_idxs), act_dim_sum), np.float32)
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                rb_add(reset_data, indices=done_idxs, validate_args=cfg.buffer.validate_args)
                step_data["rewards"][:, done_idxs] = 0.0
                step_data["terminated"][:, done_idxs] = 0.0
                step_data["truncated"][:, done_idxs] = 0.0
                step_data["is_first"][:, done_idxs] = 1.0

            is_first_np = done.astype(np.float32).reshape(num_envs, 1)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(aggregator, info)
        env_time += time.perf_counter() - env_t0

        if logger is not None and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == num_iters or cfg.dry_run
        ):
            dispatcher.drain(aggregator)  # the window's only blocking device sync
            metrics = aggregator.compute()
            window_sps = dispatcher.pop_window_sps()
            if window_sps is not None:
                metrics["Time/sps_train"] = window_sps
            metrics["Time/sps_env_interaction"] = (
                policy_steps_per_iter / world / env_time if env_time > 0 else 0.0
            )
            metrics["Params/replay_ratio"] = (
                cumulative_grad_steps * world / policy_step if policy_step > 0 else 0.0
            )
            metrics["Params/exploration_amount"] = expl_amount
            metrics.update(replay_age_metrics(rb))
            metrics.update(rollout_metrics(envs))
            monitor.log_metrics(logger, metrics, policy_step)
            aggregator.reset()
            last_log = policy_step

        def save_ckpt():
            nonlocal last_checkpoint
            state = {
                "params": params,
                "opt_states": opt_states,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "cumulative_grad_steps": cumulative_grad_steps,
            }
            if cfg.buffer.checkpoint:
                state["rb"] = rb.state_dict()
            path = ckpt_manager.save(policy_step, state)
            last_checkpoint = policy_step
            return path

        if (
            cfg.checkpoint.every > 0
            and (policy_step - last_checkpoint) >= cfg.checkpoint.every
            or iter_num == num_iters
            and cfg.checkpoint.save_last
        ):
            save_ckpt()
        guard.boundary(policy_step, save_ckpt)

    monitor.close()
    envs.close()
    if prefetcher is not None:
        prefetcher.close()
    if cfg.algo.run_test and ctx.is_global_zero:
        reward = test(player_step, params, player_state_init, ctx, cfg, log_dir)
        if logger is not None:
            logger.log_metrics({"Test/cumulative_reward": reward}, policy_step)
    if logger is not None:
        logger.close()


def lower_for_audit():
    """IR-audit hook (``python -m sheeprl_tpu.analysis.ir``): the DreamerV2
    gradient block (``make_train_step`` in the dispatcher's ``make_train_block``
    scan, hard target copies on the DV2 ``count_offset=0`` cadence) at tiny
    MLP-only synthetic shapes."""
    from sheeprl_tpu.analysis.ir.synth import (
        DREAMER_DISCRETE_OVERRIDES,
        DREAMER_TINY_OVERRIDES,
        compose_tiny,
        sequence_batch,
        tiny_ctx,
        vector_space,
    )
    from sheeprl_tpu.analysis.ir.types import AuditEntry
    from sheeprl_tpu.utils.blocks import make_train_block

    cfg = compose_tiny(
        ["exp=dreamer_v2_dummy", "env=discrete_dummy", *DREAMER_TINY_OVERRIDES, *DREAMER_DISCRETE_OVERRIDES]
    )
    ctx = tiny_ctx(cfg)
    obs_space = vector_space()
    actions_dim, is_continuous = (3,), False
    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, is_continuous, cfg, obs_space)
    train_step, init_opt_states = make_train_step(world_model, actor, critic, cfg, [], ["state"])
    carry = (params, init_opt_states(params))

    def _block_step(carry, batch, key, update_target):
        params, opt_states = carry
        params, opt_states, metrics = train_step(params, opt_states, batch, key, update_target)
        return (params, opt_states), metrics

    block = make_train_block(_block_step, cfg.algo.critic.per_rank_target_network_update_freq, 0)
    batch = sequence_batch(
        {"state": obs_space["state"].shape},
        act_dim=int(sum(actions_dim)),
        T=int(cfg.algo.per_rank_sequence_length),
        B=int(cfg.algo.per_rank_batch_size),
    )
    return [
        AuditEntry(
            name="dreamer_v2/train_block",
            fn=block,
            args=(carry, (batch,), jax.random.PRNGKey(0), 0),
            covers=("dreamer_v2", "p2e_dv2_finetuning"),
            precision=str(cfg.mesh.precision),
        )
    ]
