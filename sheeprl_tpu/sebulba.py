"""``python -m sheeprl_tpu.sebulba exp=... [overrides]``: Sebulba launcher.

Places one learner process plus ``distributed.num_actors`` actor processes,
babysits them (bounded-backoff actor respawn with generation bumps), and exits
with the learner's code; see ``sheeprl_tpu/distributed/launcher.py`` and
``howto/sebulba.md``.
"""

from sheeprl_tpu.distributed.launcher import main

if __name__ == "__main__":
    main()
