"""Sebulba roles: the actor and learner process bodies for the decoupled algorithms.

The thread-decoupled entry points (``sac_decoupled``, ``ppo_decoupled``) already
split acting from learning; this module re-places those two roles into separate
OS processes connected by the transport channel (Podracer's Sebulba topology,
arXiv 2104.06272 §3):

* **actor** (``distributed.role=actor``, one process per ``actor_id``): owns its
  env shard (seeded disjointly via ``rank=actor_id``, exactly the multi-host
  seeding contract of ``make_vector_env``) and its replay SHARD — ``buffer.size /
  (num_envs * num_actors)`` rows, so no process ever materializes the global
  buffer.  It acts with the freshest published params, samples its gradient
  blocks locally, and streams them to the learner.
* **learner** (``distributed.role=learner``): accepts actor channels, consumes
  transition blocks from one bounded inbox (TCP backpressure throttles actors
  when it fills), runs the same jitted mesh update as the thread path, and
  broadcasts stamped params back through the weight publisher.

Parity contract with the thread path (pinned by
``tests/test_distributed/test_sebulba_smoke.py``): with ``num_actors=1`` and the
same seed, the PPO lockstep schedule feeds the learner bit-identical batches and
produces a bit-identical final checkpoint — every per-iteration count below uses
``num_actors`` exactly where the thread path uses ``jax.process_count()``.

Liveness contract (pinned by ``tests/test_distributed/test_actor_kill.py``): a
SIGKILLed actor closes its channel; the learner keeps consuming the surviving
channels (no barrier anywhere on the block path) while the launcher respawns the
actor with a bumped generation; the respawn reconnects, receives the latest
params as a welcome publish, and refills its replay shard from scratch.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.distributed.placement import SUMMARY_ENV_VAR, PlacementSpec
from sheeprl_tpu.distributed.publish import (
    PARAMS_KIND,
    ChannelWeightPublisher,
    staleness_steps,
)
from sheeprl_tpu.distributed.transport import (
    Channel,
    ChannelClosed,
    FramingError,
    Listener,
    connect,
    maybe_digest,
)
from sheeprl_tpu.obs import perf as obs_perf
from sheeprl_tpu.obs import flight_recorder as _flight_recorder
from sheeprl_tpu.obs import tracer as _tracer
from sheeprl_tpu.obs.fleet import maybe_exporter
from sheeprl_tpu.rollout.sharding import shard_pool_cfg

HELLO_KIND = "hello"
BLOCK_KIND = "block"
DONE_KIND = "done"
ABANDON_KIND = "abandon"

#: Sebulba observability keys (howto/observability.md): inbox depth in blocks,
#: actor-side policy-step age of the params each block was acted with, and the
#: transport byte counters (per-channel keys get a ``/ch<actor_id>`` suffix).
SEBULBA_METRIC_KEYS = frozenset(
    {"Sebulba/queue_depth", "Sebulba/param_staleness_steps", "Sebulba/xfer_bytes"}
)


# ----------------------------------------------------------------------- inbox
class LearnerInbox:
    """Accept loop + one reader thread per actor channel, all feeding ONE bounded
    queue — the process analogue of the thread path's ``batch_q``.

    The queue depth (``distributed.queue_depth``) is the whole flow control: when
    the learner falls behind, readers block on ``put``, the kernel socket buffers
    fill, and every actor's ``send`` stalls — backpressure without any protocol.
    A dead actor never wedges the learner: its reader dies with ``ChannelClosed``
    and enqueues a ``closed`` control item instead of a block.
    """

    def __init__(self, listener: Listener, spec: PlacementSpec, on_connect=None):
        self._listener = listener
        self._spec = spec
        self._q: "queue.Queue[Tuple[str, int, Dict[str, Any], Any]]" = queue.Queue(
            maxsize=spec.queue_depth
        )
        self._lock = threading.Lock()
        self._channels: Dict[int, Channel] = {}
        self._bytes_drained = 0
        self._stop = threading.Event()
        #: [monotonic_t, actor_id, generation, event] — the learner summary's
        #: lifecycle trace (the actor-kill test reads the kill window off it).
        self.events: List[List[Any]] = []
        self.on_connect = on_connect
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="sebulba-accept", daemon=True
        )
        self._accept_thread.start()

    def channels(self) -> List[Channel]:
        with self._lock:
            return list(self._channels.values())

    def qsize(self) -> int:
        return self._q.qsize()

    def bytes_received(self) -> int:
        # Closed channels fold their totals into _bytes_drained (exactly once,
        # in their reader's finally) so the counter survives actor churn.
        with self._lock:
            return self._bytes_drained + sum(ch.bytes_received for ch in self._channels.values())

    def record(self, actor_id: int, generation: int, event: str) -> None:
        with self._lock:
            self.events.append([time.monotonic(), int(actor_id), int(generation), event])

    def get(self, timeout: Optional[float] = None) -> Tuple[str, int, Dict[str, Any], Any]:
        return self._q.get(timeout=timeout)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ch = self._listener.accept(timeout=0.5)
            except TimeoutError:
                continue
            except OSError:
                return
            threading.Thread(target=self._reader, args=(ch,), daemon=True).start()

    def _reader(self, ch: Channel) -> None:
        actor_id: Optional[int] = None
        generation = 0
        done = False
        try:
            kind, meta, _ = ch.recv(timeout=self._spec.connect_timeout_s)
            if kind == ABANDON_KIND:
                # The launcher gave up respawning this slot; tell the learner so
                # it does not wait forever for a ``done`` that will never come.
                self._q.put((ABANDON_KIND, int(meta["actor_id"]), dict(meta), None))
                return
            if kind != HELLO_KIND:
                return
            actor_id = int(meta["actor_id"])
            generation = int(meta.get("generation", 0))
            with self._lock:
                stale = self._channels.get(actor_id)
                self._channels[actor_id] = ch
            if stale is not None:
                stale.close()
            self.record(actor_id, generation, "connected")
            if self.on_connect is not None:
                self.on_connect(ch)
            while not done:
                before = ch.bytes_received
                kind, meta, payload = ch.recv()
                meta = dict(meta)
                meta["_wire_bytes"] = ch.bytes_received - before
                meta["_generation"] = generation
                done = kind == DONE_KIND
                self._q.put((kind, actor_id, meta, payload))
            # Retire the channel at ``done``: the publisher must stop sending to
            # a finished actor (a publish RSTing its draining socket is harmless,
            # but pointless) and closing here gives its drain loop prompt EOF.
        except (ChannelClosed, FramingError, TimeoutError):
            pass
        finally:
            was_current = False
            if actor_id is not None:
                with self._lock:
                    if self._channels.get(actor_id) is ch:
                        del self._channels[actor_id]
                        was_current = True
            with self._lock:
                self._bytes_drained += ch.bytes_received
            ch.close()
            if was_current and not done and not self._stop.is_set():
                self.record(actor_id, generation, "closed")
                self._q.put(("closed", actor_id, {"generation": generation}, None))

    def close(self) -> None:
        self._stop.set()
        self._listener.close()
        for ch in self.channels():
            ch.close()


# ------------------------------------------------------------------- utilities
class _StatsCollector:
    """Duck-typed aggregator for ``record_episode_stats``: captures the
    (name, value) updates so an actor can ship episode stats in block meta
    instead of owning a metrics pipeline."""

    def __init__(self) -> None:
        self.pairs: List[List[Any]] = []

    def update(self, name: str, value: Any) -> None:
        self.pairs.append([name, float(value)])

    def drain(self) -> List[List[Any]]:
        pairs, self.pairs = self.pairs, []
        return pairs


def _stamp_of(meta: Dict[str, Any]) -> Dict[str, Any]:
    """Consumer-side stamp: the pinned ``{seq, grad_step, policy_step}`` plus the
    publisher's ``t_pub`` lineage timestamp riding separately in transport meta."""
    stamp = dict(meta.get("stamp") or {})
    if meta.get("t_pub") is not None:
        stamp["t_pub"] = float(meta["t_pub"])
    return stamp


def _freshest(
    latest: Optional[Tuple[Any, Dict[str, Any]]], candidate: Tuple[Any, Dict[str, Any]]
) -> Tuple[Any, Dict[str, Any]]:
    """Max-seq wins, not last-arrived: publisher sends are lock-free, so a
    welcome publish can overtake a newer broadcast on the wire — applying it
    would regress params."""
    if latest is None or int(candidate[1].get("seq", 0)) >= int(latest[1].get("seq", 0)):
        return candidate
    return latest


def _pickup_params(ch: Channel, latest: Optional[Tuple[Any, Dict[str, Any]]]):
    """Drain every pending publish, keep only the freshest (actors may skip
    publishes, never act on older-than-latest params)."""
    while ch.poll(0):
        kind, meta, payload = ch.recv()
        if kind == PARAMS_KIND:
            latest = _freshest(latest, (payload, _stamp_of(meta)))
    return latest


def _await_params(ch: Channel, last_seq: int, timeout_s: float):
    """PPO lockstep: block until a publish NEWER than ``last_seq`` arrives, then
    drain to the freshest (one publish per consumed block keeps this 1:1 with
    the thread path's blocking ``param_q.get``)."""
    deadline = time.monotonic() + timeout_s
    latest: Optional[Tuple[Any, Dict[str, Any]]] = None
    while latest is None or int(latest[1].get("seq", 0)) <= last_seq:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"no param publish newer than seq={last_seq} within {timeout_s}s")
        kind, meta, payload = ch.recv(timeout=remaining)
        if kind == PARAMS_KIND:
            latest = _freshest(latest, (payload, _stamp_of(meta)))
    return _pickup_params(ch, latest)


#: Set once any summary (success or error) reached disk in this process, so the
#: setup-crash fallback in :func:`run` never clobbers the loop's richer one.
_summary_written = False


def _write_summary(summary: Dict[str, Any]) -> None:
    global _summary_written
    path = os.environ.get(SUMMARY_ENV_VAR)
    if not path:
        return
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(summary, f)
    os.replace(tmp, path)
    _summary_written = True


def _exc_summary(exc: BaseException) -> Dict[str, Any]:
    return {"type": type(exc).__name__, "message": str(exc)[:2000]}


def _actor_observability(cfg, spec: PlacementSpec, log_dir: str, algo: str):
    """Arm the actor-side observability stack (actors historically ran dark —
    only the learner had a TrainingMonitor): a flight recorder whose ring the
    fleet blackbox collects from survivors, a span tracer when ``obs.enabled``
    turns tracing on (the exporter ships its events at close, so this process
    gets a track in the merged Perfetto timeline), and the fleet exporter
    itself.  Returns ``(exporter, tracer)``; both may be ``None``."""
    obs_cfg = dict(cfg.get("obs") or {})
    if bool(obs_cfg.get("flight_recorder", True)) and _flight_recorder.get_active() is None:
        _flight_recorder.install(
            _flight_recorder.FlightRecorder(
                log_dir=log_dir,
                capacity=int(obs_cfg.get("flight_recorder_capacity", 4096)),
                keep_events=int(obs_cfg.get("flight_recorder_keep_events", 512)),
                algo=f"{algo}_sebulba_actor",
                cfg=cfg,
            )
        )
    tracer = None
    if bool(obs_cfg.get("enabled", False)) and bool(obs_cfg.get("trace", True)):
        tracer = _tracer.SpanTracer(rank=0, max_events=int(obs_cfg.get("max_events", 100_000)))
        _tracer.set_active(tracer)
    exporter = maybe_exporter(
        cfg, "actor", actor_id=spec.actor_id, generation=spec.generation, log_dir=log_dir
    )
    return exporter, tracer


def _actor_obs_teardown(exporter, tracer) -> None:
    """Ship the trace (exporter close does it while the tracer is still active),
    then restore tracer state.  Never raises — actor teardown already has
    channel/env cleanup to finish."""
    try:
        if exporter is not None:
            exporter.close()
    except Exception:
        pass
    if tracer is not None and _tracer.get_active() is tracer:
        _tracer.set_active(None)


def _note_param_apply(exporter, stamp: Dict[str, Any], policy_step: int) -> None:
    """Staleness lineage: the consumer folds the publisher's transport-meta
    ``t_pub`` into publish→apply latency, making a publish traceable from
    learner emit to actor apply (the flight-recorder event joins the two rings
    in a fleet blackbox bundle)."""
    apply_ms = None
    if stamp.get("t_pub") is not None:
        apply_ms = max((time.time() - float(stamp["t_pub"])) * 1000.0, 0.0)
    _flight_recorder.record_event(
        "param_apply", seq=stamp.get("seq"), grad_step=stamp.get("grad_step"), apply_ms=apply_ms
    )
    if exporter is None:
        return
    exporter.gauge("Sebulba/publish_seq_applied", stamp.get("seq"))
    exporter.gauge("Sebulba/publish_apply_ms", apply_ms)
    staleness = staleness_steps(stamp, policy_step)
    if staleness is not None:
        exporter.gauge("Sebulba/param_staleness_steps", staleness)


class _SlotAccounting:
    """Monotonic global env-step counter across actor generations: each slot
    reports its own cumulative steps; a closed slot's latest count folds into a
    base offset so the respawn (restarting at zero) never moves the total
    backwards."""

    def __init__(self) -> None:
        self._latest: Dict[int, int] = {}
        self._offset = 0

    def report(self, actor_id: int, env_steps: int) -> None:
        self._latest[actor_id] = max(self._latest.get(actor_id, 0), int(env_steps))

    def fold(self, actor_id: int) -> None:
        self._offset += self._latest.pop(actor_id, 0)

    @property
    def total(self) -> int:
        return self._offset + sum(self._latest.values())


# ------------------------------------------------------------------ SAC: actor
def _run_sac_actor(ctx, cfg, spec: PlacementSpec) -> None:
    import jax

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.utils import prepare_obs
    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.fault import chaos
    from sheeprl_tpu.utils.env import make_vector_env
    from sheeprl_tpu.utils.logger import get_log_dir
    from sheeprl_tpu.utils.metric import record_episode_stats
    from sheeprl_tpu.utils.utils import Ratio

    actor_id = spec.actor_id
    log_dir = get_log_dir(cfg)
    fleet_exporter, actor_tracer = _actor_observability(cfg, spec, log_dir, "sac")
    shard_pool_cfg(cfg, spec.num_actors, actor_id)
    envs = make_vector_env(cfg, cfg.seed, actor_id, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    act_low, act_high = act_space.low, act_space.high
    rescale = np.isfinite(act_low).all() and np.isfinite(act_high).all()

    # Same seed -> same ctx.rng() chain -> bit-identical initial params as the
    # learner built; the first publish only has to arrive before they diverge.
    actor_net, _, params = build_agent(ctx, act_space, obs_space, cfg)
    local_actor_params = params["actor"]

    num_envs = cfg.env.num_envs
    num_actors = spec.num_actors
    rb = ReplayBuffer(
        max(int(cfg.buffer.size) // max(num_envs * num_actors, 1), 1),
        num_envs,
        obs_keys=mlp_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{actor_id}")
        if cfg.buffer.memmap
        else None,
    )
    rb.seed(cfg.seed + actor_id)
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    batch_size = cfg.algo.per_rank_batch_size
    stats = _StatsCollector()

    @jax.jit
    def act_fn(p, obs, key):
        mean, log_std = actor_net.apply(p, obs)
        dist = actor_net.dist(mean, log_std)
        return dist.sample(key)

    # num_actors plays exactly the role jax.process_count() plays in the thread
    # path: per-iter global step increment, learning-starts conversion, and the
    # replay-ratio normalization all divide by the acting world size.
    policy_steps_per_iter = num_envs * num_actors
    total_steps = int(cfg.algo.total_steps)
    num_iters = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_iters = max(learning_starts - 1, 0)

    ch = connect(spec.host, spec.port, spec.connect_timeout_s)
    try:
        ch.send(HELLO_KIND, None, actor_id=actor_id, generation=spec.generation, algo="sac")
        key = jax.random.PRNGKey(cfg.seed + 10_000 + actor_id)
        latest: Optional[Tuple[Any, Dict[str, Any]]] = None
        stamp: Dict[str, Any] = {}
        policy_step = 0
        obs, _ = envs.reset(seed=cfg.seed + actor_id)
        step_data: Dict[str, np.ndarray] = {}
        for iter_num in range(1, num_iters + 1):
            chaos.maybe_actor_fault(actor_id, spec.generation, iter_num)
            picked = _pickup_params(ch, latest)
            if picked is not latest and picked is not None:
                latest = picked
                local_actor_params, stamp = jax.device_put(picked[0]["actor"]), picked[1]
                _note_param_apply(fleet_exporter, stamp, policy_step)
            env_t0 = time.perf_counter()
            if iter_num <= learning_starts:
                actions = np.stack([act_space.sample() for _ in range(num_envs)])
                tanh_actions = (
                    2 * (actions - act_low) / (act_high - act_low) - 1 if rescale else actions
                )
            else:
                key, sub = jax.random.split(key)
                obs_t = prepare_obs(obs, mlp_keys)
                tanh_actions = np.asarray(jax.device_get(act_fn(local_actor_params, obs_t, sub)))
                actions = (
                    act_low + (tanh_actions + 1) * 0.5 * (act_high - act_low)
                    if rescale
                    else tanh_actions
                )
            with _tracer.span("Time/env_interaction"):
                next_obs, reward, terminated, truncated, info = envs.step(actions)
            done = np.logical_or(terminated, truncated)

            real_next = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
            if done.any() and "final_obs" in info:
                for i in np.nonzero(done)[0]:
                    if info["final_obs"][i] is not None:
                        for k in mlp_keys:
                            real_next[k][i] = np.asarray(info["final_obs"][i][k])

            for k in mlp_keys:
                step_data[k] = np.asarray(obs[k])[None]
                step_data[f"next_{k}"] = real_next[k][None]
            step_data["actions"] = tanh_actions.astype(np.float32)[None]
            step_data["rewards"] = np.asarray(reward, dtype=np.float32).reshape(num_envs, 1)[None]
            step_data["dones"] = terminated.astype(np.float32).reshape(num_envs, 1)[None]
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
            obs = next_obs
            policy_step += policy_steps_per_iter
            record_episode_stats(stats, info)
            env_time = time.perf_counter() - env_t0

            grad_steps = 0
            batches = None
            if iter_num >= learning_starts:
                grad_steps = ratio(
                    (policy_step - prefill_iters * policy_steps_per_iter) / num_actors
                )
                if grad_steps > 0:
                    sample = rb.sample(batch_size * grad_steps)
                    batches = {
                        "obs": np.concatenate(
                            [sample[k].reshape(grad_steps, batch_size, -1) for k in mlp_keys], -1
                        ),
                        "next_obs": np.concatenate(
                            [sample[f"next_{k}"].reshape(grad_steps, batch_size, -1) for k in mlp_keys],
                            -1,
                        ),
                        "actions": sample["actions"].reshape(grad_steps, batch_size, -1),
                        "rewards": sample["rewards"].reshape(grad_steps, batch_size, 1),
                        "dones": sample["dones"].reshape(grad_steps, batch_size, 1),
                    }
            with _tracer.span("Time/block_send"):
                ch.send(
                    BLOCK_KIND,
                    {"batches": batches},
                    iter_num=iter_num,
                    grad_steps=grad_steps,
                    policy_step=policy_step,
                    env_time=env_time,
                    env_steps=iter_num * num_envs,
                    staleness=staleness_steps(stamp, policy_step),
                    stats=stats.drain(),
                )
            if fleet_exporter is not None:
                fleet_exporter.counter("env_steps", iter_num * num_envs)
                fleet_exporter.counter("blocks", iter_num)
                fleet_exporter.counter("bytes_sent", ch.bytes_sent)
                fleet_exporter.gauge("policy_step", policy_step)
        ch.send(DONE_KIND, None, env_steps=num_iters * num_envs)
        ch.drain_until_closed(spec.connect_timeout_s)
    finally:
        _actor_obs_teardown(fleet_exporter, actor_tracer)
        ch.close()
        envs.close()


# ---------------------------------------------------------------- SAC: learner
def _run_sac_learner(ctx, cfg, spec: PlacementSpec) -> None:
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_train_fn
    from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS
    from sheeprl_tpu.analysis.strict import assert_finite, strict_guard
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import save_config
    from sheeprl_tpu.fault.guard import TrainingGuard
    from sheeprl_tpu.obs import TrainingMonitor
    from sheeprl_tpu.utils.logger import get_log_dir, get_logger
    from sheeprl_tpu.utils.metric import MetricAggregator

    log_dir = get_log_dir(cfg)
    save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)
    fleet_exporter = maybe_exporter(cfg, "learner", generation=spec.generation, log_dir=log_dir)

    obs_space, act_space = _probe_spaces(cfg)
    actor_net, critic, params = build_agent(ctx, act_space, obs_space, cfg)
    actor_opt, critic_opt, alpha_opt, train_fn = make_sac_train_fn(actor_net, critic, cfg, act_space)
    train_fn = obs_perf.instrument(cfg, "sac_sebulba/train_fn", strict_guard(cfg, "sac_sebulba/train_fn", train_fn))
    opt_state = ctx.replicate(
        {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | SEBULBA_METRIC_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    def train_block(meta, payload, cumulative_grad_steps):
        grad_steps = int(meta["grad_steps"])
        if grad_steps <= 0 or payload.get("batches") is None:
            return 0, 0.0
        maybe_digest(f"sac:{int(meta['iter_num'])}", payload["batches"])
        batches = ctx.put_batch(payload["batches"], batch_axis=1)
        key = ctx.rng()
        t0 = time.perf_counter()
        nonlocal_params[0], nonlocal_opt[0], train_metrics = train_fn(
            nonlocal_params[0], nonlocal_opt[0], batches, key, jnp.asarray(cumulative_grad_steps)
        )
        train_metrics = jax.device_get(train_metrics)
        assert_finite(cfg, train_metrics, "sac_sebulba/update")
        for k, v in train_metrics.items():
            aggregator.update(k, float(v))
        return grad_steps, time.perf_counter() - t0

    nonlocal_params = [params]
    nonlocal_opt = [opt_state]

    def publish(publisher, cumulative_grad_steps, policy_step):
        # SAC actors only act — publish the actor net alone (a fraction of the
        # full params+critic+targets tree on the wire).
        publisher.publish(
            {"actor": nonlocal_params[0]["actor"]},
            grad_step=cumulative_grad_steps,
            policy_step=policy_step,
        )

    def save_state(policy_step, cumulative_grad_steps, blocks):
        return {
            "params": nonlocal_params[0],
            "opt_state": nonlocal_opt[0],
            "iter_num": blocks,
            "policy_step": policy_step,
            "cumulative_grad_steps": cumulative_grad_steps,
        }

    _learner_loop(
        cfg,
        spec,
        logger=logger,
        monitor=monitor,
        aggregator=aggregator,
        ckpt_manager=ckpt_manager,
        guard=guard,
        train_block=train_block,
        publish=publish,
        save_state=save_state,
        sps_env_steps=cfg.env.num_envs,
        fleet_exporter=fleet_exporter,
    )


# ------------------------------------------------------------------ PPO: actor
def _run_ppo_actor(ctx, cfg, spec: PlacementSpec) -> None:
    import jax

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.algos.ppo.utils import prepare_obs
    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.fault import chaos
    from sheeprl_tpu.utils.env import make_vector_env
    from sheeprl_tpu.utils.logger import get_log_dir
    from sheeprl_tpu.utils.metric import record_episode_stats

    actor_id = spec.actor_id
    log_dir = get_log_dir(cfg)
    fleet_exporter, actor_tracer = _actor_observability(cfg, spec, log_dir, "ppo")
    shard_pool_cfg(cfg, spec.num_actors, actor_id)
    envs = make_vector_env(cfg, cfg.seed, actor_id, log_dir if cfg.env.capture_video else None)
    obs_space = envs.single_observation_space
    act_space = envs.single_action_space
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    is_continuous = agent.is_continuous

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    num_actors = spec.num_actors
    policy_steps_per_iter = int(num_envs * rollout_steps * num_actors)
    total_steps = int(cfg.algo.total_steps)
    num_updates = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    # The actor only needs the jitted policy/value calls + GAE from the bundle.
    fns = PPOTrainFns(ctx, agent, cfg, obs_keys, num_updates)
    act_fn, values_fn, gae_fn, batch_n = fns.act_fn, fns.values_fn, fns.gae_fn, fns.batch_n
    gamma = cfg.algo.gamma
    stats = _StatsCollector()

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{actor_id}")
        if cfg.buffer.memmap
        else None,
    )
    rb.seed(cfg.seed + actor_id)

    ch = connect(spec.host, spec.port, spec.connect_timeout_s)
    try:
        ch.send(HELLO_KIND, None, actor_id=actor_id, generation=spec.generation, algo="ppo")
        key = jax.random.PRNGKey(cfg.seed + 10_000 + actor_id)
        local_params = params
        stamp: Dict[str, Any] = {}
        last_seq = 0
        policy_step = 0
        obs, _ = envs.reset(seed=cfg.seed + actor_id)
        step_data: Dict[str, np.ndarray] = {}
        for update in range(1, num_updates + 1):
            chaos.maybe_actor_fault(actor_id, spec.generation, update)
            env_t0 = time.perf_counter()
            for _ in range(rollout_steps):
                key, sub = jax.random.split(key)
                obs_t = prepare_obs(obs, cnn_keys, mlp_keys)
                env_act, stored_act, logprob, value = act_fn(local_params, obs_t, sub)
                env_act_np = np.asarray(jax.device_get(env_act))
                if is_continuous:
                    low, high = act_space.low, act_space.high
                    env_actions = (
                        np.clip(env_act_np, low, high) if np.isfinite(low).all() else env_act_np
                    )
                elif len(agent.action_dims) == 1:
                    env_actions = env_act_np[..., 0]
                else:
                    env_actions = env_act_np
                with _tracer.span("Time/env_interaction"):
                    next_obs, reward, terminated, truncated, info = envs.step(env_actions)
                if cfg.env.clip_rewards:
                    reward = np.clip(reward, -1, 1)
                done = np.logical_or(terminated, truncated)
                reward = np.asarray(reward, dtype=np.float32).reshape(num_envs)

                if truncated.any() and "final_obs" in info:
                    trunc_idx = np.nonzero(truncated)[0]
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][i][k]) for i in trunc_idx])
                        for k in obs_keys
                    }
                    v_final = np.asarray(
                        jax.device_get(values_fn(local_params, prepare_obs(final_obs, cnn_keys, mlp_keys)))
                    )
                    reward[trunc_idx] += gamma * v_final

                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[None]
                step_data["actions"] = env_act_np.reshape(num_envs, -1).astype(np.float32)[None]
                step_data["logprobs"] = np.asarray(jax.device_get(logprob)).reshape(num_envs, 1)[None]
                step_data["values"] = np.asarray(jax.device_get(value)).reshape(num_envs, 1)[None]
                step_data["rewards"] = reward.reshape(num_envs, 1)[None]
                step_data["dones"] = done.astype(np.float32).reshape(num_envs, 1)[None]
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                obs = next_obs
                policy_step += num_envs * num_actors
                record_episode_stats(stats, info)
            env_time = time.perf_counter() - env_t0

            local = rb.to_tensor()
            next_value = values_fn(local_params, prepare_obs(obs, cnn_keys, mlp_keys))[:, None]
            returns, advantages = gae_fn(local["rewards"], local["values"], local["dones"], next_value)
            data = {
                **{k: local[k] for k in obs_keys},
                "actions": local["actions"],
                "logprobs": local["logprobs"][..., 0],
                "values": local["values"][..., 0],
                "returns": returns[..., 0],
                "advantages": advantages[..., 0],
            }
            data = jax.tree.map(lambda x: np.asarray(x).reshape(batch_n, *x.shape[2:]), data)
            with _tracer.span("Time/block_send"):
                ch.send(
                    BLOCK_KIND,
                    {"data": data},
                    update=update,
                    policy_step=policy_step,
                    env_time=env_time,
                    env_steps=update * rollout_steps * num_envs,
                    staleness=staleness_steps(stamp, policy_step),
                    stats=stats.drain(),
                )

            # Lockstep publish pickup (the thread player's blocking param_q.get):
            # this is what makes the 1-actor schedule bit-identical.
            with _tracer.span("Time/param_wait"):
                payload, stamp = _await_params(ch, last_seq, spec.connect_timeout_s)
            last_seq = int(stamp.get("seq", last_seq + 1))
            local_params = jax.device_put(payload)
            _note_param_apply(fleet_exporter, stamp, policy_step)
            if fleet_exporter is not None:
                fleet_exporter.counter("env_steps", update * rollout_steps * num_envs)
                fleet_exporter.counter("blocks", update)
                fleet_exporter.counter("bytes_sent", ch.bytes_sent)
                fleet_exporter.gauge("policy_step", policy_step)
        ch.send(DONE_KIND, None, env_steps=num_updates * rollout_steps * num_envs)
        ch.drain_until_closed(spec.connect_timeout_s)
    finally:
        _actor_obs_teardown(fleet_exporter, actor_tracer)
        ch.close()
        envs.close()


# ---------------------------------------------------------------- PPO: learner
def _run_ppo_learner(ctx, cfg, spec: PlacementSpec) -> None:
    import jax

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import PPOTrainFns
    from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS
    from sheeprl_tpu.analysis.strict import assert_finite, strict_guard
    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import save_config
    from sheeprl_tpu.fault.guard import TrainingGuard
    from sheeprl_tpu.obs import TrainingMonitor
    from sheeprl_tpu.utils.logger import get_log_dir, get_logger
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.utils import polynomial_decay

    log_dir = get_log_dir(cfg)
    save_config(cfg, Path(log_dir) / "config.yaml")
    logger = get_logger(cfg, log_dir)
    monitor = TrainingMonitor(cfg, log_dir)
    fleet_exporter = maybe_exporter(cfg, "learner", generation=spec.generation, log_dir=log_dir)

    obs_space, act_space = _probe_spaces(cfg)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    agent, params = build_agent(ctx, act_space, obs_space, cfg)
    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    policy_steps_per_iter = int(num_envs * rollout_steps * spec.num_actors)
    total_steps = int(cfg.algo.total_steps)
    num_updates = max(total_steps // policy_steps_per_iter, 1) if not cfg.dry_run else 1

    fns = PPOTrainFns(ctx, agent, cfg, obs_keys, num_updates)
    opt_state = ctx.replicate(fns.opt.init(params))
    train_fn = obs_perf.instrument(cfg, "ppo_sebulba/train_fn", strict_guard(cfg, "ppo_sebulba/train_fn", fns.train_fn))
    if cfg.checkpoint.get("resume_from"):
        state = CheckpointManager.load(
            cfg.checkpoint.resume_from,
            templates={"params": jax.device_get(params), "opt_state": jax.device_get(opt_state)},
        )
        params = ctx.replicate(state["params"])
        opt_state = ctx.replicate(state["opt_state"])

    aggregator = MetricAggregator(cfg.metric.aggregator.get("metrics", {}))
    aggregator.keep(AGGREGATOR_KEYS | SEBULBA_METRIC_KEYS | set(cfg.metric.aggregator.get("metrics", {})))
    ckpt_manager = CheckpointManager(Path(log_dir) / "checkpoints", keep_last=cfg.checkpoint.keep_last)
    guard = TrainingGuard(cfg, log_dir)

    nonlocal_params = [params]
    nonlocal_opt = [opt_state]

    def train_block(meta, payload, cumulative_grad_steps):
        update = int(meta["update"])
        maybe_digest(f"ppo:{update}", payload["data"])
        clip_coef = cfg.algo.clip_coef
        ent_coef = cfg.algo.ent_coef
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(update, initial=clip_coef, final=0.0, max_decay_steps=num_updates)
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(update, initial=ent_coef, final=0.0, max_decay_steps=num_updates)
        key = ctx.rng()
        t0 = time.perf_counter()
        nonlocal_params[0], nonlocal_opt[0], train_metrics = train_fn(
            nonlocal_params[0], nonlocal_opt[0], payload["data"], key, clip_coef, ent_coef
        )
        train_metrics = jax.device_get(train_metrics)
        assert_finite(cfg, train_metrics, "ppo_sebulba/update")
        for k, v in train_metrics.items():
            aggregator.update(k, float(v))
        return fns.grad_steps_per_update, time.perf_counter() - t0

    def publish(publisher, cumulative_grad_steps, policy_step):
        publisher.publish(
            nonlocal_params[0], grad_step=cumulative_grad_steps, policy_step=policy_step
        )

    def save_state(policy_step, cumulative_grad_steps, blocks):
        return {
            "params": nonlocal_params[0],
            "opt_state": nonlocal_opt[0],
            "update": blocks,
            "policy_step": policy_step,
        }

    _learner_loop(
        cfg,
        spec,
        logger=logger,
        monitor=monitor,
        aggregator=aggregator,
        ckpt_manager=ckpt_manager,
        guard=guard,
        train_block=train_block,
        publish=publish,
        save_state=save_state,
        sps_env_steps=num_envs * rollout_steps,
        publish_empty_blocks=True,
        fleet_exporter=fleet_exporter,
    )


# -------------------------------------------------------------- learner kernel
def _learner_loop(
    cfg,
    spec: PlacementSpec,
    *,
    logger,
    monitor,
    aggregator,
    ckpt_manager,
    guard,
    train_block,
    publish,
    save_state,
    sps_env_steps: int,
    publish_empty_blocks: bool = False,
    fleet_exporter=None,
) -> None:
    """Algorithm-agnostic learner body: inbox consumption, publishing, metrics,
    checkpoint cadence, lifecycle accounting, and the exit summary.

    ``train_block(meta, payload, cumulative_grad_steps) -> (grad_steps, train_time)``
    runs the jitted update and mutates the closed-over params/opt state;
    ``publish`` broadcasts them; ``save_state`` materializes the checkpoint tree.
    ``publish_empty_blocks`` keeps the PPO lockstep alive on blocks that carry no
    gradient work (SAC prefill blocks skip the publish like the thread path).
    """
    listener = Listener(spec.host, spec.port)
    publisher = ChannelWeightPublisher(lambda: inbox.channels())
    inbox = LearnerInbox(listener, spec, on_connect=publisher.maybe_welcome)

    t_start = time.monotonic()
    done_slots: set = set()
    slots = _SlotAccounting()
    cumulative_grad_steps = 0
    blocks = 0
    policy_step = 0
    last_log = 0
    last_checkpoint = 0
    last_progress = time.monotonic()
    #: [monotonic_t, cumulative_grad_steps] per consumed block — the liveness
    #: trace the actor-kill test asserts strict increase on across the kill window.
    grad_trace: List[List[float]] = []
    idle_timeout_s = max(float(spec.connect_timeout_s) * 5.0, 60.0)

    def save_ckpt():
        nonlocal last_checkpoint
        path = ckpt_manager.save(policy_step, save_state(policy_step, cumulative_grad_steps, blocks))
        last_checkpoint = policy_step
        return path

    error: Optional[Dict[str, Any]] = None
    try:
        while len(done_slots) < spec.num_actors:
            try:
                kind, actor_id, meta, payload = inbox.get(timeout=1.0)
            except queue.Empty:
                if time.monotonic() - last_progress > idle_timeout_s:
                    raise RuntimeError(
                        f"sebulba learner starved: no actor message for {idle_timeout_s:.0f}s "
                        f"({len(done_slots)}/{spec.num_actors} actors done)"
                    )
                continue
            last_progress = time.monotonic()
            if kind == DONE_KIND:
                done_slots.add(actor_id)
                slots.report(actor_id, int(meta.get("env_steps", 0)))
                inbox.record(actor_id, int(meta.get("_generation", 0)), "done")
                continue
            if kind == "closed":
                if actor_id not in done_slots:
                    slots.fold(actor_id)
                continue
            if kind == ABANDON_KIND:
                # The launcher exhausted this slot's respawn budget; stop
                # waiting for it (its env steps stay folded from the close).
                done_slots.add(actor_id)
                inbox.record(actor_id, -1, "abandoned")
                continue
            if kind != BLOCK_KIND:
                continue

            monitor.advance()
            blocks += 1
            policy_step = max(policy_step, int(meta.get("policy_step", 0)))
            slots.report(actor_id, int(meta.get("env_steps", 0)))
            grad_steps, train_time = train_block(meta, payload, cumulative_grad_steps)
            cumulative_grad_steps += grad_steps
            grad_trace.append([time.monotonic(), cumulative_grad_steps])
            if grad_steps > 0 or publish_empty_blocks:
                publish(publisher, cumulative_grad_steps, policy_step)

            for name, value in meta.get("stats") or []:
                aggregator.update(name, value)
            aggregator.update("Sebulba/queue_depth", inbox.qsize())
            if meta.get("staleness") is not None:
                aggregator.update("Sebulba/param_staleness_steps", float(meta["staleness"]))
            aggregator.update("Sebulba/xfer_bytes", float(meta.get("_wire_bytes", 0)))
            aggregator.update(f"Sebulba/xfer_bytes/ch{actor_id}", float(meta.get("_wire_bytes", 0)))

            if fleet_exporter is not None:
                # Dict writes only — the exporter's daemon thread owns the sends.
                fleet_exporter.counter("grad_steps", cumulative_grad_steps)
                fleet_exporter.counter("env_steps", slots.total)
                fleet_exporter.counter("blocks", blocks)
                fleet_exporter.counter("publishes", publisher.seq)
                fleet_exporter.counter("bytes_published", publisher.bytes_published)
                fleet_exporter.gauge("policy_step", policy_step)
                fleet_exporter.gauge("Sebulba/queue_depth", inbox.qsize())
                if meta.get("staleness") is not None:
                    fleet_exporter.gauge("Sebulba/param_staleness_steps", float(meta["staleness"]))

            if logger is not None and (policy_step - last_log >= cfg.metric.log_every or cfg.dry_run):
                metrics = aggregator.compute()
                aggregator.reset()
                if train_time > 0:
                    metrics["Time/sps_train"] = grad_steps / train_time
                env_time = float(meta.get("env_time", 0) or 0)
                if env_time > 0:
                    metrics["Time/sps_env_interaction"] = sps_env_steps / env_time
                monitor.log_metrics(logger, metrics, policy_step)
                last_log = policy_step

            if cfg.checkpoint.every > 0 and (policy_step - last_checkpoint) >= cfg.checkpoint.every:
                save_ckpt()
            guard.boundary(policy_step, save_ckpt)

        if cfg.checkpoint.save_last:
            save_ckpt()
    except BaseException as exc:
        # A crashing learner must still leave its summary behind: the grad-step
        # trace and lifecycle events are exactly what the chaos tests and
        # sebulba_bench.py need to diagnose the death (satellite of this PR —
        # previously only the happy path wrote it).
        error = _exc_summary(exc)
        raise
    finally:
        bytes_received = inbox.bytes_received()
        if fleet_exporter is not None:
            try:
                # Before monitor.close(): the exporter ships the tracer's spans
                # for the merged fleet Perfetto file, and close() deactivates it.
                fleet_exporter.close()
            except Exception:
                pass
        inbox.close()
        try:
            # monitor.close() can itself raise (strict mode drains pending NaN
            # trips there) — the summary write may not depend on it surviving.
            monitor.close()
        except BaseException as exc:
            if error is None:
                error = _exc_summary(exc)
            raise
        finally:
            _write_summary(
                {
                    "wall_time_s": time.monotonic() - t_start,
                    "blocks": blocks,
                    "cumulative_grad_steps": cumulative_grad_steps,
                    "env_steps_total": slots.total,
                    "policy_step": policy_step,
                    "bytes_received": bytes_received,
                    "bytes_published": publisher.bytes_published,
                    "publishes": publisher.seq,
                    "grad_step_trace": grad_trace,
                    "events": inbox.events,
                    "t_start": t_start,
                    "error": error,
                }
            )
    if logger is not None:
        logger.close()


def _probe_spaces(cfg):
    """The learner never steps envs; build ONE wrapped env to read the spaces the
    agent builder needs, then tear it down (same thunk as the actors' env 0, so
    the spaces — and thus the built params — match bit-for-bit)."""
    from sheeprl_tpu.utils.env import make_env

    probe = make_env(cfg, cfg.seed, 0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    return obs_space, act_space


# ----------------------------------------------------------------------- entry
_RUNNERS = {
    ("sac", "learner"): _run_sac_learner,
    ("sac", "actor"): _run_sac_actor,
    ("ppo", "learner"): _run_ppo_learner,
    ("ppo", "actor"): _run_ppo_actor,
}


def run(ctx, cfg, spec: PlacementSpec, algo: str) -> None:
    """Role dispatch for a Sebulba child process (called from the decoupled
    algorithm ``main``s when ``distributed.mode=sebulba``)."""
    key = (algo, spec.role)
    if key not in _RUNNERS:
        raise ValueError(f"no sebulba runner for algo={algo!r} role={spec.role!r}")
    try:
        _RUNNERS[key](ctx, cfg, spec)
    except BaseException as exc:
        # Learner crashes BEFORE _learner_loop (agent build, checkpoint resume,
        # space probe) never reach the loop's summary-writing finally; leave a
        # minimal error summary so the launcher/bench still learn what happened.
        if spec.is_learner and not _summary_written:
            _write_summary(
                {
                    "wall_time_s": 0.0,
                    "blocks": 0,
                    "cumulative_grad_steps": 0,
                    "env_steps_total": 0,
                    "policy_step": 0,
                    "bytes_received": 0,
                    "bytes_published": 0,
                    "publishes": 0,
                    "grad_step_trace": [],
                    "events": [],
                    "t_start": time.monotonic(),
                    "error": _exc_summary(exc),
                }
            )
        raise
