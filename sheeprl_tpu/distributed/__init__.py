"""Sebulba placed-process topology: launcher, placement spec, transport, and the
actor/learner process bodies for the decoupled algorithms (Podracer, arXiv
2104.06272 §3; howto/sebulba.md).

Import discipline: ``transport`` and ``placement`` are stdlib+numpy only so actor
tooling and tests can use them without touching JAX; the heavy role bodies live
in ``sebulba`` and import their algorithm modules lazily.
"""

from sheeprl_tpu.distributed.placement import PlacementSpec, placement_from_cfg
from sheeprl_tpu.distributed.transport import (
    Channel,
    ChannelClosed,
    FramingError,
    Listener,
    connect,
    maybe_digest,
    tree_digest,
)

__all__ = [
    "Channel",
    "ChannelClosed",
    "FramingError",
    "Listener",
    "PlacementSpec",
    "connect",
    "maybe_digest",
    "placement_from_cfg",
    "tree_digest",
]
