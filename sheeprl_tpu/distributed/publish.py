"""Weight publishing: how fresh learner params reach the actors.

The contract (howto/sebulba.md) has three parts:

1. **Freshest wins.** A publish never queues behind an older one — the stale
   entry is evicted and the new one takes its slot (the thread path's
   ``param_q`` does the same).  Actors may *skip* publishes, never act on
   older-than-latest params.
2. **Stamped.** Every publish carries ``{seq, grad_step, policy_step}`` so the
   consumer can log ``Sebulba/param_staleness_steps`` — the policy-step gap
   between the params it acts with and the data the learner trained them on.
3. **No per-publish host round-trip when a device path exists.** Where the
   actor's device is addressable from the learner process (threads on one
   host's chips; a shared-mesh placement), the publish is one
   ``jax.device_put`` device-to-device — asserted under
   ``jax.transfer_guard_device_to_host("disallow")`` in the tests.  Where it is
   not (separate CPU processes — this host), the documented fallback is ONE
   ``jax.device_get`` per publish, wired straight into the transport channel;
   the bytes show up in ``Sebulba/xfer_bytes``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from sheeprl_tpu.distributed.transport import Channel, ChannelClosed

#: Message kind carrying a stamped parameter block on the wire.
PARAMS_KIND = "params"


def make_stamp(seq: int, grad_step: int, policy_step: int) -> Dict[str, int]:
    return {"seq": int(seq), "grad_step": int(grad_step), "policy_step": int(policy_step)}


def staleness_steps(stamp: Optional[Dict[str, Any]], policy_step: int) -> Optional[int]:
    """Policy-step age of ``stamp``-ed params at the consumer's ``policy_step``."""
    if not stamp:
        return None
    return max(int(policy_step) - int(stamp.get("policy_step", policy_step)), 0)


def evict_and_put(q: "queue.Queue", item: Any) -> int:
    """Freshest-wins publish into a bounded queue: drop stale entries, never block.

    Returns how many stale publishes were evicted (0 on the happy path).  This is
    the in-process analogue of the channel publisher and the one true way to feed
    ``param_q`` — a plain ``put_nowait`` with ``except queue.Full: pass`` silently
    keeps the OLD params, which is exactly the staleness bug this fixes."""
    evicted = 0
    while True:
        try:
            q.put_nowait(item)
            return evicted
        except queue.Full:
            try:
                q.get_nowait()
                evicted += 1
            except queue.Empty:
                pass


class DeviceWeightPublisher:
    """Device-path publisher: ``jax.device_put`` onto the consumer's device(s).

    No host round-trip — under ``jax.transfer_guard_device_to_host("disallow")``
    every publish still succeeds (device-to-device transfers are allowed; a
    ``device_get`` would raise).  ``sink`` receives the stamped placement, e.g.
    ``lambda item: evict_and_put(param_q, item)``.
    """

    def __init__(self, sink: Callable[[Tuple[Any, Dict[str, int]]], Any], device: Any = None):
        self._sink = sink
        self._device = device
        self.seq = 0
        self.bytes_published = 0

    def publish(self, params: Any, *, grad_step: int, policy_step: int) -> Dict[str, int]:
        import jax

        self.seq += 1
        stamp = make_stamp(self.seq, grad_step, policy_step)
        placed = jax.device_put(params, self._device) if self._device is not None else params
        self.bytes_published += sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(placed)
        )
        self._sink((placed, stamp))
        return stamp


class ChannelWeightPublisher:
    """Host-fallback publisher: one ``device_get`` per publish, fanned out to every
    live actor channel.  The single ``device_get`` is the whole documented CPU
    cost; per-channel sends reuse its result (no per-actor re-fetch)."""

    def __init__(self, channels: Callable[[], Iterable[Channel]]):
        self._channels = channels
        self._lock = threading.Lock()
        self._last: Optional[Tuple[Any, Dict[str, int]]] = None
        self.seq = 0
        self.bytes_published = 0

    def publish(self, params: Any, *, grad_step: int, policy_step: int) -> Dict[str, int]:
        import jax

        # THE one host round-trip — outside the lock: device_get parks the
        # thread until device work drains, and nothing it reads is shared.
        host_params = jax.device_get(params)
        with self._lock:
            self.seq += 1
            stamp = make_stamp(self.seq, grad_step, policy_step)
            self._last = (host_params, stamp)
            channels = list(self._channels())
        # Sends are lock-free (JL010): a backpressured actor socket must not
        # convoy maybe_welcome() callers on the inbox accept thread.  Wire
        # order between racing sends is therefore unguaranteed — the consumer
        # keeps the max-seq publish (sebulba ``_pickup_params``), so an
        # overtaken older send is skipped, never applied.
        #
        # t_pub rides transport meta, NOT the stamp: the stamp's
        # {seq, grad_step, policy_step} shape is a pinned contract, while
        # t_pub is fleet-telemetry lineage (publish→apply latency) that the
        # consumer folds into its local copy of the stamp.
        sent = 0
        for ch in channels:
            try:
                sent += ch.send(PARAMS_KIND, host_params, stamp=stamp, t_pub=time.time())
            except ChannelClosed:
                pass  # dead actor: its respawn gets a welcome publish instead
        if sent:
            with self._lock:
                self.bytes_published += sent
        return stamp

    def maybe_welcome(self, ch: Channel) -> None:
        """Seed one just-connected actor with the latest already-fetched params —
        a respawned actor must not act on init-time params when trained ones
        exist.  No-op before the first publish (every actor builds bit-identical
        init params from the shared seed)."""
        with self._lock:
            last = self._last
        if last is None:
            return
        host_params, stamp = last
        try:
            sent = ch.send(PARAMS_KIND, host_params, stamp=stamp, t_pub=time.time())
        except ChannelClosed:
            return
        with self._lock:
            self.bytes_published += sent
