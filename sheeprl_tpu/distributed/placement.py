"""Placement spec: which Sebulba role this process plays, and where its peers are.

Podracer's Sebulba topology (arXiv 2104.06272 §3) is a *placement*: one learner
process owning the training mesh, N actor processes owning env shards, and typed
channels between them.  This module is the single source of truth for that
placement — the launcher composes it from the ``distributed`` config group and
stamps each child with role/actor_id overrides; hand-started processes (or the
MULTICHIP dryrun) can instead set the ``SHEEPRL_TPU_SEBULBA_*`` env vars, which
take precedence so one spawn path serves both.

The generation counter rides an env var rather than the config: it changes on
every respawn, and keeping it out of the composed config keeps the child's
config (and thus its compilation-cache keys) identical across respawns.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

ROLE_LAUNCHER = "launcher"
ROLE_LEARNER = "learner"
ROLE_ACTOR = "actor"
_ROLES = (ROLE_LAUNCHER, ROLE_LEARNER, ROLE_ACTOR)

_PUBLISH_MODES = ("auto", "device", "host")

#: Env-var overrides: the launcher sets GENERATION on respawned actors; all of
#: them let a hand-started process join a placement without config surgery.
ROLE_ENV_VAR = "SHEEPRL_TPU_SEBULBA_ROLE"
ACTOR_ID_ENV_VAR = "SHEEPRL_TPU_SEBULBA_ACTOR_ID"
HOST_ENV_VAR = "SHEEPRL_TPU_SEBULBA_HOST"
PORT_ENV_VAR = "SHEEPRL_TPU_SEBULBA_PORT"
GENERATION_ENV_VAR = "SHEEPRL_TPU_ACTOR_GENERATION"

#: Learner-side summary JSON (grad-step trace, per-channel byte counters) —
#: written at exit when set; the actor-kill test reads it to pin liveness.
SUMMARY_ENV_VAR = "SHEEPRL_TPU_SEBULBA_SUMMARY"


def _dist_cfg(cfg: Any) -> Dict[str, Any]:
    try:
        section = cfg.get("distributed") if hasattr(cfg, "get") else getattr(cfg, "distributed", None)
    except Exception:
        section = None
    return dict(section) if section else {}


@dataclass(frozen=True)
class PlacementSpec:
    """One process's view of the Sebulba placement."""

    mode: str = "thread"
    role: str = ROLE_LAUNCHER
    num_actors: int = 1
    host: str = "127.0.0.1"
    port: int = 0
    actor_id: int = 0
    generation: int = 0
    connect_timeout_s: float = 60.0
    publish: str = "auto"
    queue_depth: int = 2
    respawn: bool = True
    respawn_backoff_s: float = 0.5
    max_actor_respawns: int = 3

    def __post_init__(self) -> None:
        if self.role not in _ROLES:
            raise ValueError(f"distributed.role must be one of {_ROLES}; got {self.role!r}")
        if self.publish not in _PUBLISH_MODES:
            raise ValueError(f"distributed.publish must be one of {_PUBLISH_MODES}; got {self.publish!r}")
        if self.num_actors < 1:
            raise ValueError(f"distributed.num_actors must be >= 1; got {self.num_actors}")
        if not (0 <= self.actor_id < self.num_actors):
            raise ValueError(
                f"distributed.actor_id={self.actor_id} out of range for num_actors={self.num_actors}"
            )
        if self.queue_depth < 1:
            raise ValueError(f"distributed.queue_depth must be >= 1; got {self.queue_depth}")

    @property
    def is_sebulba(self) -> bool:
        return self.mode == "sebulba"

    @property
    def is_learner(self) -> bool:
        return self.role == ROLE_LEARNER

    @property
    def is_actor(self) -> bool:
        return self.role == ROLE_ACTOR

    def child_overrides(self, role: str, port: int, actor_id: int = 0) -> list:
        """CLI overrides the launcher appends when spawning this child role."""
        ovs = [
            "distributed.mode=sebulba",
            f"distributed.role={role}",
            f"distributed.port={port}",
            f"distributed.host={self.host}",
            f"distributed.num_actors={self.num_actors}",
        ]
        if role == ROLE_ACTOR:
            ovs.append(f"distributed.actor_id={actor_id}")
        return ovs


def placement_from_cfg(cfg: Any, env: Optional[Dict[str, str]] = None) -> PlacementSpec:
    """Build the spec from the ``distributed`` config group + env-var overrides."""
    env = os.environ if env is None else env
    dist = _dist_cfg(cfg)

    def pick(env_var: str, key: str, default: Any, cast) -> Any:
        if env_var and env.get(env_var) not in (None, ""):
            return cast(env[env_var])
        value = dist.get(key, default)
        return default if value is None else cast(value)

    return PlacementSpec(
        mode=str(dist.get("mode", "thread") or "thread"),
        role=pick(ROLE_ENV_VAR, "role", ROLE_LAUNCHER, str),
        num_actors=pick("", "num_actors", 1, int),
        host=pick(HOST_ENV_VAR, "host", "127.0.0.1", str),
        port=pick(PORT_ENV_VAR, "port", 0, int),
        actor_id=pick(ACTOR_ID_ENV_VAR, "actor_id", 0, int),
        generation=int(env.get(GENERATION_ENV_VAR, 0) or 0),
        connect_timeout_s=pick("", "connect_timeout_s", 60.0, float),
        publish=str(dist.get("publish", "auto") or "auto"),
        queue_depth=pick("", "queue_depth", 2, int),
        respawn=bool(dist.get("respawn", True)),
        respawn_backoff_s=pick("", "respawn_backoff_s", 0.5, float),
        max_actor_respawns=pick("", "max_actor_respawns", 3, int),
    )
