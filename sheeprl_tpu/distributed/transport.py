"""Sebulba transport: framed array messages over TCP sockets.

The Podracer/Sebulba split (arXiv 2104.06272 §3) needs exactly one dataflow
primitive: a *typed block channel* between placed processes — actor hosts stream
transition blocks to the learner, the learner broadcasts parameter blocks back.
MindSpeed RL (arXiv 2507.19017) calls the same thing a "transfer channel": an
explicit, metered edge in the dataflow graph instead of an implicit host
round-trip hidden inside a framework collective.

This module is that primitive, deliberately boring:

* **Framing** — every message is ``MAGIC | u32 header_len | header JSON | raw
  array bytes``.  The header carries the message ``kind``, a small JSON ``meta``
  dict, and the payload *structure*: a nested dict/list skeleton in which numpy
  arrays are replaced by ``{"__nd__": i}`` placeholders describing dtype/shape.
  Arrays travel as raw bytes after the header — no pickling, so a block's wire
  size is its array size plus a few hundred header bytes, and the decode is a
  zero-copy ``np.frombuffer`` per leaf.
* **Channel** — a connected socket with thread-safe ``send`` and blocking /
  timeout / non-blocking ``recv``; byte counters feed the ``Sebulba/xfer_bytes``
  metric.
* **Listener / connect** — learner-side accept loop and actor-side
  connect-with-retry, so process start order never matters.

Import cost is stdlib + numpy only: actor processes poll this before JAX is
even touched, and transport unit tests run without compiling anything.
"""

from __future__ import annotations

import hashlib
import json
import os
import select
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"SBLB"
_HEADER_FMT = "!4sI"  # magic, header length
_HEADER_SIZE = struct.calcsize(_HEADER_FMT)

#: Messages larger than this are rejected at decode time (corrupt frame guard).
MAX_HEADER_BYTES = 16 * 1024 * 1024


class ChannelClosed(ConnectionError):
    """The peer closed the connection (process exit, SIGKILL, network death).

    Sebulba treats this as a *routine* event, not an error: a killed actor's
    channel closes, the learner keeps consuming the surviving channels, and the
    launcher respawns the actor, which reconnects on a fresh channel."""


class FramingError(RuntimeError):
    """The byte stream is not a valid frame (bad magic / oversize header)."""


# --------------------------------------------------------------------------- codec
def encode_tree(tree: Any) -> Tuple[Any, List[np.ndarray]]:
    """Replace every numpy array in ``tree`` with an indexed placeholder.

    Returns ``(structure, arrays)`` where ``structure`` is JSON-serializable.
    Scalars (python ints/floats/bools/str/None) pass through inline; numpy
    scalars are converted to python scalars.  Anything else is a hard error —
    the wire format carries data, not objects.
    """
    arrays: List[np.ndarray] = []

    def walk(obj: Any) -> Any:
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return obj
        if isinstance(obj, (np.generic,)):
            return obj.item()
        if isinstance(obj, np.ndarray):
            arr = np.ascontiguousarray(obj)
            arrays.append(arr)
            return {
                "__nd__": len(arrays) - 1,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
            }
        if isinstance(obj, dict):
            out = {}
            for k, v in obj.items():
                if not isinstance(k, str):
                    raise TypeError(f"transport dict keys must be str, got {type(k).__name__}")
                if k == "__nd__":
                    raise TypeError("'__nd__' is a reserved transport key")
                out[k] = walk(v)
            return out
        if isinstance(obj, (list, tuple)):
            return [walk(v) for v in obj]
        # Duck-typed arrays (jax.Array and friends): materialize on host.  The
        # caller should have device_get already (the publisher does, once);
        # this is the safety net, not the fast path.
        if hasattr(obj, "__array__"):
            return walk(np.asarray(obj))
        raise TypeError(f"transport cannot encode {type(obj).__name__!r}")

    return walk(tree), arrays


def decode_tree(structure: Any, buffers: List[memoryview]) -> Any:
    """Inverse of :func:`encode_tree` over received raw buffers."""

    def walk(obj: Any) -> Any:
        if isinstance(obj, dict):
            if "__nd__" in obj:
                idx = obj["__nd__"]
                arr = np.frombuffer(buffers[idx], dtype=np.dtype(obj["dtype"]))
                return arr.reshape(obj["shape"])
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [walk(v) for v in obj]
        return obj

    return walk(structure)


def _pack(kind: str, meta: Optional[Dict[str, Any]], payload: Any) -> List[bytes]:
    structure, arrays = encode_tree(payload)
    header = json.dumps(
        {
            "kind": kind,
            "meta": meta or {},
            "structure": structure,
            "nbytes": [int(a.nbytes) for a in arrays],
        }
    ).encode()
    if len(header) > MAX_HEADER_BYTES:
        raise FramingError(f"header of {len(header)} bytes exceeds MAX_HEADER_BYTES")
    chunks = [struct.pack(_HEADER_FMT, MAGIC, len(header)), header]
    chunks.extend(a.tobytes() for a in arrays)
    return chunks


class Channel:
    """A connected, framed, thread-safe-for-send message channel.

    ``send`` may be called from any thread (one internal lock serializes the
    frame).  ``recv`` must stay on a single consumer thread, like a socket.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover - e.g. AF_UNIX
            pass
        self.bytes_sent = 0
        self.bytes_received = 0

    # ------------------------------------------------------------------ send
    def send(self, kind: str, payload: Any = None, **meta: Any) -> int:
        """Frame and send one message; returns the wire size in bytes.

        Blocking: TCP backpressure is the flow control — a slow learner slows
        its actors down instead of buffering unbounded blocks in memory."""
        chunks = _pack(kind, meta, payload)
        n = sum(len(c) for c in chunks)
        with self._send_lock:
            if self._closed:
                raise ChannelClosed("send on closed channel")
            try:
                for c in chunks:
                    # jaxlint: disable=JL010 — blocking send under _send_lock is
                    # the framing contract itself: the lock exists to keep one
                    # message's chunks contiguous on the wire; writers queueing
                    # on it is the documented TCP-backpressure flow control.
                    self._sock.sendall(c)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                self._mark_closed()
                raise ChannelClosed(str(e)) from e
            # inside the lock: concurrent senders would lose += updates
            self.bytes_sent += n
        return n

    # ------------------------------------------------------------------ recv
    def _recv_exact(self, n: int) -> memoryview:
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            try:
                r = self._sock.recv_into(view[got:], n - got)
            except socket.timeout:
                raise TimeoutError(f"recv timed out with {got}/{n} bytes buffered")
            except (ConnectionResetError, OSError) as e:
                self._mark_closed()
                raise ChannelClosed(str(e)) from e
            if r == 0:
                self._mark_closed()
                raise ChannelClosed(f"peer closed with {got}/{n} bytes buffered")
            got += r
        return memoryview(buf)

    def recv(self, timeout: Optional[float] = None) -> Tuple[str, Dict[str, Any], Any]:
        """Receive one message: ``(kind, meta, payload)``.

        ``timeout=None`` blocks; a number raises ``TimeoutError`` past the
        deadline (the frame, once started, is read to completion — a timeout
        can only fire before the first header byte)."""
        if self._closed:
            raise ChannelClosed("recv on closed channel")
        if timeout is not None and not self.poll(timeout):
            raise TimeoutError(f"no message within {timeout}s")
        self._sock.settimeout(None)
        head = self._recv_exact(_HEADER_SIZE)
        magic, header_len = struct.unpack(_HEADER_FMT, head)
        if magic != MAGIC:
            self._mark_closed()
            raise FramingError(f"bad frame magic {bytes(magic)!r}")
        if header_len > MAX_HEADER_BYTES:
            self._mark_closed()
            raise FramingError(f"header of {header_len} bytes exceeds MAX_HEADER_BYTES")
        header = json.loads(bytes(self._recv_exact(header_len)))
        buffers = [self._recv_exact(n) for n in header["nbytes"]]
        self.bytes_received += _HEADER_SIZE + header_len + sum(header["nbytes"])
        payload = decode_tree(header["structure"], buffers)
        return header["kind"], header["meta"], payload

    def poll(self, timeout: float = 0.0) -> bool:
        """True when at least one byte is readable (non-blocking recv gate)."""
        if self._closed:
            return False
        try:
            readable, _, _ = select.select([self._sock], [], [], timeout)
        except (OSError, ValueError):
            return False
        return bool(readable)

    def drain_until_closed(self, timeout_s: float = 30.0) -> None:
        """Graceful goodbye: half-close the write side, then consume (and drop)
        inbound bytes until the peer closes or the deadline passes.

        Closing outright with unread inbound data (a params publish in flight)
        makes the kernel answer further peer writes with RST — which also
        destroys whatever WE sent that the peer has not read yet.  An actor that
        lingers here after its ``done`` keeps absorbing late publishes so every
        block it sent survives to the learner."""
        with self._send_lock:
            if self._closed:
                return
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                return
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                self._sock.settimeout(max(min(1.0, deadline - time.monotonic()), 0.01))
                if not self._sock.recv(1 << 16):
                    return  # peer closed: every byte we sent was delivered
            except socket.timeout:
                continue
            except OSError:
                return

    # ------------------------------------------------------------------ state
    def _mark_closed(self) -> None:
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass


class Listener:
    """Learner-side accept socket; survives any number of peer deaths.

    A killed actor's channel dies with the actor; the listener stays open and
    its respawned replacement connects on a fresh channel — 'reconnect' is a
    new accept, never a resurrected socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, backlog: int = 16):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(backlog)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def accept(self, timeout: Optional[float] = None) -> Channel:
        self._sock.settimeout(timeout)
        try:
            conn, _ = self._sock.accept()
        except socket.timeout:
            raise TimeoutError(f"no connection within {timeout}s")
        conn.settimeout(None)
        return Channel(conn)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass


def connect(
    host: str,
    port: int,
    timeout_s: float = 30.0,
    retry_interval_s: float = 0.1,
) -> Channel:
    """Actor-side connect with retry: the learner may still be importing JAX
    when its actors launch, so refusals are retried until ``timeout_s``."""
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.settimeout(max(min(retry_interval_s * 10, timeout_s), 0.1))
            sock.connect((host, port))
            sock.settimeout(None)
            return Channel(sock)
        except OSError as e:
            last = e
            sock.close()
            time.sleep(retry_interval_s)
    raise ConnectionError(f"could not reach {host}:{port} within {timeout_s}s: {last}")


# ------------------------------------------------------------------ batch digest
#: When set, learner loops append one sha256 line per consumed batch block to
#: this file — the bit-identity pin the 2-process smoke compares against the
#: in-process thread path (tests/test_distributed/test_sebulba_smoke.py).
BATCH_DIGEST_ENV_VAR = "SHEEPRL_TPU_BATCH_DIGEST"


def tree_digest(tree: Any) -> str:
    """Order-stable sha256 over every array leaf (dtype+shape+bytes) of a tree."""
    h = hashlib.sha256()

    def walk(obj: Any, path: str) -> None:
        if isinstance(obj, dict):
            for k in sorted(obj):
                walk(obj[k], f"{path}/{k}")
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(v, f"{path}[{i}]")
        elif obj is None:
            h.update(f"{path}:none".encode())
        else:
            arr = np.ascontiguousarray(np.asarray(obj))
            h.update(f"{path}:{arr.dtype.str}:{arr.shape}".encode())
            h.update(arr.tobytes())

    walk(tree, "")
    return h.hexdigest()


def maybe_digest(tag: str, tree: Any) -> None:
    """Append ``<tag> <sha256>`` for this batch when the digest hook is armed.

    No-op (one env lookup) in normal runs; both the thread-decoupled learners
    and the Sebulba learner call it on every consumed block, so the smoke can
    pin that the process topology feeds the update bit-identical data."""
    path = os.environ.get(BATCH_DIGEST_ENV_VAR)
    if not path:
        return
    with open(path, "a") as f:
        f.write(f"{tag} {tree_digest(tree)}\n")
