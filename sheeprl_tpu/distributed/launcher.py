"""Sebulba launcher: ``python -m sheeprl_tpu.sebulba <overrides>``.

The PR-10 autoresume supervisor grown into a *process manager*: instead of
relaunching one training process on death, it places and babysits a whole
topology — one learner plus ``distributed.num_actors`` actor processes, each an
ordinary ``python -m sheeprl_tpu`` run with role overrides stamped on (so every
child gets the full CLI pipeline: config compose, chaos install, flight
recorder, blackbox dumps).

Lifecycle policy:

* the **learner** is the run: when it exits, everything exits with its code;
  the launcher never respawns it (that remains ``sheeprl_tpu.supervise``'s job,
  which can wrap this launcher exactly like any other run).
* an **actor** that dies (chaos SIGKILL, OOM, env crash) is respawned with a
  bumped ``SHEEPRL_TPU_ACTOR_GENERATION`` after bounded backoff
  (``distributed.respawn_backoff_s``, ``distributed.max_actor_respawns``,
  reusing the supervisor's ``backoff_seconds`` curve).  A respawned actor
  reconnects, receives the freshest params as a welcome publish, and refills
  its replay shard from scratch.  An actor that exits 0 is done.
* a slot whose respawn budget is exhausted is **abandoned**: the launcher
  connects to the learner and sends an ``abandon`` control message so the
  learner stops waiting for that slot instead of starving.

Children write their logs into distinct run dirs — the learner keeps the pinned
``run_name``; actor *i* gets ``<run_name>_actor<i>`` — so the versioned log-dir
machinery never races across processes.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.distributed.placement import (
    GENERATION_ENV_VAR,
    ROLE_ACTOR,
    ROLE_LEARNER,
    SUMMARY_ENV_VAR,
    PlacementSpec,
    placement_from_cfg,
)
from sheeprl_tpu.distributed.transport import Listener, connect
from sheeprl_tpu.fault.supervisor import _strip_override, backoff_seconds, run_dir_for
from sheeprl_tpu.obs.fleet import (
    FLEET_ENV_VAR,
    TRACE_ID_ENV_VAR,
    FleetAggregator,
    new_trace_id,
)


def _log(msg: str) -> None:
    print(f"[sebulba] {msg}", flush=True)


def _base_overrides(overrides: List[str]) -> List[str]:
    """Strip the launcher-owned keys so children only see what we stamp on."""
    for key in ("distributed.role", "distributed.port", "distributed.actor_id", "run_name",
                "fault.autoresume"):
        overrides, _ = _strip_override(overrides, key)
    return overrides


def _spawn(
    overrides: List[str],
    child_ovs: List[str],
    run_name: str,
    env: Dict[str, str],
    log_prefix: str,
) -> subprocess.Popen:
    cmd = [sys.executable, "-m", "sheeprl_tpu"] + overrides + child_ovs + [
        f"run_name={run_name}",
        "fault.autoresume=False",
    ]
    _log(f"spawning {log_prefix}: {' '.join(cmd[3:])}")
    return subprocess.Popen(cmd, env=env)


def _abandon(spec: PlacementSpec, port: int, actor_id: int) -> None:
    try:
        ch = connect(spec.host, port, timeout_s=5.0)
        ch.send("abandon", None, actor_id=actor_id)
        ch.close()
    except (ConnectionError, OSError) as e:
        _log(f"could not notify learner of abandoned actor {actor_id}: {e}")


def launch(args: Optional[List[str]] = None) -> int:
    """Compose the placement, spawn learner + actors, babysit until done."""
    from sheeprl_tpu.config.core import compose

    overrides = list(args if args is not None else sys.argv[1:])
    overrides = _base_overrides(overrides)
    if not any(ov.startswith("distributed.mode=") for ov in overrides):
        overrides.append("distributed.mode=sebulba")
    cfg = compose(overrides=overrides)
    spec = placement_from_cfg(cfg)
    if not cfg.get("run_name"):
        import datetime

        stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
        cfg.run_name = f"{stamp}_{cfg.get('exp_name', 'run')}_{cfg.get('seed', 0)}_sebulba"
    run_name = str(cfg.run_name)

    # Reserve the rendezvous port here (port=0 → pick a free one) and release it
    # before the learner binds: children get the concrete number as an override.
    port = spec.port
    if port == 0:
        probe = Listener(spec.host, 0)
        port = probe.port
        probe.close()

    # Fleet telemetry plane: the launcher hosts the aggregator (the only process
    # that outlives every role) and hands children its address + the run-level
    # trace id through the environment.  run_dir_for needs no JAX — the fleet
    # dir lands next to the learner's versioned log dirs.
    fleet: Optional[FleetAggregator] = None
    trace_id = os.environ.get(TRACE_ID_ENV_VAR) or new_trace_id()
    fleet_cfg = dict((cfg.get("obs") or {}).get("fleet") or {})
    if bool(fleet_cfg.get("enabled", True)):
        fleet_dir = str(fleet_cfg.get("dir") or run_dir_for(cfg) / "fleet")
        try:
            fleet = FleetAggregator(
                fleet_dir,
                host=spec.host,
                liveness_timeout_s=float(fleet_cfg.get("liveness_timeout_s", 10.0)),
                trace_id=trace_id,
                max_timeline_mb=float(fleet_cfg.get("max_timeline_mb", 64.0)),
            )
            _log(f"fleet telemetry at {fleet.address} -> {fleet_dir} (trace_id={trace_id})")
        except OSError as e:
            _log(f"fleet telemetry disabled: {e}")

    def child_env(role: str, generation: int = 0) -> Dict[str, str]:
        env = dict(os.environ)
        # The summary sink is learner-only; role/ids travel as overrides.
        env.pop(SUMMARY_ENV_VAR, None)
        if role == ROLE_LEARNER and os.environ.get(SUMMARY_ENV_VAR):
            env[SUMMARY_ENV_VAR] = os.environ[SUMMARY_ENV_VAR]
        env[GENERATION_ENV_VAR] = str(generation)
        env[TRACE_ID_ENV_VAR] = trace_id
        env.pop(FLEET_ENV_VAR, None)
        if fleet is not None:
            env[FLEET_ENV_VAR] = fleet.address
        return env

    learner = _spawn(
        overrides,
        spec.child_overrides(ROLE_LEARNER, port),
        run_name,
        child_env(ROLE_LEARNER),
        "learner",
    )
    actors: Dict[int, Optional[subprocess.Popen]] = {}
    generations: Dict[int, int] = {i: 0 for i in range(spec.num_actors)}
    respawns: Dict[int, int] = {i: 0 for i in range(spec.num_actors)}
    respawn_at: Dict[int, float] = {}
    for i in range(spec.num_actors):
        actors[i] = _spawn(
            overrides,
            spec.child_overrides(ROLE_ACTOR, port, actor_id=i),
            f"{run_name}_actor{i}",
            child_env(ROLE_ACTOR),
            f"actor{i}",
        )

    children = lambda: [p for p in [learner, *actors.values()] if p is not None]
    terminating = {"flag": False}

    def forward_term(signum, frame):  # pragma: no cover - signal timing
        terminating["flag"] = True
        for p in children():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)

    old_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old_handlers[sig] = signal.signal(sig, forward_term)
        except ValueError:  # not on the main thread (tests)
            pass

    def collect_fleet_blackboxes(reason: str) -> None:
        """Fleet blackbox: a child died — ask every survivor to dump its flight-
        recorder ring into one correlated crash bundle (plus any on-disk
        ``blackbox/`` dumps, the dead child's own crash dump among them)."""
        if fleet is None or terminating["flag"]:
            return
        try:
            bundle = fleet.collect_blackboxes(reason)
            if bundle:
                _log(f"fleet blackbox bundle: {bundle}")
        except Exception as e:  # forensics must never take down the topology
            _log(f"fleet blackbox collection failed: {e}")

    try:
        while True:
            rc = learner.poll()
            if rc is not None:
                _log(f"learner exited rc={rc}")
                if rc != 0:
                    collect_fleet_blackboxes(f"learner_rc{rc}")
                return rc
            now = time.monotonic()
            for i, proc in list(actors.items()):
                if proc is not None and proc.poll() is not None:
                    arc = proc.returncode
                    actors[i] = None
                    if arc == 0:
                        _log(f"actor{i} done")
                        continue
                    collect_fleet_blackboxes(f"actor{i}_rc{arc}")
                    if terminating["flag"] or not spec.respawn:
                        _log(f"actor{i} died rc={arc}; not respawning")
                        continue
                    respawns[i] += 1
                    if fleet is not None:
                        fleet.note_respawn(i, respawns[i])
                    if respawns[i] > spec.max_actor_respawns:
                        _log(
                            f"actor{i} died rc={arc}; respawn budget "
                            f"({spec.max_actor_respawns}) exhausted — abandoning slot"
                        )
                        _abandon(spec, port, i)
                        continue
                    delay = backoff_seconds(respawns[i], spec.respawn_backoff_s, 30.0)
                    _log(
                        f"actor{i} died rc={arc}; respawn {respawns[i]}/"
                        f"{spec.max_actor_respawns} in {delay:.1f}s"
                    )
                    respawn_at[i] = now + delay
                elif proc is None and i in respawn_at and now >= respawn_at[i]:
                    del respawn_at[i]
                    generations[i] += 1
                    actors[i] = _spawn(
                        overrides,
                        spec.child_overrides(ROLE_ACTOR, port, actor_id=i),
                        f"{run_name}_actor{i}_g{generations[i]}",
                        child_env(ROLE_ACTOR, generation=generations[i]),
                        f"actor{i}(gen{generations[i]})",
                    )
            time.sleep(0.05)
    finally:
        for p in children():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10.0
        for p in children():
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        if fleet is not None:
            # After the children exited: their exporters' close-time flushes and
            # trace shipments are in, so the merged Perfetto file and the final
            # snapshot cover every process.
            fleet.close()
        for sig, handler in old_handlers.items():
            signal.signal(sig, handler)


def main(args: Optional[List[str]] = None) -> None:
    sys.exit(launch(args))
