"""Deterministic chaos harness: a seeded fault schedule driven by the ``chaos``
config group.

Generalizes ``analysis.inject_nan`` (one hard-coded fault) into a schedule of
*infrastructure* faults, each pinned to a policy step so every chaos run is exactly
reproducible:

* ``chaos.kill_at_step=N``      — deliver ``chaos.kill_signal`` (SIGTERM | SIGKILL)
  to this process at the first loop boundary past step N.  SIGTERM exercises the
  graceful-preemption path (boundary checkpoint + ``PREEMPTED`` marker + exit 75);
  SIGKILL exercises the supervisor's crash-resume path (no goodbye at all).
* ``chaos.corrupt_ckpt_at_step=N`` + ``chaos.corrupt_mode=bitflip|truncate``
  — damage the newest *published* checkpoint (seeded byte, so the damage is
  deterministic), proving ``CheckpointManager.load`` falls back to the previous
  valid checkpoint instead of deserializing garbage.
* ``chaos.delay_at_step=N`` + ``chaos.delay_ms`` — stall one loop boundary
  (elastic-timing faults: slow NFS, a throttled host).
* ``chaos.worker_fault_at_step=N`` + ``chaos.worker_fault_mode=crash|hang`` +
  ``chaos.worker_index=i`` — make EnvPool worker *i* crash (``os._exit``) or hang
  (sleep past the step timeout) at its N-th step command, exercising the pool's
  restart machinery.  The spec rides the fork into the worker process
  (``rollout/worker.py`` polls :func:`maybe_worker_fault`); only generation 0
  fires, so the restarted replacement worker runs clean.
* ``chaos.kill_actor_at_step=N`` + ``chaos.kill_actor_index=i`` — SIGKILL Sebulba
  ACTOR process *i* at its N-th iteration (``distributed/sebulba.py`` polls
  :func:`maybe_actor_fault` once per iteration).  The learner must keep taking
  gradient steps on the surviving actors' blocks while the launcher respawns the
  victim; only actor generation 0 fires, so the respawn runs clean.

Step triggers are *edge* triggers: a fault fires when the step counter crosses its
threshold, and a run resumed past the threshold (in-process or via the supervisor)
never re-fires it — that is what makes kill-at-step-N + autoresume a terminating,
deterministic experiment.

Stdlib-only at import: forked EnvPool workers import this and must stay JAX-free.
"""

from __future__ import annotations

import os
import signal
import time
import warnings
from pathlib import Path
from typing import Any, Dict, Optional

from sheeprl_tpu.fault import counters as _counters
from sheeprl_tpu.obs import flight_recorder as _flight_recorder

_KILL_SIGNALS = {"SIGTERM": signal.SIGTERM, "SIGINT": signal.SIGINT, "SIGKILL": signal.SIGKILL}
_CORRUPT_MODES = ("bitflip", "truncate")
_WORKER_MODES = ("crash", "hang")

#: Exit code a chaos-crashed EnvPool worker dies with (distinctive in ps/logs).
WORKER_CRASH_EXIT_CODE = 117

# Worker-fault spec, set in the parent BEFORE EnvPool forks its workers so the
# children inherit it through fork; None means no worker fault scheduled.
_worker_fault: Optional[Dict[str, Any]] = None

# Sebulba actor-kill spec: unlike the worker fault it does not ride a fork — the
# actor is its own CLI process whose ``install(cfg)`` parses the same overrides.
_actor_fault: Optional[Dict[str, Any]] = None


def _chaos_cfg(cfg: Any) -> Dict[str, Any]:
    try:
        chaos = cfg.get("chaos") if hasattr(cfg, "get") else getattr(cfg, "chaos", None)
    except Exception:
        return {}
    return dict(chaos) if chaos else {}


def install(cfg: Any) -> None:
    """Parse the worker-fault part of the schedule into module state (call before
    any EnvPool fork; ``cli.run_algorithm`` does).  Validates the grammar loudly."""
    global _worker_fault, _actor_fault
    chaos = _chaos_cfg(cfg)
    _worker_fault = None
    _actor_fault = None
    if not chaos:
        return
    if chaos.get("kill_actor_at_step") is not None:
        _actor_fault = {
            "at_step": int(chaos["kill_actor_at_step"]),
            "actor": int(chaos.get("kill_actor_index", 0) or 0),
        }
    sig_name = str(chaos.get("kill_signal", "SIGTERM")).upper()
    if chaos.get("kill_at_step") is not None and sig_name not in _KILL_SIGNALS:
        raise ValueError(f"chaos.kill_signal must be one of {sorted(_KILL_SIGNALS)}; got {sig_name!r}")
    mode = str(chaos.get("corrupt_mode", "bitflip"))
    if chaos.get("corrupt_ckpt_at_step") is not None and mode not in _CORRUPT_MODES:
        raise ValueError(f"chaos.corrupt_mode must be one of {_CORRUPT_MODES}; got {mode!r}")
    if chaos.get("worker_fault_at_step") is not None:
        wmode = str(chaos.get("worker_fault_mode", "crash"))
        if wmode not in _WORKER_MODES:
            raise ValueError(f"chaos.worker_fault_mode must be one of {_WORKER_MODES}; got {wmode!r}")
        _worker_fault = {
            "at_step": int(chaos["worker_fault_at_step"]),
            "mode": wmode,
            "worker": int(chaos.get("worker_index", 0) or 0),
            "hang_s": float(chaos.get("worker_hang_s", 3600.0)),
        }


def maybe_actor_fault(actor_id: int, generation: int, step_count: int) -> None:
    """Polled by the Sebulba actor loop once per iteration.  SIGKILL — no goodbye,
    no flushed buffers — because the contract under test is the LEARNER's: its
    gradient-step counter must keep increasing across the kill window while the
    launcher respawns this process (generation > 0 never re-fires, so the
    experiment terminates)."""
    spec = _actor_fault
    if spec is None or generation != 0 or actor_id != spec["actor"]:
        return
    if step_count >= spec["at_step"]:
        _flight_recorder.record_event("chaos_actor_kill", step=step_count, actor_id=actor_id)
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_worker_fault(worker_idx: int, generation: int, step_count: int) -> None:
    """Polled by ``rollout/worker.py`` once per step command (inherited via fork)."""
    spec = _worker_fault
    if spec is None or generation != 0 or worker_idx != spec["worker"]:
        return
    if step_count == spec["at_step"]:
        if spec["mode"] == "crash":
            os._exit(WORKER_CRASH_EXIT_CODE)
        time.sleep(spec["hang_s"])  # hang: the parent's step timeout reaps us


class ChaosMonkey:
    """Boundary-side fault injector; inert (one attribute check) without a schedule.

    ``fire(step)`` is called once per training-loop boundary by
    :class:`~sheeprl_tpu.fault.guard.TrainingGuard` with the current policy step.
    """

    def __init__(self, cfg: Any, ckpt_dir: Optional[os.PathLike] = None, resumed: Optional[bool] = None):
        chaos = _chaos_cfg(cfg)
        self.seed = int(chaos.get("seed", 0) or 0)
        self.kill_at_step = chaos.get("kill_at_step")
        self.kill_signal = str(chaos.get("kill_signal", "SIGTERM")).upper()
        self.corrupt_at_step = chaos.get("corrupt_ckpt_at_step")
        self.corrupt_mode = str(chaos.get("corrupt_mode", "bitflip"))
        self.delay_at_step = chaos.get("delay_at_step")
        self.delay_ms = float(chaos.get("delay_ms", 500) or 0)
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir is not None else None
        if resumed is None:
            try:
                resumed = bool(cfg.get("checkpoint", {}).get("resume_from"))
            except Exception:
                resumed = False
        self.resumed = bool(resumed)
        self.enabled = any(
            v is not None for v in (self.kill_at_step, self.corrupt_at_step, self.delay_at_step)
        )
        self._last_step: Optional[int] = None
        self._fired: set = set()

    def _crossed(self, kind: str, at_step: Optional[Any], step: int) -> bool:
        """Edge trigger: True exactly once, when ``step`` first crosses ``at_step``
        *within this run*.  A RESUMED run whose very first boundary is already past
        the threshold crossed it in a previous life — mark fired, never re-fire
        (that is what makes kill-at-step-N + autoresume terminate)."""
        if at_step is None or kind in self._fired:
            return False
        if self._last_step is None and self.resumed and step >= int(at_step):
            self._fired.add(kind)  # resumed past the threshold
            return False
        if step >= int(at_step):
            self._fired.add(kind)
            return True
        return False

    def fire(self, step: int) -> None:
        if not self.enabled:
            return
        step = int(step)
        if self._crossed("delay", self.delay_at_step, step):
            _flight_recorder.record_event("chaos_delay", step=step, delay_ms=self.delay_ms)
            _counters.bump("Fault/chaos_injected")
            time.sleep(self.delay_ms / 1000.0)
        if self._crossed("corrupt", self.corrupt_at_step, step):
            _counters.bump("Fault/chaos_injected")
            self._corrupt_latest(step)
        if self._crossed("kill", self.kill_at_step, step):
            _flight_recorder.record_event("chaos_kill", step=step, sig=self.kill_signal)
            _counters.bump("Fault/chaos_injected")
            self._kill()
        self._last_step = step

    # ------------------------------------------------------------------ faults
    def _kill(self) -> None:
        sig = _KILL_SIGNALS[self.kill_signal]
        os.kill(os.getpid(), sig)
        if sig != signal.SIGKILL:
            # Signal delivery to the main thread is asynchronous; wait for the
            # sticky flag so the *same* boundary handles the preemption — that
            # determinism is what the bit-identity e2e rests on.
            from sheeprl_tpu.fault import preemption

            deadline = time.monotonic() + 5.0
            while not preemption.preemption_requested() and time.monotonic() < deadline:
                time.sleep(0.005)

    def _corrupt_latest(self, step: int) -> None:
        if self.ckpt_dir is None or not self.ckpt_dir.exists():
            warnings.warn(f"chaos.corrupt_ckpt_at_step={self.corrupt_at_step}: no checkpoint dir to corrupt")
            return
        ckpts = sorted(
            (p for p in self.ckpt_dir.iterdir() if p.is_dir() and p.name.startswith("ckpt_")),
            key=lambda p: int(p.name.split("_")[1]),
        )
        if not ckpts:
            warnings.warn(f"chaos.corrupt_ckpt_at_step={self.corrupt_at_step}: no published checkpoint yet")
            return
        target_dir = ckpts[-1]
        victims = sorted(target_dir.glob("*.msgpack"), key=lambda p: p.stat().st_size, reverse=True)
        if not victims:
            victims = sorted((p for p in target_dir.iterdir() if p.is_file()), key=lambda p: p.stat().st_size, reverse=True)
        if not victims:
            return
        corrupt_file(victims[0], mode=self.corrupt_mode, seed=self.seed)
        _flight_recorder.record_event(
            "chaos_corrupt", step=step, path=str(victims[0]), mode=self.corrupt_mode
        )


def corrupt_file(path: os.PathLike, mode: str = "bitflip", seed: int = 0) -> None:
    """Deterministically damage ``path``: flip one seeded bit, or cut the file in half."""
    path = Path(path)
    size = path.stat().st_size
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 0))
        return
    if size == 0:
        return
    offset = seed % size
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ 0x01]))
