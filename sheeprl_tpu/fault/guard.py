"""``TrainingGuard``: the one-line safe-boundary hook every training loop calls.

    guard = TrainingGuard(cfg, log_dir)            # next to CheckpointManager setup
    ...
    for update in ...:
        ...train, log, periodic checkpoint...
        guard.boundary(policy_step, save_ckpt)     # end of every update

``boundary`` does two things, in order:

1. fires any scheduled chaos faults that cross this step
   (:class:`~sheeprl_tpu.fault.chaos.ChaosMonkey` — inert without a ``chaos``
   schedule);
2. checks the sticky preemption flag
   (:mod:`~sheeprl_tpu.fault.preemption`); when set it calls ``save_ckpt`` —
   the loop's own checkpoint closure, so the preemption checkpoint has exactly
   the periodic checkpoint's contents — writes the ``PREEMPTED`` marker and
   raises :class:`~sheeprl_tpu.fault.preemption.Preempted`.

The boundary sits at the END of the update (after the periodic-checkpoint block):
the loop's counters then describe *completed* work, so the closure saves a state
a resume can continue from without repeating or skipping an update.

Cost when nothing is scheduled and no signal arrived: two attribute checks.
"""

from __future__ import annotations

import warnings
from pathlib import Path
from typing import Any, Callable, Optional

from sheeprl_tpu.fault import counters as _counters
from sheeprl_tpu.fault import preemption
from sheeprl_tpu.fault.chaos import ChaosMonkey
from sheeprl_tpu.obs import flight_recorder as _flight_recorder


class TrainingGuard:
    def __init__(self, cfg: Any, log_dir: Optional[str] = None, ckpt_dir: Optional[str] = None):
        self.cfg = cfg
        self.log_dir = str(log_dir) if log_dir else None
        # Every entry point keeps its checkpoints in <log_dir>/checkpoints; the
        # chaos corrupt fault and the PREEMPTED marker's resume hint both key off it.
        if ckpt_dir is None and log_dir:
            ckpt_dir = str(Path(log_dir) / "checkpoints")
        self.ckpt_dir = ckpt_dir
        self.chaos = ChaosMonkey(cfg, ckpt_dir=ckpt_dir)

    def boundary(self, step: int, save_ckpt: Optional[Callable[[], Any]] = None) -> None:
        """Call once per update with the current policy step; ``save_ckpt`` is the
        loop's checkpoint closure (returns the checkpoint path, or None)."""
        if self.chaos.enabled:
            self.chaos.fire(step)
        if preemption.preemption_requested():
            self._preempt(int(step), save_ckpt)

    def _preempt(self, step: int, save_ckpt: Optional[Callable[[], Any]]) -> None:
        sig = preemption.signal_name()
        _counters.bump("Fault/preemptions")
        _flight_recorder.record_event("preemption", step=step, signal=sig)
        ckpt_path = None
        if save_ckpt is not None:
            try:
                ckpt_path = save_ckpt()
            except Exception as e:  # a failed goodbye checkpoint must not mask the exit
                warnings.warn(f"preemption checkpoint at step {step} failed: {e}")
        if ckpt_path is None and self.ckpt_dir is not None:
            # The closure saved but returned nothing (or failed): point the marker
            # at the newest checkpoint on disk instead of leaving it blank.
            from sheeprl_tpu.checkpoint.manager import CheckpointManager

            ckpt_path = CheckpointManager.latest_valid(self.ckpt_dir)
        if self.log_dir:
            preemption.write_marker(self.log_dir, step, resume_from=ckpt_path)
        raise preemption.Preempted(
            step, log_dir=self.log_dir, ckpt_path=str(ckpt_path) if ckpt_path else None
        )
