"""Fault tolerance: graceful preemption, autoresume, checkpoint integrity, chaos.

The north star runs on preemptible accelerator fleets (Podracer, arXiv:2104.06272),
where eviction mid-run is the normal case, not the exception.  This package is the
recovery half of the observability story: the flight recorder (``sheeprl_tpu/obs``)
diagnoses a dead run, ``sheeprl_tpu.fault`` keeps it alive —

* :mod:`~sheeprl_tpu.fault.preemption` — SIGTERM/SIGINT become a sticky flag that
  every training loop checks at its safe boundary (between updates, where a
  checkpoint is consistent), cuts one final checkpoint, writes a ``PREEMPTED``
  marker and exits with :data:`RESUMABLE_EXIT_CODE`;
* :mod:`~sheeprl_tpu.fault.guard` — :class:`TrainingGuard`, the one-line boundary
  hook the entry points call once per update;
* :mod:`~sheeprl_tpu.fault.supervisor` — ``python -m sheeprl_tpu.supervise``
  relaunches a crashed/preempted run from the latest *valid* checkpoint with
  bounded exponential backoff; ``fault.autoresume=True`` does the same in-process;
* :mod:`~sheeprl_tpu.fault.classify` — the retry/fatal matrix (non-finite loss is
  deterministic: never retried; preemptions and worker crashes are transient:
  always retried);
* :mod:`~sheeprl_tpu.fault.chaos` — a seeded, deterministic fault schedule
  (``chaos`` config group) that kills the process, corrupts a checkpoint, hangs a
  rollout worker or delays a dispatch at step N, so the e2e tests *prove*
  kill+resume reaches the same final params as an uninterrupted run;
* :mod:`~sheeprl_tpu.fault.counters` — ``Fault/*`` metrics merged into every
  metric flush by ``TrainingMonitor.log_metrics``.

See ``howto/fault_tolerance.md`` for the operator-facing guarantees.
"""

from __future__ import annotations

from sheeprl_tpu.fault.counters import bump as bump_counter
from sheeprl_tpu.fault.counters import fault_metrics
from sheeprl_tpu.fault.guard import TrainingGuard
from sheeprl_tpu.fault.preemption import (
    PREEMPTED_MARKER,
    RESUMABLE_EXIT_CODE,
    Preempted,
    clear_preemption,
    install_signal_handlers,
    preemption_requested,
    read_marker,
    request_preemption,
    write_marker,
)

__all__ = [
    "PREEMPTED_MARKER",
    "RESUMABLE_EXIT_CODE",
    "Preempted",
    "TrainingGuard",
    "bump_counter",
    "clear_preemption",
    "fault_metrics",
    "install_signal_handlers",
    "preemption_requested",
    "read_marker",
    "request_preemption",
    "write_marker",
]
