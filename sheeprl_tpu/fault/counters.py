"""``Fault/*`` counters: process-global, thread-safe, merged into every metric flush.

``TrainingMonitor.log_metrics`` folds :func:`fault_metrics` into each flush the same
way it folds the named-timer registry — independent of ``obs.enabled``, so a
preempted production run still shows its ``Fault/preemptions`` trail on the dashboard.
Counters that were never bumped are not reported (a healthy run's metric stream is
unchanged).

The supervisor seeds :data:`RESTARTS_ENV_VAR` into each child it relaunches so the
per-attempt processes report the *cumulative* restart count, not their own zero.

Stdlib-only at import: the EnvPool worker processes may import this transitively.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

#: Set by the supervisor on relaunched children: cumulative restarts so far.
RESTARTS_ENV_VAR = "SHEEPRL_TPU_FAULT_RESTARTS"

_lock = threading.Lock()
_counters: Dict[str, float] = {}


def _seed_from_env() -> None:
    restarts = os.environ.get(RESTARTS_ENV_VAR)
    if restarts:
        try:
            _counters["Fault/restarts"] = float(int(restarts))
        except ValueError:
            pass


_seed_from_env()


def bump(name: str, n: float = 1) -> None:
    """Increment ``Fault/<name>`` (pass the full key, e.g. ``"Fault/preemptions"``)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def fault_metrics() -> Dict[str, float]:
    """Snapshot of every counter that was ever bumped (empty for a healthy run)."""
    with _lock:
        return dict(_counters)


def reset() -> None:
    """Tests only: drop all counters, then re-seed from the environment."""
    with _lock:
        _counters.clear()
        _seed_from_env()
