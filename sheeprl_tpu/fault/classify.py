"""Failure classification: the supervisor's retry/fatal matrix.

A relaunch loop must answer one question per death: *would running the exact same
computation again do any good?*  The matrix (documented for operators in
``howto/fault_tolerance.md``):

===========================  ==========  =====================================
observation                  verdict     why
===========================  ==========  =====================================
exit 0                       DONE        the run finished
exit 75 / ``Preempted``      RESUME      graceful preemption: a boundary
                                         checkpoint exists, resume immediately
``NonFiniteError``           FATAL       a NaN/Inf is a deterministic function
                                         of the checkpointed state: the retry
                                         hits the same NaN at the same step
``SignatureDriftError``      FATAL       config/code bug, deterministic
``RecompileError``           FATAL       config/code bug, deterministic
``KeyboardInterrupt``        FATAL       the operator asked for a stop
anything else                RETRY       worker crash, OOM, flaky I/O, SIGKILL:
                                         transient until proven otherwise
                                         (bounded by ``fault.max_retries``)
===========================  ==========  =====================================

The exception *type name* comes from the flight recorder's blackbox dump
(``blackbox/meta.json`` → ``exception.type``) when classifying a dead subprocess,
or from the live exception object in-process — same names either way, so both
paths share one table.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

from sheeprl_tpu.fault.preemption import RESUMABLE_EXIT_CODE, Preempted

DONE = "done"
RESUME = "resume"  # graceful preemption: restart from the boundary checkpoint now
RETRY = "retry"  # transient: restart with backoff, bounded by fault.max_retries
FATAL = "fatal"  # deterministic: retrying replays the same failure

#: Exception type names that make a retry pointless (see the module docstring).
FATAL_EXCEPTIONS = frozenset(
    {"NonFiniteError", "SignatureDriftError", "RecompileError", "KeyboardInterrupt"}
)


def classify_exception(exc: BaseException) -> str:
    """In-process verdict (``fault.autoresume=True`` path)."""
    if isinstance(exc, Preempted):
        return RESUME
    return FATAL if type(exc).__name__ in FATAL_EXCEPTIONS else RETRY


def classify_exit(returncode: int, blackbox_meta: Optional[Dict[str, Any]] = None) -> str:
    """Subprocess verdict (supervisor path): exit code first, then the blackbox."""
    if returncode == 0:
        return DONE
    if returncode == RESUMABLE_EXIT_CODE:
        return RESUME
    exc_type = ((blackbox_meta or {}).get("exception") or {}).get("type")
    return FATAL if exc_type in FATAL_EXCEPTIONS else RETRY


def read_blackbox_meta(run_dir: Path) -> Optional[Dict[str, Any]]:
    """Newest ``blackbox/meta.json`` under the run dir (any ``version_*``), or None."""
    metas = sorted(
        Path(run_dir).glob("**/blackbox/meta.json"),
        key=lambda p: p.stat().st_mtime,
        reverse=True,
    )
    for meta_path in metas:
        try:
            with open(meta_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
    return None
