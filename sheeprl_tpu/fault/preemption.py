"""Graceful preemption: SIGTERM/SIGINT → sticky flag → one final checkpoint → exit 75.

Preemptible TPU fleets deliver SIGTERM with a bounded grace window (30 s on GCE
spot VMs) before the hard kill.  The handler here does NOT checkpoint — a signal
can land mid-dispatch, where device state is inconsistent and a blocking
``device_get`` inside a handler can deadlock.  It only sets a *sticky flag*; every
training loop polls the flag once per update at its safe boundary (between
dispatches, where the checkpointable state is exactly what a periodic checkpoint
would save) via :class:`sheeprl_tpu.fault.guard.TrainingGuard`, cuts one final
checkpoint, writes the ``PREEMPTED`` marker and raises :class:`Preempted`, which
``cli.run`` converts into :data:`RESUMABLE_EXIT_CODE` (75, BSD ``EX_TEMPFAIL``:
"failure is transient, retry") — the code the supervisor treats as
resume-immediately.

A second SIGINT restores Python's default KeyboardInterrupt (an operator hammering
Ctrl-C gets the usual abort, losing at most the boundary checkpoint); a second
SIGTERM hard-exits with the resumable code (the platform is done waiting).

``fault.grace_seconds > 0`` arms a best-effort deadline: a daemon thread hard-exits
with the resumable code if the boundary checkpoint has not finished inside the
window — a truncated tmp dir is invisible to resume (the atomic-rename publish
never happened), so exiting beats being SIGKILLed mid-rename.

Stdlib-only at import (the CLI installs handlers before JAX backends exist).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import warnings
from pathlib import Path
from typing import Any, Dict, Optional

from sheeprl_tpu.fault import counters as _counters
from sheeprl_tpu.obs import flight_recorder as _flight_recorder

#: BSD ``EX_TEMPFAIL``: the canonical "transient failure, retry me" exit code.
RESUMABLE_EXIT_CODE = 75

#: Marker file name written next to the run's checkpoints on graceful shutdown.
PREEMPTED_MARKER = "PREEMPTED"

_flag = threading.Event()
_installed = False
_signal_name: Optional[str] = None
_grace_seconds: float = 0.0


class Preempted(Exception):
    """Raised at a training-loop boundary after the preemption checkpoint is cut.

    Carries what the resume path needs: the policy step the final checkpoint
    covers, the checkpoint path (when the loop's save hook returned one) and the
    run's log dir (where the ``PREEMPTED`` marker lives).
    """

    def __init__(self, step: int, log_dir: Optional[str] = None, ckpt_path: Optional[str] = None):
        self.step = int(step)
        self.log_dir = log_dir
        self.ckpt_path = ckpt_path
        super().__init__(
            f"preempted ({_signal_name or 'requested'}) at policy step {step}; "
            f"final checkpoint: {ckpt_path or 'none'}"
        )


def preemption_requested() -> bool:
    """True once a shutdown signal arrived (or :func:`request_preemption` ran)."""
    return _flag.is_set()


def request_preemption(reason: str = "requested") -> None:
    """Set the sticky flag programmatically (tests, embedding applications)."""
    global _signal_name
    if not _flag.is_set():
        _signal_name = reason
        _flag.set()


def clear_preemption() -> None:
    """Drop the sticky flag (in-process autoresume clears it before re-running)."""
    global _signal_name
    _signal_name = None
    _flag.clear()


def signal_name() -> Optional[str]:
    return _signal_name


def _arm_grace_deadline() -> None:
    if _grace_seconds <= 0:
        return

    def deadline() -> None:
        time.sleep(_grace_seconds)
        if _flag.is_set():  # autoresume may have cleared it: shutdown is off
            _flight_recorder.dump_active("preemption_grace_expired")
            os._exit(RESUMABLE_EXIT_CODE)

    threading.Thread(target=deadline, name="fault-grace-deadline", daemon=True).start()


def _handler(signum: int, frame: Any) -> None:
    global _signal_name
    name = signal.Signals(signum).name
    if _flag.is_set():
        # Second signal: the sender is done waiting for the boundary checkpoint.
        if signum == signal.SIGINT:
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
        os._exit(RESUMABLE_EXIT_CODE)
    _signal_name = name
    _flag.set()
    _counters.bump("Fault/preemption_signals")
    _flight_recorder.record_event("preemption_signal", signal=name)
    _arm_grace_deadline()


def install_signal_handlers(grace_seconds: float = 0.0) -> bool:
    """Install the SIGTERM/SIGINT → sticky-flag handlers (idempotent).

    Returns False without side effects when not on the main thread (signal
    handlers can only be installed there; library embedders calling
    ``run_algorithm`` from a worker thread keep their own handling).
    """
    global _installed, _grace_seconds
    _grace_seconds = float(grace_seconds or 0.0)
    if _installed:
        return True
    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
    except ValueError:  # not the main thread
        return False
    _installed = True
    return True


# --------------------------------------------------------------------- marker file
def write_marker(log_dir: os.PathLike, step: int, resume_from: Optional[str] = None) -> Optional[Path]:
    """Write ``<log_dir>/PREEMPTED`` (JSON: step, resume checkpoint, signal, time).

    The marker is advisory — resume discovery always re-validates checkpoints —
    but it lets an operator (and CI) see at a glance that the run shut down
    *gracefully* and where it intends to pick up.  Fsynced: the marker must
    survive the platform's hard kill that follows the grace window.
    """
    try:
        path = Path(log_dir) / PREEMPTED_MARKER
        payload = {
            "step": int(step),
            "resume_from": str(resume_from) if resume_from else None,
            "signal": _signal_name,
            "time": time.time(),
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        return path
    except OSError as e:
        warnings.warn(f"could not write {PREEMPTED_MARKER} marker in {log_dir}: {e}")
        return None


def read_marker(log_dir: os.PathLike) -> Optional[Dict[str, Any]]:
    """Parse ``<log_dir>/PREEMPTED``; None when absent or unreadable."""
    try:
        with open(Path(log_dir) / PREEMPTED_MARKER) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_marker(log_dir: os.PathLike) -> None:
    try:
        (Path(log_dir) / PREEMPTED_MARKER).unlink(missing_ok=True)
    except OSError:
        pass
