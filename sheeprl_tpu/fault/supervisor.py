"""Autoresume supervisor: ``python -m sheeprl_tpu.supervise <overrides>``.

Wraps a training run in a relaunch loop the way a fleet scheduler would, but on one
host and with the repo's own failure classification:

1. compose the config once (no JAX touched) to pin ``run_name`` — every attempt
   lands in the same run directory tree, so checkpoints, markers and blackboxes
   accumulate in one place;
2. launch ``python -m sheeprl_tpu <overrides> run_name=<pinned>`` as a subprocess
   (plus ``checkpoint.resume_from=<latest valid>`` from the second attempt on);
3. classify each death (:mod:`~sheeprl_tpu.fault.classify`): exit 0 → done; exit 75
   (graceful preemption) → resume immediately; a blackbox whose exception is
   deterministic (``NonFiniteError`` ...) → stop; anything else → retry with
   bounded exponential backoff (``fault.backoff_s`` doubling up to
   ``fault.backoff_max_s``, at most ``fault.max_retries`` times);
4. resume from the newest checkpoint *that verifies* — a truncated or bit-flipped
   latest checkpoint is skipped, not deserialized (``CheckpointManager.verify``)
   — searching every ``version_*`` dir of the run (each attempt logs into a fresh
   version).

Children get ``SHEEPRL_TPU_FAULT_RESTARTS`` so their ``Fault/restarts`` counter
(merged into every metric flush by ``TrainingMonitor``) reports the cumulative
relaunch count, and ``fault.autoresume=False`` so retry accounting lives in exactly
one place.

``fault.autoresume=True`` gives the same loop in-process (``cli.run``) — enough for
SIGTERM-style chaos drills and CI; SIGKILL/OOM survival needs this supervisor.

Serving mode: ``python -m sheeprl_tpu.supervise --serve <overrides>`` wraps a
``python -m sheeprl_tpu.serve`` replica instead (:func:`supervise_serve`).
Replicas are *stateless* — their checkpoints live in the model registry — so the
loop is simpler: no run-dir pinning, no resume-checkpoint discovery.  Exit 0
(clean shutdown) → done; exit 75 (SIGTERM → drained everything accepted) →
respawn immediately, bounded by ``fault.max_preemptions``; anything else →
retry with the same bounded backoff as training.  Backoff scales with the
*consecutive* crash count — a clean preemption in between proves the binary
healthy and resets the clock — while ``fault.max_retries`` bounds total
crashes over the supervisor's lifetime.  Every exit path (clean, budget
exhausted, or the supervisor itself dying) writes a summary JSON to
``fault.summary_path`` / ``SHEEPRL_TPU_SUPERVISE_SUMMARY``.

Fleet mode: with ``serve.fleet.enabled=True`` the same entry point becomes the
fleet manager (:func:`sheeprl_tpu.serve.fleet.manager.supervise_fleet`): front
+ N replicas, per-slot respawn, autoscaling, canary.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.fault import classify as _classify
from sheeprl_tpu.fault.counters import RESTARTS_ENV_VAR
from sheeprl_tpu.fault.preemption import RESUMABLE_EXIT_CODE

#: Env var override for where the supervisor's exit summary lands.
SUPERVISE_SUMMARY_ENV_VAR = "SHEEPRL_TPU_SUPERVISE_SUMMARY"


def fault_cfg(cfg: Any) -> Dict[str, Any]:
    try:
        section = cfg.get("fault") if hasattr(cfg, "get") else getattr(cfg, "fault", None)
    except Exception:
        section = None
    return dict(section) if section else {}


def run_dir_for(cfg: Any) -> Path:
    """The run's root directory (all ``version_*`` attempts live under it)."""
    return Path(cfg.get("log_root", "logs")) / "runs" / str(cfg["root_dir"]) / str(cfg["run_name"])


def find_resume_checkpoint(run_dir: os.PathLike) -> Optional[Path]:
    """Newest *valid* checkpoint across every ``version_*`` of the run.

    Sorted by (step, version): a later attempt resumes from the globally newest
    step, wherever the attempt that wrote it logged.  Corrupt candidates are
    skipped via ``CheckpointManager.verify`` — never deserialized.
    """
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    run_dir = Path(run_dir)
    if not run_dir.exists():
        return None

    def sort_key(ckpt: Path) -> Tuple[int, int]:
        step = int(ckpt.name.split("_")[1])
        version_dir = ckpt.parent.parent.name  # version_N/checkpoints/ckpt_S
        version = int(version_dir.split("_")[1]) if version_dir.startswith("version_") else -1
        return (step, version)

    candidates = sorted(run_dir.glob("version_*/checkpoints/ckpt_*"), key=sort_key, reverse=True)
    for candidate in candidates:
        if candidate.is_dir() and CheckpointManager.verify(candidate):
            return candidate
    return None


def backoff_seconds(retries: int, base_s: float, max_s: float) -> float:
    """Exponential backoff for retry number ``retries`` (1-based): base * 2^(n-1)."""
    return min(float(base_s) * (2 ** max(retries - 1, 0)), float(max_s))


def _strip_override(overrides: List[str], key: str) -> Tuple[List[str], Optional[str]]:
    value = None
    kept = []
    for ov in overrides:
        if ov.startswith(f"{key}="):
            value = ov.split("=", 1)[1]
        else:
            kept.append(ov)
    return kept, value


def _log(msg: str) -> None:
    print(f"[supervise] {msg}", flush=True)


def write_supervisor_summary(f_cfg: Dict[str, Any], doc: Dict[str, Any]) -> Optional[Path]:
    """Atomically write the supervisor's lifetime summary.  Called from the exit
    ``finally`` of every supervising loop — clean, budget-exhausted or crashed —
    so post-mortems always find an account of what the supervisor saw."""
    import json
    import tempfile

    path = os.environ.get(SUPERVISE_SUMMARY_ENV_VAR) or f_cfg.get("summary_path")
    if not path:
        return None
    out = Path(str(path))
    out.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(prefix=f".{out.name}.", suffix=".tmp", dir=out.parent)
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp_name, out)
    return out


def supervise_serve(overrides: List[str]) -> int:
    """The serving-mode relaunch loop: keep one stateless replica alive.

    A drained preemption (rc 75) means every accepted request was answered
    before exit — the respawn is immediate because a replica that is down is
    pure lost capacity.  Crashes back off on the *consecutive*-crash count
    (reset by a clean preemption: a replica that drained correctly is healthy,
    the next crash is a fresh incident, not an escalation), while
    ``fault.max_retries`` still bounds total crashes.

    With ``serve.fleet.enabled=True`` this becomes the fleet manager instead:
    front + N replicas, autoscaling, canary (``serve/fleet/manager.py``).
    """
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.obs.fleet import (
        FLEET_ENV_VAR,
        TRACE_ID_ENV_VAR,
        FleetAggregator,
        new_trace_id,
    )

    cfg = compose(config_name="serve_cli", overrides=overrides)
    if bool(((cfg.get("serve") or {}).get("fleet") or {}).get("enabled", False)):
        from sheeprl_tpu.serve.fleet.manager import supervise_fleet

        return supervise_fleet(overrides, cfg=cfg)
    f_cfg = fault_cfg(cfg)
    max_retries = int(f_cfg.get("max_retries", 3))
    max_preemptions = f_cfg.get("max_preemptions")  # None = respawn preemptions forever
    base_backoff = float(f_cfg.get("backoff_s", 2.0))
    max_backoff = float(f_cfg.get("backoff_max_s", 60.0))

    # Fleet telemetry across replica generations: replicas are stateless (no run
    # dir), so the supervisor only hosts an aggregator when obs.fleet.dir pins an
    # output location.  Each respawn reconnects to the same plane with a bumped
    # generation, so `obs.top` shows the replica lineage in one slot.
    fleet: Optional[Any] = None
    trace_id = os.environ.get(TRACE_ID_ENV_VAR) or new_trace_id()
    fleet_cfg = dict((cfg.get("obs") or {}).get("fleet") or {})
    if bool(fleet_cfg.get("enabled", True)) and fleet_cfg.get("dir"):
        try:
            fleet = FleetAggregator(
                str(fleet_cfg["dir"]),
                liveness_timeout_s=float(fleet_cfg.get("liveness_timeout_s", 10.0)),
                trace_id=trace_id,
                max_timeline_mb=float(fleet_cfg.get("max_timeline_mb", 64.0)),
            )
            _log(f"fleet telemetry at {fleet.address} -> {fleet_cfg['dir']}")
        except OSError as e:
            _log(f"fleet telemetry disabled: {e}")

    retries = 0  # total crashes, bounded by fault.max_retries
    preemptions = 0
    consecutive_crashes = 0  # backoff input; a clean preemption resets it
    summary: Dict[str, Any] = {
        "mode": "serve",
        "attempts": 0,
        "retries": 0,
        "preemptions": 0,
        "events": [],
        "outcome": None,
        "rc": None,
    }

    def _finish(outcome: str, rc: int) -> int:
        summary["outcome"] = outcome
        summary["rc"] = rc
        return rc

    try:
        while True:
            env = dict(os.environ)
            env[RESTARTS_ENV_VAR] = str(retries + preemptions)
            env[TRACE_ID_ENV_VAR] = trace_id
            env.pop(FLEET_ENV_VAR, None)
            if fleet is not None:
                env[FLEET_ENV_VAR] = fleet.address
            summary["attempts"] += 1
            _log(
                f"serve attempt {retries + preemptions + 1} "
                f"(retries={retries}/{max_retries}, preemptions={preemptions})"
            )
            proc = subprocess.run([sys.executable, "-m", "sheeprl_tpu.serve"] + overrides, env=env)
            rc = proc.returncode
            if rc == 0:
                _log("replica shut down cleanly")
                return _finish("clean", 0)
            if rc == RESUMABLE_EXIT_CODE:
                preemptions += 1
                consecutive_crashes = 0  # a correct drain proves the binary healthy
                summary["preemptions"] = preemptions
                summary["events"].append({"kind": "preemption", "rc": rc, "time": time.time()})
                if max_preemptions is not None and preemptions > int(max_preemptions):
                    _log(f"exceeded fault.max_preemptions={max_preemptions}; giving up")
                    return _finish("preemption_budget", rc)
                _log(f"replica drained on preemption (rc={rc}); respawning immediately")
                continue
            retries += 1
            consecutive_crashes += 1
            summary["retries"] = retries
            summary["events"].append({"kind": "crash", "rc": rc, "time": time.time()})
            if fleet is not None:
                try:
                    bundle = fleet.collect_blackboxes(f"serve_rc{rc}")
                    if bundle:
                        _log(f"fleet blackbox bundle: {bundle}")
                except Exception as e:
                    _log(f"fleet blackbox collection failed: {e}")
            if retries > max_retries:
                _log(f"exceeded fault.max_retries={max_retries}; giving up (rc={rc})")
                return _finish("retry_budget", rc if rc else 1)
            delay = backoff_seconds(consecutive_crashes, base_backoff, max_backoff)
            _log(
                f"replica died (rc={rc}); retry {retries}/{max_retries} "
                f"(consecutive crash {consecutive_crashes}) in {delay:.1f}s"
            )
            time.sleep(delay)
    except BaseException:
        if summary["outcome"] is None:
            summary["outcome"] = "supervisor_crashed"
        raise
    finally:
        write_supervisor_summary(f_cfg, summary)
        if fleet is not None:
            fleet.close()


def supervise(args: Optional[List[str]] = None) -> int:
    """The relaunch loop; returns the exit code to die with."""
    from sheeprl_tpu.config.core import compose

    overrides = list(args if args is not None else sys.argv[1:])
    if "--serve" in overrides:
        return supervise_serve([ov for ov in overrides if ov != "--serve"])
    if "-m" in overrides or "--multirun" in overrides:
        raise ValueError("the supervisor wraps a single run; use one supervisor per sweep job")
    # The supervisor owns retry accounting: children never self-resume, and the
    # run name is pinned so every attempt shares one run directory.
    overrides, _ = _strip_override(overrides, "fault.autoresume")
    cfg = compose(overrides=overrides)
    if not cfg.get("run_name"):
        import datetime

        stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
        cfg.run_name = f"{stamp}_{cfg.get('exp_name', 'run')}_{cfg.get('seed', 0)}_supervised"
    overrides, _ = _strip_override(overrides, "run_name")
    f_cfg = fault_cfg(cfg)
    max_retries = int(f_cfg.get("max_retries", 3))
    max_preemptions = f_cfg.get("max_preemptions")  # None = resume preemptions forever
    base_backoff = float(f_cfg.get("backoff_s", 2.0))
    max_backoff = float(f_cfg.get("backoff_max_s", 60.0))
    run_dir = run_dir_for(cfg)

    retries = 0  # crash relaunches, bounded by fault.max_retries
    preemptions = 0  # graceful resumes, unbounded unless fault.max_preemptions
    resume_from: Optional[str] = cfg.get("checkpoint", {}).get("resume_from")
    last_rc = 1
    while True:
        attempt_overrides = list(overrides) + [f"run_name={cfg.run_name}", "fault.autoresume=False"]
        if resume_from:
            attempt_overrides = [
                ov for ov in attempt_overrides if not ov.startswith("checkpoint.resume_from=")
            ] + [f"checkpoint.resume_from={resume_from}"]
        env = dict(os.environ)
        env[RESTARTS_ENV_VAR] = str(retries + preemptions)
        attempt_start = time.time()
        _log(
            f"attempt {retries + preemptions + 1} (retries={retries}/{max_retries}, "
            f"preemptions={preemptions})"
            + (f", resuming from {resume_from}" if resume_from else "")
        )
        proc = subprocess.run([sys.executable, "-m", "sheeprl_tpu"] + attempt_overrides, env=env)
        last_rc = proc.returncode

        meta = None
        if last_rc not in (0, RESUMABLE_EXIT_CODE):
            meta = _classify.read_blackbox_meta(run_dir)
            if meta is not None and float(meta.get("time", 0) or 0) < attempt_start - 1:
                meta = None  # stale dump from an earlier attempt: not this death's story
        verdict = _classify.classify_exit(last_rc, meta)

        if verdict == _classify.DONE:
            _log("run completed")
            return 0
        if verdict == _classify.FATAL:
            exc = ((meta or {}).get("exception") or {}).get("type", "unknown")
            _log(f"fatal failure ({exc}, rc={last_rc}): retrying would replay it deterministically; giving up")
            return last_rc if last_rc else 1
        if verdict == _classify.RESUME:
            preemptions += 1
            if max_preemptions is not None and preemptions > int(max_preemptions):
                _log(f"exceeded fault.max_preemptions={max_preemptions}; giving up")
                return last_rc
            _log(f"graceful preemption (rc={last_rc}); resuming immediately")
        else:  # RETRY
            retries += 1
            if retries > max_retries:
                _log(f"exceeded fault.max_retries={max_retries}; giving up (rc={last_rc})")
                return last_rc if last_rc else 1
            delay = backoff_seconds(retries, base_backoff, max_backoff)
            _log(f"transient failure (rc={last_rc}); retry {retries}/{max_retries} in {delay:.1f}s")
            time.sleep(delay)

        ckpt = find_resume_checkpoint(run_dir)
        if ckpt is None:
            _log("no valid checkpoint yet; restarting from scratch")
            resume_from = None
        else:
            resume_from = str(ckpt)


def main(args: Optional[List[str]] = None) -> None:
    sys.exit(supervise(args))
