"""Ring attention: sequence-parallel exact attention over the ``sequence`` mesh axis.

The reference framework has no attention module and no context parallelism at all
(SURVEY §2.4/§5: "no TP/PP/SP/EP/CP/ring-attention anywhere") — long-context support is
a capability this framework adds natively.  The ``sequence`` axis reserved by
``build_mesh`` becomes usable: queries stay put, key/value blocks rotate around the
ring (``lax.ppermute`` over ICI neighbours), and a flash-style online-softmax
accumulator keeps the result EXACT while each device only ever holds ``T/ring`` keys —
memory per device is O(T·d/ring + T²/ring²) instead of O(T²).

Shapes follow the usual convention: ``q, k, v: [B, T_local, H, D]`` sharded over the
time axis (``PartitionSpec(None, "sequence")``).  ``ring_attention`` is the per-device
function for use inside ``shard_map``; ``make_ring_attention`` wraps it with the
``shard_map`` plumbing for a given mesh.  Causal masking uses global positions, so the
semantics match full causal attention regardless of the ring size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _attn_block(q, k_blk, v_blk, acc, m, l, scale, q_pos, kv_pos, causal):
    """One flash-attention accumulation step against a single kv block.

    ``acc``: [B, H, Tq, D] un-normalised output; ``m``: [B, H, Tq] running max;
    ``l``: [B, H, Tq] running denominator."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale  # [B, H, Tq, Tk]
    if causal:
        mask = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None])
    if causal:
        # re-mask: a fully-masked row has s == m_new == finfo.min everywhere, so the
        # exp above would contribute p = 1 per masked entry without this zeroing
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(-1)
    acc = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
    return acc, m_new, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sequence",
    causal: bool = False,
) -> jax.Array:
    """Per-device ring attention body (call inside ``shard_map``).

    ``q, k, v``: the LOCAL ``[B, T_local, H, D]`` blocks of a global ``[B, T, H, D]``
    sequence sharded over ``axis_name``.  Returns the local ``[B, T_local, H, D]``
    output of exact (optionally causal) attention over the full sequence.
    """
    ring = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, T_local, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))  # f32, matching the accumulators

    q_pos = my_idx * T_local + jnp.arange(T_local)
    acc = jnp.zeros((B, H, T_local, D), jnp.float32)
    m = jnp.full((B, H, T_local), jnp.finfo(jnp.float32).min)
    l = jnp.zeros((B, H, T_local))

    qf, kf, vf = q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    perm = [(i, (i + 1) % ring) for i in range(ring)]
    k_blk, v_blk = kf, vf
    for r in range(ring):
        src = (my_idx - r) % ring  # which device's kv block we currently hold
        kv_pos = src * T_local + jnp.arange(T_local)
        acc, m, l = _attn_block(qf, k_blk, v_blk, acc, m, l, scale, q_pos, kv_pos, causal)
        if r + 1 < ring:
            # rotate kv around the ring; overlaps with the next block's compute
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tq, D]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "sequence", causal: bool = False):
    """Wrap ``ring_attention`` in ``shard_map`` for ``[B, T, H, D]`` inputs sharded
    over ``axis_name`` on ``mesh`` (time axis 1)."""
    spec = P(None, axis_name)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )

    def apply(q, k, v):
        sharding = NamedSharding(mesh, spec)
        return fn(jax.device_put(q, sharding), jax.device_put(k, sharding), jax.device_put(v, sharding))

    return apply


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False) -> jax.Array:
    """Plain full-materialisation attention for parity checks."""
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / jnp.sqrt(jnp.asarray(D, jnp.float32))
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
