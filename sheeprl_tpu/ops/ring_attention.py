"""Ring attention: sequence-parallel exact attention over the ``sequence`` mesh axis.

The reference framework has no attention module and no context parallelism at all
(SURVEY §2.4/§5: "no TP/PP/SP/EP/CP/ring-attention anywhere") — long-context support is
a capability this framework adds natively.  The ``sequence`` axis reserved by
``build_mesh`` becomes usable: queries stay put, key/value blocks rotate around the
ring (``lax.ppermute`` over ICI neighbours), and a flash-style online-softmax
accumulator keeps the result EXACT while each device only ever holds ``T/ring`` keys —
memory per device is O(T·d/ring + T²/ring²) instead of O(T²).

Shapes follow the usual convention: ``q, k, v: [B, T_local, H, D]`` sharded over the
time axis (``PartitionSpec(None, "sequence")``).  ``ring_attention`` is the per-device
function for use inside ``shard_map``; ``make_ring_attention`` wraps it with the
``shard_map`` plumbing for a given mesh.  Causal masking uses global positions, so the
semantics match full causal attention regardless of the ring size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_mask(q_pos, kv_pos, causal, q_seg=None, kv_seg=None, window=None):
    """[B?, Tq, Tk] boolean mask combining causality, segment equality (episode
    boundaries) and a sliding attention window; None when nothing masks."""
    mask = None
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Tq, Tk]
    if window is not None:
        # A window always excludes the future too ("the LAST `window` positions"),
        # so window-only attention is causal-windowed by construction.
        delta = q_pos[:, None] - kv_pos[None, :]
        w = (delta >= 0) & (delta < window)
        mask = w if mask is None else (mask & w)
    if mask is not None:
        mask = mask[None]  # broadcast over batch
    if q_seg is not None:
        seg = q_seg[:, :, None] == kv_seg[:, None, :]  # [B, Tq, Tk]
        mask = seg if mask is None else (mask & seg)
    return mask


def _attn_block(q, k_blk, v_blk, acc, m, l, scale, mask):
    """One flash-attention accumulation step against a single kv block.

    ``acc``: [B, H, Tq, D] un-normalised output; ``m``: [B, H, Tq] running max;
    ``l``: [B, H, Tq] running denominator; ``mask``: [B|1, Tq, Tk] or None."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale  # [B, H, Tq, Tk]
    if mask is not None:
        s = jnp.where(mask[:, None], s, jnp.finfo(s.dtype).min)
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        # re-mask: a fully-masked row has s == m_new == finfo.min everywhere, so the
        # exp above would contribute p = 1 per masked entry without this zeroing
        p = jnp.where(mask[:, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(-1)
    acc = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
    return acc, m_new, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array = None,
    axis_name: str = "sequence",
    causal: bool = False,
    window: int = None,
) -> jax.Array:
    """Per-device ring attention body (call inside ``shard_map``).

    ``q, k, v``: the LOCAL ``[B, T_local, H, D]`` blocks of a global ``[B, T, H, D]``
    sequence sharded over ``axis_name``; ``segment_ids``: optional local ``[B,
    T_local]`` int segments (attention never crosses a segment boundary — episode
    masking); ``window``: optional sliding-window size (a query attends to at most
    the last ``window`` positions).  Returns the local ``[B, T_local, H, D]`` output
    of exact attention over the full sequence under those masks.
    """
    ring = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, T_local, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))  # f32, matching the accumulators

    q_pos = my_idx * T_local + jnp.arange(T_local)
    acc = jnp.zeros((B, H, T_local, D), jnp.float32)
    m = jnp.full((B, H, T_local), jnp.finfo(jnp.float32).min)
    l = jnp.zeros((B, H, T_local))

    qf, kf, vf = q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    perm = [(i, (i + 1) % ring) for i in range(ring)]
    k_blk, v_blk = kf, vf
    kv_seg = segment_ids
    for r in range(ring):
        src = (my_idx - r) % ring  # which device's kv block we currently hold
        kv_pos = src * T_local + jnp.arange(T_local)
        mask = _block_mask(q_pos, kv_pos, causal, segment_ids, kv_seg, window)
        acc, m, l = _attn_block(qf, k_blk, v_blk, acc, m, l, scale, mask)
        if r + 1 < ring:
            # rotate kv (and its segments) around the ring; overlaps with the next
            # block's compute
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            if kv_seg is not None:
                kv_seg = jax.lax.ppermute(kv_seg, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tq, D]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "sequence", causal: bool = False, window: int = None
):
    """Wrap ``ring_attention`` in ``shard_map`` for ``[B, T, H, D]`` inputs sharded
    over ``axis_name`` on ``mesh`` (time axis 1); optional ``[B, T]``
    ``segment_ids``."""
    spec = P(None, axis_name)
    body = functools.partial(ring_attention, axis_name=axis_name, causal=causal, window=window)
    from sheeprl_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    fn_seg = shard_map_compat(body, mesh=mesh, in_specs=(spec, spec, spec, spec), out_specs=spec)

    def apply(q, k, v, segment_ids=None):
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        if segment_ids is None:
            return fn(q, k, v)
        return fn_seg(q, k, v, jax.device_put(segment_ids, sharding))

    return apply


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    segment_ids: jax.Array = None,
    window: int = None,
) -> jax.Array:
    """Plain full-materialisation attention (same masks as ``ring_attention``) —
    the single-device path and the parity oracle for the ring."""
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / jnp.sqrt(jnp.asarray(D, jnp.float32))
    pos = jnp.arange(T)
    mask = _block_mask(pos, pos, causal, segment_ids, segment_ids, window)
    if mask is not None:
        s = jnp.where(mask[:, None], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, -1)
    if mask is not None:
        p = jnp.where(mask[:, None], p, 0.0)  # fully-masked rows attend to nothing
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
