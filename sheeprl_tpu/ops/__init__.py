"""TPU-native kernels (Pallas) for the framework's hot ops.

Currently: ``gru.fused_layernorm_gru`` — the RSSM GRU cell's post-matmul chain
(LayerNorm + gates + state blend) as one VMEM pass.  Default ``auto``: enabled on real
TPU backends (measured +2.8% on the full DV3-S train step), off elsewhere; override
with ``SHEEPRL_TPU_FUSED_GRU=0|1``.
"""

from __future__ import annotations

import os


def fused_gru_enabled() -> bool:
    flag = os.environ.get("SHEEPRL_TPU_FUSED_GRU", "auto").lower()
    if flag in ("1", "true", "yes", "on"):
        return True
    if flag == "auto":
        import jax

        return jax.default_backend() == "tpu"
    return False
