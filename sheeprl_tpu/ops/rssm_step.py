"""Fully-fused RSSM GRU step (Pallas, TPU): matmul + LayerNorm + gates in ONE kernel.

VERDICT r4 #4: the post-matmul fusion (``ops/gru.py``) lifts size-S MFU only ~3%
because the ``[B, K] @ [K, 3H]`` projection still runs as its own tiny XLA GEMM with
an HBM round trip for the ``[B, 3H]`` intermediate between it and the gate chain.
This kernel keeps the WHOLE step VMEM-resident: weights (``[K, 3H]`` bf16, ~3 MB at
size S), the concat input row block, the projection, and the gate chain never touch
HBM between the matmul and the new state.

The matmul still uses the MXU (``jnp.dot`` inside the kernel lowers to MXU ops); the
fusion removes per-step kernel boundaries and intermediate materialisation — the two
costs XLA cannot always eliminate across a ``lax.scan`` step boundary.

Hand-derived VJP (single kernel for the backward too): recomputes the projection and
LN/gate intermediates in VMEM from the saved ``(xh, h)`` residuals, then forms
``dW = xhᵀ @ dp`` and ``dxh = dp @ Wᵀ`` on the MXU in the same pass.

Single-tile kernel (whole batch in one block): the RSSM scan runs at B = 16–64 rows,
far under one (8, 128) tile budget in VMEM; ``fused_step_supported`` gates callers.
Reference hot loop: ``/root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:134-145``
(the 64-step recurrent unroll this step implements one iteration of).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from sheeprl_tpu.ops.gru import _gates, _ln


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(xh_ref, h_ref, w_ref, gamma_ref, beta_ref, out_ref, *, hidden: int, eps: float):
    xh = xh_ref[:]
    w = w_ref[:]
    # MXU matmul with f32 accumulation; everything downstream in f32 in VMEM.
    proj = jnp.dot(xh, w, preferred_element_type=jnp.float32)
    n, _, _ = _ln(proj, gamma_ref[:].astype(jnp.float32), beta_ref[:].astype(jnp.float32), eps)
    out, _, _, _ = _gates(n, h_ref[:].astype(jnp.float32), hidden)
    out_ref[:] = out.astype(out_ref.dtype)


def _bwd_kernel(
    xh_ref,
    h_ref,
    w_ref,
    gamma_ref,
    beta_ref,
    g_ref,
    dxh_ref,
    dh_ref,
    dw_ref,
    dgamma_ref,
    dbeta_ref,
    *,
    hidden: int,
    eps: float,
):
    xh = xh_ref[:]
    h = h_ref[:].astype(jnp.float32)
    w = w_ref[:]
    gamma = gamma_ref[:].astype(jnp.float32)
    beta = beta_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)

    # Recompute forward intermediates in VMEM (cheaper than storing them per step).
    proj = jnp.dot(xh, w, preferred_element_type=jnp.float32)
    n, unit, inv = _ln(proj, gamma, beta, eps)
    _, reset, cand, update = _gates(n, h, hidden)

    # Gate chain backward.
    dh = g * (1.0 - update)
    du = g * (cand - h)
    dn_u = du * update * (1.0 - update)
    dcand = g * update
    dtanh = dcand * (1.0 - jnp.square(cand))
    n_c = n[:, hidden : 2 * hidden]
    dreset = dtanh * n_c
    dn_c = dtanh * reset
    dn_r = dreset * reset * (1.0 - reset)
    dn = jnp.concatenate([dn_r, dn_c, dn_u], axis=-1)

    # LayerNorm backward.
    dg_hat = dn * gamma
    m1 = jnp.mean(dg_hat, -1, keepdims=True)
    m2 = jnp.mean(dg_hat * unit, -1, keepdims=True)
    dp = (dg_hat - m1 - unit * m2) * inv

    # Matmul backward on the MXU, still VMEM-resident.
    dxh_ref[:] = jnp.dot(dp.astype(xh.dtype), w.T, preferred_element_type=jnp.float32).astype(dxh_ref.dtype)
    dw_ref[:] = jnp.dot(xh.T, dp.astype(xh.dtype), preferred_element_type=jnp.float32).astype(dw_ref.dtype)
    dh_ref[:] = dh.astype(dh_ref.dtype)
    dgamma_ref[:] = jnp.sum(dn * unit, axis=0, keepdims=True).astype(dgamma_ref.dtype)
    dbeta_ref[:] = jnp.sum(dn, axis=0, keepdims=True).astype(dbeta_ref.dtype)


def fused_step_supported(batch: int, in_features: int, hidden: int, itemsize: int = 4) -> bool:
    """Single-tile budget: batch within one grid step and the working set
    (weights + activations + grads, f32-dominated in the backward) inside a
    conservative 12 MB VMEM envelope."""
    three_h = 3 * hidden
    working = (
        in_features * three_h * itemsize  # W (+ dW in bwd, covered by the margin)
        + batch * (in_features + three_h * 3 + hidden * 3) * 4
    )
    return batch <= 256 and working * 2 <= 12 * 1024 * 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_gru_step(
    xh: jax.Array, h: jax.Array, w: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float = 1e-3
) -> jax.Array:
    """``h' = GRUGates(LN(xh @ w) * gamma + beta, h)`` — one VMEM-resident kernel.

    ``xh``: [B, K] concat(input, h); ``w``: [K, 3H]; ``h``: [B, H];
    ``gamma``/``beta``: [3H].  Returns [B, H].
    """
    return _fused_step_fwd(xh, h, w, gamma, beta, eps)[0]


def _specs(batch, k, hidden):
    three_h = 3 * hidden
    return [
        pl.BlockSpec((batch, k), lambda: (0, 0)),
        pl.BlockSpec((batch, hidden), lambda: (0, 0)),
        pl.BlockSpec((k, three_h), lambda: (0, 0)),
        pl.BlockSpec((three_h,), lambda: (0,)),
        pl.BlockSpec((three_h,), lambda: (0,)),
    ]


def _fused_step_fwd(xh, h, w, gamma, beta, eps=1e-3):
    batch, k = xh.shape
    hidden = h.shape[-1]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, hidden=hidden, eps=eps),
        in_specs=_specs(batch, k, hidden),
        out_specs=pl.BlockSpec((batch, hidden), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((batch, hidden), h.dtype),
        interpret=_interpret(),
    )(xh, h, w, gamma, beta)
    return out, (xh, h, w, gamma, beta)


def _fused_step_bwd(eps, residuals, g):
    xh, h, w, gamma, beta = residuals
    batch, k = xh.shape
    hidden = h.shape[-1]
    three_h = 3 * hidden
    dxh, dh, dw, dgamma, dbeta = pl.pallas_call(
        functools.partial(_bwd_kernel, hidden=hidden, eps=eps),
        in_specs=_specs(batch, k, hidden) + [pl.BlockSpec((batch, hidden), lambda: (0, 0))],
        out_specs=[
            pl.BlockSpec((batch, k), lambda: (0, 0)),
            pl.BlockSpec((batch, hidden), lambda: (0, 0)),
            pl.BlockSpec((k, three_h), lambda: (0, 0)),
            pl.BlockSpec((1, three_h), lambda: (0, 0)),
            pl.BlockSpec((1, three_h), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, k), xh.dtype),
            jax.ShapeDtypeStruct((batch, hidden), h.dtype),
            jax.ShapeDtypeStruct((k, three_h), w.dtype),
            jax.ShapeDtypeStruct((1, three_h), jnp.float32),
            jax.ShapeDtypeStruct((1, three_h), jnp.float32),
        ],
        interpret=_interpret(),
    )(xh, h, w, gamma, beta, g)
    return dxh, dh, dw, dgamma[0].astype(gamma.dtype), dbeta[0].astype(beta.dtype)


fused_gru_step.defvjp(_fused_step_fwd, _fused_step_bwd)


def reference_gru_step(xh, h, w, gamma, beta, eps: float = 1e-3):
    """Plain-XLA same math: the parity target and the non-fused fallback."""
    proj = jnp.dot(xh, w, preferred_element_type=jnp.float32)
    n, _, _ = _ln(proj, gamma.astype(jnp.float32), beta.astype(jnp.float32), eps)
    out, _, _, _ = _gates(n, h.astype(jnp.float32), h.shape[-1])
    return out.astype(h.dtype)
