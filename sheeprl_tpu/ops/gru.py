"""Fused LayerNorm-GRU gate kernel (Pallas, TPU).

The RSSM's hot loop (SURVEY §3.1 hot loop 1: 64 sequential GRU steps per gradient
step) is ``h' = GRUGates(LayerNorm(concat(x, h) @ W), h)``.  The matmul belongs on the
MXU and is left to XLA; everything AFTER it — LayerNorm over the fused ``3H``
projection, the three gate nonlinearities and the state blend — is a chain of
HBM-bandwidth-bound elementwise ops.  This kernel runs that whole chain in ONE VMEM
pass per batch tile (one HBM read of the projection + one write of the new state,
instead of XLA's worst case of several intermediate materialisations inside a scan).

A hand-derived VJP keeps it differentiable: the backward kernel recomputes the LN/gate
intermediates in VMEM from the saved ``(proj, h)`` residuals — rematerialisation is
cheaper than storing five intermediates per scan step.

Used by ``LayerNormGRUCell`` (``sheeprl_tpu/models/blocks.py``) when the
``SHEEPRL_TPU_FUSED_GRU`` switch is on (default ``auto`` = TPU backends only), or call
``fused_layernorm_gru(proj, h, gamma, beta, eps)`` directly.  Off-TPU backends run the
same kernel in interpreter mode, so tests exercise identical code paths.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ln(p: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float) -> Tuple[jax.Array, jax.Array, jax.Array]:
    mean = jnp.mean(p, -1, keepdims=True)
    var = jnp.mean(jnp.square(p - mean), -1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    unit = (p - mean) * inv
    return unit * gamma + beta, unit, inv


def _gates(n: jax.Array, h: jax.Array, hidden: int):
    reset = jax.nn.sigmoid(n[..., :hidden])
    cand = jnp.tanh(reset * n[..., hidden : 2 * hidden])
    update = jax.nn.sigmoid(n[..., 2 * hidden :] - 1.0)
    out = update * cand + (1.0 - update) * h
    return out, reset, cand, update


def _fwd_kernel(proj_ref, h_ref, gamma_ref, beta_ref, out_ref, *, hidden: int, eps: float):
    p = proj_ref[:].astype(jnp.float32)
    n, _, _ = _ln(p, gamma_ref[:].astype(jnp.float32), beta_ref[:].astype(jnp.float32), eps)
    out, _, _, _ = _gates(n, h_ref[:].astype(jnp.float32), hidden)
    out_ref[:] = out.astype(out_ref.dtype)


def _bwd_kernel(proj_ref, h_ref, gamma_ref, beta_ref, g_ref, dproj_ref, dh_ref, dgamma_ref, dbeta_ref, *, hidden: int, eps: float):
    p = proj_ref[:].astype(jnp.float32)
    h = h_ref[:].astype(jnp.float32)
    gamma = gamma_ref[:].astype(jnp.float32)
    beta = beta_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)

    # Recompute the forward intermediates in VMEM.
    n, unit, inv = _ln(p, gamma, beta, eps)
    _, reset, cand, update = _gates(n, h, hidden)

    # Gate gradients.
    dh = g * (1.0 - update)
    du = g * (cand - h)
    dn_u = du * update * (1.0 - update)
    dcand = g * update
    dtanh = dcand * (1.0 - jnp.square(cand))
    n_c = n[:, hidden : 2 * hidden]
    dreset = dtanh * n_c
    dn_c = dtanh * reset
    dn_r = dreset * reset * (1.0 - reset)
    dn = jnp.concatenate([dn_r, dn_c, dn_u], axis=-1)

    # LayerNorm backward (per-row statistics over the fused 3H axis).
    dg_hat = dn * gamma
    m1 = jnp.mean(dg_hat, -1, keepdims=True)
    m2 = jnp.mean(dg_hat * unit, -1, keepdims=True)
    dp = (dg_hat - m1 - unit * m2) * inv

    dproj_ref[:] = dp.astype(dproj_ref.dtype)
    dh_ref[:] = dh.astype(dh_ref.dtype)
    # Per-tile partial parameter gradients; summed over the grid outside.
    dgamma_ref[:] = jnp.sum(dn * unit, axis=0, keepdims=True).astype(dgamma_ref.dtype)
    dbeta_ref[:] = jnp.sum(dn, axis=0, keepdims=True).astype(dbeta_ref.dtype)


def _block(batch: int) -> int:
    for tile in (256, 128, 64, 32, 16, 8):
        if batch % tile == 0:
            return tile
    return batch


def fused_supported(batch: int) -> bool:
    """The kernel runs single-tile only: the backward's per-tile ``dgamma``/``dbeta``
    partials have ``[1, 3H]`` blocks, which Mosaic rejects when the grid has more
    than one tile (first block dim 1 is neither 8-divisible nor the array dim).
    Multi-tile batches (e.g. the continuous-actor imagination path at T*B rows)
    fall back to the reference implementation."""
    return _block(batch) == batch and batch <= 256


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_layernorm_gru(proj: jax.Array, h: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float = 1e-3) -> jax.Array:
    """``h' = GRUGates(LN(proj) * gamma + beta, h)`` fused in one VMEM pass.

    ``proj``: [B, 3H] fused projection of ``concat(x, h)``; ``h``: [B, H];
    ``gamma``/``beta``: [3H] LayerNorm parameters.  Returns [B, H].
    """
    return _fused_fwd(proj, h, gamma, beta, eps)[0]


def _fused_fwd(proj, h, gamma, beta, eps=1e-3):
    batch, three_h = proj.shape
    hidden = three_h // 3
    bt = _block(batch)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, hidden=hidden, eps=eps),
        grid=(batch // bt,),
        in_specs=[
            pl.BlockSpec((bt, three_h), lambda i: (i, 0)),
            pl.BlockSpec((bt, hidden), lambda i: (i, 0)),
            pl.BlockSpec((three_h,), lambda i: (0,)),
            pl.BlockSpec((three_h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((bt, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch, hidden), h.dtype),
        interpret=_interpret(),
    )(proj, h, gamma, beta)
    return out, (proj, h, gamma, beta)


def _fused_bwd(eps, residuals, g):
    proj, h, gamma, beta = residuals
    batch, three_h = proj.shape
    hidden = three_h // 3
    bt = _block(batch)
    n_tiles = batch // bt
    dproj, dh, dgamma_t, dbeta_t = pl.pallas_call(
        functools.partial(_bwd_kernel, hidden=hidden, eps=eps),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((bt, three_h), lambda i: (i, 0)),
            pl.BlockSpec((bt, hidden), lambda i: (i, 0)),
            pl.BlockSpec((three_h,), lambda i: (0,)),
            pl.BlockSpec((three_h,), lambda i: (0,)),
            pl.BlockSpec((bt, hidden), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, three_h), lambda i: (i, 0)),
            pl.BlockSpec((bt, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, three_h), lambda i: (i, 0)),
            pl.BlockSpec((1, three_h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, three_h), proj.dtype),
            jax.ShapeDtypeStruct((batch, hidden), h.dtype),
            jax.ShapeDtypeStruct((n_tiles, three_h), jnp.float32),
            jax.ShapeDtypeStruct((n_tiles, three_h), jnp.float32),
        ],
        interpret=_interpret(),
    )(proj, h, gamma, beta, g)
    return dproj, dh, dgamma_t.sum(0).astype(gamma.dtype), dbeta_t.sum(0).astype(beta.dtype)


fused_layernorm_gru.defvjp(_fused_fwd, _fused_bwd)


def reference_layernorm_gru(
    proj: jax.Array, h: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float = 1e-3
) -> jax.Array:
    """Plain-XLA implementation of the same math (f32 statistics, any batch rank);
    also the LayerNormGRUCell's non-fused path — parity is structural, not test-only."""
    p = proj.astype(jnp.float32)
    n, _, _ = _ln(p, gamma.astype(jnp.float32), beta.astype(jnp.float32), eps)
    out, _, _, _ = _gates(n, h.astype(jnp.float32), h.shape[-1])
    return out.astype(h.dtype)
