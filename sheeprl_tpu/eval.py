"""Evaluation launcher (reference ``sheeprl_eval.py`` / console script ``sheeprl-eval``):

    python -m sheeprl_tpu.eval checkpoint_path=<run>/checkpoints/ckpt_N [overrides]

Loads the run's saved config, merges the overrides, and dispatches to the algorithm's
registered evaluation entry point (reference ``cli.py:202,369``).
"""

from sheeprl_tpu.cli import evaluate

if __name__ == "__main__":
    evaluate()
