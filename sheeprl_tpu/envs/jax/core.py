"""Pure-functional JAX environments — the device half of the Anakin architecture
(Podracer, arxiv 2104.06272; ROADMAP item 1).

The host envs (``sheeprl_tpu/utils/env.py``) step numpy worlds one python call at
a time; PROFILE_r05 §1 measures that wall at ~150 ms/iteration plus ~125 ms of
player round trip.  A :class:`JaxEnv` instead expresses the WHOLE environment as
a pure function over a small state pytree::

    params = env.default_params()
    state, obs = env.reset(params, key)
    state, obs, reward, done, info = env.step(params, state, action, key)

so N instances vmap into one tensor program and the entire act→step→learn loop
compiles into a single ``lax.scan`` dispatch (``sheeprl_tpu/engine/anakin.py``)
— zero host work per env step.

Contract:

* ``state`` is a NamedTuple of arrays (vmappable, checkpointable through
  ``CheckpointManager`` as a plain device pytree); it carries its own step
  counter, so the gymnasium ``TimeLimit`` wrapper has an in-graph equivalent;
* ``step`` NEVER branches in python on traced values (jaxlint JL002): episode
  ends surface as the ``done`` flag and :meth:`JaxEnv.step_autoreset` folds the
  reset in with the ``lax.cond``/``lax.select`` idiom below;
* ``info`` is a small dict of arrays with at least ``terminated``/``truncated``
  (SAC's TD target masks on terminated only, like the host loops) and
  ``final_obs`` — the TRUE pre-reset observation of the finishing step, the
  in-graph analogue of the vector envs' SAME_STEP ``info["final_obs"]``;
* spaces are reported as gymnasium spaces so the existing agent builders work
  unchanged, and the reset distribution matches the gymnasium counterpart
  (documented per env) so host-vs-device runs are statistically comparable.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp


class JaxEnv:
    """Base class for pure-functional envs; subclasses implement ``default_params``,
    ``reset``, ``step`` and the two space properties."""

    name: str = "jax_env"

    def default_params(self) -> NamedTuple:
        raise NotImplementedError

    def reset(self, params: NamedTuple, key: jax.Array) -> Tuple[NamedTuple, jax.Array]:
        raise NotImplementedError

    def step(
        self, params: NamedTuple, state: NamedTuple, action: jax.Array, key: jax.Array
    ) -> Tuple[NamedTuple, jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
        raise NotImplementedError

    def observation_space(self, params: NamedTuple) -> gym.spaces.Box:
        raise NotImplementedError

    def action_space(self, params: NamedTuple) -> gym.spaces.Space:
        raise NotImplementedError

    def step_autoreset(
        self, params: NamedTuple, state: NamedTuple, action: jax.Array, key: jax.Array
    ) -> Tuple[NamedTuple, jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
        """Step with SAME_STEP auto-reset: on ``done`` the returned state/obs are a
        fresh reset (reward and ``info["final_obs"]`` still describe the finishing
        step).  Both branches are computed and ``lax.select``'d — the reset is a
        few FLOPs, and a data-dependent ``lax.cond`` would block vmap batching
        (under vmap it lowers to both branches anyway)."""
        key_step, key_reset = jax.random.split(key)
        stepped, obs_st, reward, done, info = self.step(params, state, action, key_step)
        reset_state, reset_obs = self.reset(params, key_reset)
        state = jax.tree.map(lambda r, s: jax.lax.select(done, r, s), reset_state, stepped)
        obs = jax.lax.select(done, reset_obs, obs_st)
        info = {**info, "final_obs": obs_st}
        return state, obs, reward, done, info

    def sample_action(self, params: NamedTuple, key: jax.Array) -> jax.Array:
        """Uniform random action draw (the prefill analogue of
        ``action_space.sample()``), jittable so prefill scans stay on device."""
        space = self.action_space(params)
        if isinstance(space, gym.spaces.Discrete):
            return jax.random.randint(key, (), 0, int(space.n), dtype=jnp.int32)
        low = jnp.asarray(space.low, jnp.float32)
        high = jnp.asarray(space.high, jnp.float32)
        return jax.random.uniform(key, space.shape, jnp.float32, low, high)


def time_limit(params: NamedTuple, time: jax.Array, terminated: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """In-graph ``TimeLimit``: given the post-step ``time`` counter, return
    ``(truncated, done)``.  ``params.max_episode_steps <= 0`` disables it."""
    max_steps = jnp.asarray(params.max_episode_steps, jnp.int32)
    truncated = jnp.logical_and(max_steps > 0, time >= max_steps)
    truncated = jnp.logical_and(truncated, jnp.logical_not(terminated))
    return truncated, jnp.logical_or(terminated, truncated)
