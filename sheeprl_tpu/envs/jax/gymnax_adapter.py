"""Adapter exposing external `gymnax <https://github.com/RobertTLange/gymnax>`_
environments through the in-tree :class:`~sheeprl_tpu.envs.jax.core.JaxEnv`
protocol, so every gymnax env plugs straight into the Anakin engine
(``env.jax.env_id=gymnax:<EnvName>``).  gymnax is an optional dependency —
importing this module without it raises with an actionable message, and the
in-tree classic-control envs never touch it."""

from __future__ import annotations

from typing import Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv


def _to_gym_space(space) -> gym.spaces.Space:
    """gymnax spaces → gymnasium spaces (Box/Discrete cover the gymnax registry)."""
    kind = type(space).__name__
    if kind == "Discrete":
        return gym.spaces.Discrete(int(space.n))
    if kind == "Box":
        low = np.broadcast_to(np.asarray(space.low, np.float32), space.shape)
        high = np.broadcast_to(np.asarray(space.high, np.float32), space.shape)
        return gym.spaces.Box(low, high, shape=tuple(space.shape), dtype=np.float32)
    raise ValueError(f"Unsupported gymnax space for the Anakin engine: {space!r}")


class GymnaxAdapter(JaxEnv):
    """Wrap ``gymnax.make(env_id)``: argument order is remapped (gymnax steps as
    ``step_env(key, state, action, params)``), auto-reset is left to
    :meth:`JaxEnv.step_autoreset` (gymnax's own ``step`` folds a reset in with a
    different final-obs convention), and ``done`` is exposed as ``terminated``
    (gymnax predates the terminated/truncated split)."""

    def __init__(self, env_id: str, **env_kwargs):
        try:
            import gymnax
        except ImportError as exc:  # pragma: no cover - exercised only without gymnax
            raise ImportError(
                f"env id 'gymnax:{env_id}' needs the optional gymnax package "
                "(pip install gymnax); the in-tree jax envs (cartpole, pendulum, "
                "mountain_car_continuous) work without it."
            ) from exc
        self._env, self._default_params = gymnax.make(env_id, **env_kwargs)
        self.name = f"gymnax_{env_id}"

    def default_params(self):
        return self._default_params

    def reset(self, params, key: jax.Array) -> Tuple:
        obs, state = self._env.reset_env(key, params)
        return state, jnp.asarray(obs, jnp.float32)

    def step(self, params, state, action: jax.Array, key: jax.Array):
        obs, new_state, reward, done, info = self._env.step_env(key, state, action, params)
        done = jnp.asarray(done, bool)
        info = {**info, "terminated": done, "truncated": jnp.zeros((), bool)}
        return new_state, jnp.asarray(obs, jnp.float32), jnp.asarray(reward, jnp.float32), done, info

    def observation_space(self, params) -> gym.spaces.Space:
        return _to_gym_space(self._env.observation_space(params))

    def action_space(self, params) -> gym.spaces.Space:
        return _to_gym_space(self._env.action_space(params))
