"""Pure-JAX continuous Mountain Car, parity-matched to gymnasium
``MountainCarContinuous-v0`` (sparse +100 goal reward minus a quadratic action
cost; in-graph ``TimeLimit(999)``).  Reset distribution equivalence: gymnasium
draws ``position ~ U(-0.6, -0.4)`` with zero velocity — so does
:meth:`MountainCarContinuous.reset`."""

from __future__ import annotations

from typing import NamedTuple, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv, time_limit


class MountainCarParams(NamedTuple):
    min_position: float = -1.2
    max_position: float = 0.6
    max_speed: float = 0.07
    goal_position: float = 0.45
    goal_velocity: float = 0.0
    power: float = 0.0015
    max_episode_steps: int = 999


class MountainCarState(NamedTuple):
    position: jax.Array
    velocity: jax.Array
    time: jax.Array


class MountainCarContinuous(JaxEnv):
    name = "mountain_car_continuous"

    def default_params(self) -> MountainCarParams:
        return MountainCarParams()

    def reset(self, params: MountainCarParams, key: jax.Array) -> Tuple[MountainCarState, jax.Array]:
        position = jax.random.uniform(key, (), jnp.float32, -0.6, -0.4)
        state = MountainCarState(position, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))
        return state, self._obs(state)

    @staticmethod
    def _obs(state: MountainCarState) -> jax.Array:
        return jnp.stack([state.position, state.velocity]).astype(jnp.float32)

    def step(self, params: MountainCarParams, state: MountainCarState, action: jax.Array, key: jax.Array):
        force = jnp.clip(jnp.asarray(action, jnp.float32).reshape(-1)[0], -1.0, 1.0)
        velocity = state.velocity + force * params.power - 0.0025 * jnp.cos(3 * state.position)
        velocity = jnp.clip(velocity, -params.max_speed, params.max_speed)
        position = jnp.clip(state.position + velocity, params.min_position, params.max_position)
        # hitting the left wall kills leftward velocity (gymnasium's inelastic stop)
        velocity = jnp.where(
            jnp.logical_and(position == params.min_position, velocity < 0), 0.0, velocity
        )
        new_state = MountainCarState(position, velocity, state.time + 1)
        terminated = jnp.logical_and(position >= params.goal_position, velocity >= params.goal_velocity)
        truncated, done = time_limit(params, new_state.time, terminated)
        reward = jnp.where(terminated, 100.0, 0.0) - 0.1 * force**2
        info = {"terminated": terminated, "truncated": truncated}
        return new_state, self._obs(new_state), reward.astype(jnp.float32), done, info

    def observation_space(self, params: MountainCarParams) -> gym.spaces.Box:
        low = np.array([params.min_position, -params.max_speed], dtype=np.float32)
        high = np.array([params.max_position, params.max_speed], dtype=np.float32)
        return gym.spaces.Box(low, high, dtype=np.float32)

    def action_space(self, params: MountainCarParams) -> gym.spaces.Box:
        return gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
