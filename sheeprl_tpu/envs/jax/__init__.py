"""Pure-functional JAX environments for the Anakin training mode
(``sheeprl_tpu/engine/anakin.py``; Podracer, arxiv 2104.06272).

``make_jax_env`` is the registry: in-tree classic-control ids (with or without
the config-facing ``jax_`` prefix) plus ``gymnax:<EnvName>`` for any env from
the optional gymnax package.
"""

from __future__ import annotations

from sheeprl_tpu.envs.jax.cartpole import CartPole
from sheeprl_tpu.envs.jax.core import JaxEnv
from sheeprl_tpu.envs.jax.mountain_car import MountainCarContinuous
from sheeprl_tpu.envs.jax.pendulum import Pendulum

_JAX_ENVS = {
    "cartpole": CartPole,
    "pendulum": Pendulum,
    "mountain_car_continuous": MountainCarContinuous,
    "mountain_car": MountainCarContinuous,  # alias: env/jax_mountain_car.yaml's id
}


def make_jax_env(env_id: str, **env_kwargs) -> JaxEnv:
    """Build a pure-functional env by id: ``cartpole`` / ``jax_cartpole`` /
    ``gymnax:CartPole-v1`` / ..."""
    name = str(env_id)
    if name.startswith("gymnax:"):
        from sheeprl_tpu.envs.jax.gymnax_adapter import GymnaxAdapter

        return GymnaxAdapter(name.split(":", 1)[1], **env_kwargs)
    short = name[len("jax_"):] if name.startswith("jax_") else name
    if short in _JAX_ENVS:
        return _JAX_ENVS[short](**env_kwargs)
    raise ValueError(
        f"Unknown jax env id {env_id!r}; in-tree: {sorted(_JAX_ENVS)} "
        "(optionally prefixed 'jax_'), external: 'gymnax:<EnvName>'."
    )


__all__ = [
    "CartPole",
    "JaxEnv",
    "MountainCarContinuous",
    "Pendulum",
    "make_jax_env",
]
