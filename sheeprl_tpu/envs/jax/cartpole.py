"""Pure-JAX CartPole, trajectory-parity-matched to gymnasium ``CartPole-v1``.

Physics constants, the euler integrator and the termination thresholds are
copied from ``gymnasium/envs/classic_control/cartpole.py`` verbatim; the parity
contract (``tests/test_envs/test_jax_envs.py``) steps both implementations from
an identical physics state and asserts matching observation/reward/termination
trajectories.  Reset distribution equivalence: gymnasium draws the 4-vector
uniformly from ``[-0.05, 0.05]`` — so does :meth:`CartPole.reset` (different
PRNG streams, identical distribution).  The ``TimeLimit(500)`` that
``gymnasium.make`` adds is folded into ``params.max_episode_steps``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv, time_limit


class CartPoleParams(NamedTuple):
    gravity: float = 9.8
    masscart: float = 1.0
    masspole: float = 0.1
    length: float = 0.5  # half the pole's length
    force_mag: float = 10.0
    tau: float = 0.02
    theta_threshold: float = 12 * 2 * np.pi / 360
    x_threshold: float = 2.4
    reset_bound: float = 0.05
    max_episode_steps: int = 500


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array
    time: jax.Array


class CartPole(JaxEnv):
    name = "cartpole"

    def default_params(self) -> CartPoleParams:
        return CartPoleParams()

    def reset(self, params: CartPoleParams, key: jax.Array) -> Tuple[CartPoleState, jax.Array]:
        vals = jax.random.uniform(key, (4,), jnp.float32, -params.reset_bound, params.reset_bound)
        state = CartPoleState(vals[0], vals[1], vals[2], vals[3], jnp.zeros((), jnp.int32))
        return state, self._obs(state)

    @staticmethod
    def _obs(state: CartPoleState) -> jax.Array:
        return jnp.stack([state.x, state.x_dot, state.theta, state.theta_dot]).astype(jnp.float32)

    def step(self, params: CartPoleParams, state: CartPoleState, action: jax.Array, key: jax.Array):
        total_mass = params.masspole + params.masscart
        polemass_length = params.masspole * params.length
        force = jnp.where(action == 1, params.force_mag, -params.force_mag)
        costheta = jnp.cos(state.theta)
        sintheta = jnp.sin(state.theta)
        temp = (force + polemass_length * jnp.square(state.theta_dot) * sintheta) / total_mass
        thetaacc = (params.gravity * sintheta - costheta * temp) / (
            params.length * (4.0 / 3.0 - params.masspole * jnp.square(costheta) / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        # euler integrator (gymnasium's default kinematics_integrator)
        x = state.x + params.tau * state.x_dot
        x_dot = state.x_dot + params.tau * xacc
        theta = state.theta + params.tau * state.theta_dot
        theta_dot = state.theta_dot + params.tau * thetaacc
        new_state = CartPoleState(x, x_dot, theta, theta_dot, state.time + 1)
        terminated = jnp.logical_or(
            jnp.abs(x) > params.x_threshold, jnp.abs(theta) > params.theta_threshold
        )
        truncated, done = time_limit(params, new_state.time, terminated)
        reward = jnp.ones((), jnp.float32)  # 1.0 every step, including the terminating one
        info = {"terminated": terminated, "truncated": truncated}
        return new_state, self._obs(new_state), reward, done, info

    def observation_space(self, params: CartPoleParams) -> gym.spaces.Box:
        high = np.array(
            [params.x_threshold * 2, np.finfo(np.float32).max, params.theta_threshold * 2, np.finfo(np.float32).max],
            dtype=np.float32,
        )
        return gym.spaces.Box(-high, high, dtype=np.float32)

    def action_space(self, params: CartPoleParams) -> gym.spaces.Discrete:
        return gym.spaces.Discrete(2)
