"""Pure-JAX Pendulum, parity-matched to gymnasium ``Pendulum-v1`` (the SAC-family
Anakin workhorse: continuous actions, dense reward, never terminates — episodes
end only on the in-graph ``TimeLimit(200)``).  Reset distribution equivalence:
gymnasium draws ``theta ~ U(-pi, pi)``, ``theta_dot ~ U(-1, 1)`` — so does
:meth:`Pendulum.reset`."""

from __future__ import annotations

from typing import NamedTuple, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.core import JaxEnv, time_limit


class PendulumParams(NamedTuple):
    max_speed: float = 8.0
    max_torque: float = 2.0
    dt: float = 0.05
    g: float = 10.0
    m: float = 1.0
    l: float = 1.0
    max_episode_steps: int = 200


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    time: jax.Array


def _angle_normalize(x: jax.Array) -> jax.Array:
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class Pendulum(JaxEnv):
    name = "pendulum"

    def default_params(self) -> PendulumParams:
        return PendulumParams()

    def reset(self, params: PendulumParams, key: jax.Array) -> Tuple[PendulumState, jax.Array]:
        high = jnp.asarray([jnp.pi, 1.0], jnp.float32)
        vals = jax.random.uniform(key, (2,), jnp.float32, -high, high)
        state = PendulumState(vals[0], vals[1], jnp.zeros((), jnp.int32))
        return state, self._obs(state)

    @staticmethod
    def _obs(state: PendulumState) -> jax.Array:
        return jnp.stack([jnp.cos(state.theta), jnp.sin(state.theta), state.theta_dot]).astype(jnp.float32)

    def step(self, params: PendulumParams, state: PendulumState, action: jax.Array, key: jax.Array):
        u = jnp.clip(jnp.asarray(action, jnp.float32).reshape(-1)[0], -params.max_torque, params.max_torque)
        costs = (
            _angle_normalize(state.theta) ** 2 + 0.1 * state.theta_dot**2 + 0.001 * (u**2)
        )
        newthdot = state.theta_dot + (
            3 * params.g / (2 * params.l) * jnp.sin(state.theta) + 3.0 / (params.m * params.l**2) * u
        ) * params.dt
        newthdot = jnp.clip(newthdot, -params.max_speed, params.max_speed)
        newth = state.theta + newthdot * params.dt
        new_state = PendulumState(newth, newthdot, state.time + 1)
        terminated = jnp.zeros((), bool)
        truncated, done = time_limit(params, new_state.time, terminated)
        info = {"terminated": terminated, "truncated": truncated}
        return new_state, self._obs(new_state), (-costs).astype(jnp.float32), done, info

    def observation_space(self, params: PendulumParams) -> gym.spaces.Box:
        high = np.array([1.0, 1.0, params.max_speed], dtype=np.float32)
        return gym.spaces.Box(-high, high, dtype=np.float32)

    def action_space(self, params: PendulumParams) -> gym.spaces.Box:
        return gym.spaces.Box(-params.max_torque, params.max_torque, (1,), np.float32)
