"""Host-side gymnasium view of a :class:`~sheeprl_tpu.envs.jax.core.JaxEnv`.

This is the COMPATIBILITY path, not the fast one: it lets ``env=jax_cartpole``
run through the unchanged host training loops (``SyncVectorEnv`` and friends,
one jitted step dispatch per env step) so host-vs-Anakin comparisons —
``benchmarks/anakin_bench.py``'s speedup row and the trajectory-parity tests —
exercise the SAME dynamics on both sides.  With ``algo.anakin=True`` the engine
bypasses this wrapper entirely and vmaps the pure env inside the fused scan."""

from __future__ import annotations

from typing import Optional

import gymnasium as gym
import jax
import numpy as np


class JaxToGymEnv(gym.Env):
    metadata = {"render_modes": []}

    def __init__(self, env_id: str, seed: Optional[int] = None, **env_kwargs):
        from sheeprl_tpu.envs.jax import make_jax_env

        self._env = make_jax_env(env_id, **env_kwargs)
        self._params = self._env.default_params()
        self.observation_space = self._env.observation_space(self._params)
        self.action_space = self._env.action_space(self._params)
        self._key = jax.random.PRNGKey(0 if seed is None else int(seed))
        self._state = None
        # Plain step (no autoreset): gymnasium's vector wrappers own the reset
        # protocol here, exactly like any other host env.
        self._step = jax.jit(self._env.step)
        self._reset = jax.jit(self._env.reset)

    def reset(self, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(int(seed))
        self._key, sub = jax.random.split(self._key)
        self._state, obs = self._reset(self._params, sub)
        return np.asarray(obs), {}

    def step(self, action):
        self._key, sub = jax.random.split(self._key)
        if isinstance(self.action_space, gym.spaces.Discrete):
            action = np.int32(action)
        else:
            action = np.asarray(action, np.float32)
        self._state, obs, reward, _done, info = self._step(self._params, self._state, action, sub)
        return (
            np.asarray(obs),
            float(reward),
            bool(info["terminated"]),
            bool(info["truncated"]),
            {},
        )

    def render(self):
        return None
