"""Generic gymnasium wrappers (reference: ``/root/reference/sheeprl/envs/wrappers.py``).

Fresh implementations against gymnasium 1.x (the reference targets 0.29-era APIs):

* ``ActionRepeat`` (reference ``:48``) — repeat actions, accumulate rewards.
* ``MaskVelocityWrapper`` (``:13``) — zero out velocity entries of classic-control obs.
* ``FrameStack`` (``:126``) — deque-based stacking with dilation, dict-obs aware, stacks
  along a new leading axis per key producing ``[stack, C, H, W]``.
* ``RestartOnException`` (``:74``) — rebuild a crashed env, bounded failures per window.
* ``RewardAsObservationWrapper`` (``:185``) — last reward appended to the obs dict.
* ``ActionsAsObservationWrapper`` (``:258``) — stack of past actions in the obs dict.
* ``GrayscaleRenderWrapper`` (``:244``) — render frames as 3-channel for video capture.
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Sequence, SupportsFloat, Tuple

import gymnasium as gym
import numpy as np


class ActionRepeat(gym.Wrapper):
    def __init__(self, env: gym.Env, amount: int):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` should be a positive integer")
        self._amount = amount
        # Adapter fast path: an env exposing ``step_repeat(action, amount)`` runs the
        # repeat loop itself and materialises only the LAST observation (the generic
        # loop discards the intermediates, but the adapter has already paid to render
        # them — for pixel envs that is half the env wall-clock).  Bound only when
        # ActionRepeat wraps the adapter DIRECTLY — reaching through intermediate
        # wrappers would silently skip their step() logic.
        self._native = getattr(env, "step_repeat", None) if env.unwrapped is env else None

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action: Any) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        if self._native is not None:
            return self._native(action, self._amount)
        done = truncated = False
        total_reward = 0.0
        obs, info = None, {}
        for _ in range(self._amount):
            obs, reward, done, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if done or truncated:
                break
        return obs, total_reward, done, truncated, info


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Mask the velocity components of classic-control observations."""

    velocity_indices: Dict[str, np.ndarray] = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        env_id = env.unwrapped.spec.id if env.unwrapped.spec is not None else ""
        if env_id not in self.velocity_indices:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}")
        self.mask = np.ones(env.observation_space.shape, dtype=np.float32)
        self.mask[self.velocity_indices[env_id]] = 0.0

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class FrameStack(gym.Wrapper):
    """Stack the last ``num_stack`` frames of the given dict keys, with dilation.

    Output per key: ``[num_stack, *frame_shape]`` (the encoder flattens stack × channel).
    """

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"Invalid value for num_stack, expected a positive integer, got: {num_stack}")
        if dilation <= 0:
            raise ValueError(f"Invalid value for dilation, expected a positive integer, got: {dilation}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(f"FrameStack requires a dict observation space, got: {type(env.observation_space)}")
        self._num_stack = num_stack
        self._dilation = dilation
        self._cnn_keys = [k for k in cnn_keys if k in env.observation_space.spaces]
        if not self._cnn_keys:
            raise RuntimeError(f"No valid cnn keys to stack: {cnn_keys}")
        self._frames: Dict[str, deque] = {k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys}
        obs_space = copy.deepcopy(dict(env.observation_space.spaces))
        for k in self._cnn_keys:
            space = env.observation_space[k]
            obs_space[k] = gym.spaces.Box(
                low=np.repeat(space.low[None], num_stack, axis=0),
                high=np.repeat(space.high[None], num_stack, axis=0),
                shape=(num_stack, *space.shape),
                dtype=space.dtype,
            )
        self.observation_space = gym.spaces.Dict(obs_space)

    def _stacked(self, key: str) -> np.ndarray:
        frames = list(self._frames[key])[:: -self._dilation][::-1]
        return np.stack(frames, axis=0)

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
            obs[k] = self._stacked(k)
        return obs, reward, done, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        for k in self._cnn_keys:
            self._frames[k].clear()
            for _ in range(self._num_stack * self._dilation):
                self._frames[k].append(obs[k])
            obs[k] = self._stacked(k)
        return obs, info


class RestartOnException(gym.Wrapper):
    """Rebuild the env when step/reset raises (reference ``:74-124``); used for flaky
    envs (MineRL-style). At most ``maxfails`` rebuilds per ``window`` seconds.

    Restart semantics differ deliberately from the reference: a restart is surfaced as
    ``truncated=True`` (+ ``info["restart_on_exception"]``), so every training loop's
    ordinary done path marks the replay-buffer episode boundary (truncated row +
    ``is_first`` on the next row).  The reference instead returns ``done=False`` and
    has DreamerV3 patch the buffer after the fact (``dreamer_v3.py:595-608``) — a
    repair step each consumer must remember; here consistency holds by construction."""

    def __init__(self, env_fn: Callable[[], gym.Env], maxfails: int = 5, window: float = 60.0):
        self._env_fn = env_fn
        env = env_fn()
        super().__init__(env)
        self._maxfails = maxfails
        self._window = window
        self._fails = 0
        self._last_fail_time = 0.0

    def _restart(self) -> None:
        now = time.time()
        if now - self._last_fail_time > self._window:
            self._fails = 0
        self._fails += 1
        self._last_fail_time = now
        if self._fails > self._maxfails:
            raise RuntimeError(f"Env failed {self._fails} times within {self._window}s; giving up.")
        try:
            self.env.close()
        except Exception:
            pass
        self.env = self._env_fn()

    def step(self, action):
        try:
            return self.env.step(action)
        except Exception:
            self._restart()
            obs, info = self.env.reset()
            info["restart_on_exception"] = True
            return obs, 0.0, False, True, info

    def reset(self, seed=None, options=None):
        try:
            return self.env.reset(seed=seed, options=options)
        except Exception:
            self._restart()
            return self.env.reset()


class RewardAsObservationWrapper(gym.Wrapper):
    def __init__(self, env: gym.Env):
        super().__init__(env)
        reward_space = gym.spaces.Box(-np.inf, np.inf, shape=(1,), dtype=np.float32)
        if isinstance(env.observation_space, gym.spaces.Dict):
            spaces = dict(env.observation_space.spaces)
            spaces["reward"] = reward_space
            self.observation_space = gym.spaces.Dict(spaces)
        else:
            self.observation_space = gym.spaces.Dict({"obs": env.observation_space, "reward": reward_space})

    def _wrap(self, obs: Any, reward: float) -> Dict[str, Any]:
        r = np.array([reward], dtype=np.float32)
        if isinstance(obs, dict):
            obs = dict(obs)
            obs["reward"] = r
        else:
            obs = {"obs": obs, "reward": r}
        return obs

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        return self._wrap(obs, float(reward)), reward, done, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        return self._wrap(obs, 0.0), info


class ActionsAsObservationWrapper(gym.Wrapper):
    """Expose the last ``num_stack`` executed actions in the obs dict under key
    ``action_stack`` (reference ``:258-342``); actions are noop-initialised on reset."""

    def __init__(self, env: gym.Env, num_stack: int, noop: Any, dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"The number of actions to the stack must be greater than zero, got: {num_stack}")
        if dilation <= 0:
            raise ValueError(f"The dilation must be greater than zero, got: {dilation}")
        self._num_stack = num_stack
        self._dilation = dilation
        act_space = env.action_space
        if isinstance(act_space, gym.spaces.Discrete):
            self._per_action = int(act_space.n)
            if not isinstance(noop, int):
                raise ValueError(f"The noop action must be an integer for discrete action spaces, got: {noop}")
            self._noop = np.zeros(self._per_action, dtype=np.float32)
            self._noop[noop] = 1.0
        elif isinstance(act_space, gym.spaces.MultiDiscrete):
            if not isinstance(noop, (list, tuple)):
                raise ValueError(f"The noop actions must be a list for multi-discrete action spaces, got: {noop}")
            nvec = act_space.nvec
            if len(noop) != len(nvec):
                raise ValueError(f"The noop action must be a list of length {len(nvec)}, got: {len(noop)}")
            self._per_action = int(sum(nvec))
            self._noop = np.zeros(self._per_action, dtype=np.float32)
            offset = 0
            for n, a in zip(nvec, noop):
                self._noop[offset + int(a)] = 1.0
                offset += int(n)
        elif isinstance(act_space, gym.spaces.Box):
            self._per_action = int(np.prod(act_space.shape))
            if isinstance(noop, (int, float)):
                # scalar noop broadcasts over the action vector (reference accepts a float)
                noop = [float(noop)] * self._per_action
            if not isinstance(noop, (list, tuple)):
                raise ValueError(f"The noop action must be a float or list for continuous action spaces, got: {noop}")
            if len(noop) != self._per_action:
                raise ValueError(f"The noop action must be a list of length {self._per_action}, got: {len(noop)}")
            self._noop = np.asarray(noop, dtype=np.float32)
        else:
            raise ValueError(f"Unsupported action space: {type(act_space)}")
        self._actions: deque = deque(maxlen=num_stack * dilation)
        shape = (num_stack * self._per_action,)
        if isinstance(env.observation_space, gym.spaces.Dict):
            spaces = dict(env.observation_space.spaces)
        else:
            spaces = {"obs": env.observation_space}
        spaces["action_stack"] = gym.spaces.Box(-np.inf, np.inf, shape=shape, dtype=np.float32)
        self.observation_space = gym.spaces.Dict(spaces)

    def _encode(self, action: Any) -> np.ndarray:
        act_space = self.env.action_space
        if isinstance(act_space, gym.spaces.Discrete):
            out = np.zeros(self._per_action, dtype=np.float32)
            out[int(np.asarray(action).item())] = 1.0
            return out
        if isinstance(act_space, gym.spaces.MultiDiscrete):
            out = np.zeros(self._per_action, dtype=np.float32)
            offset = 0
            for n, a in zip(act_space.nvec, np.asarray(action).reshape(-1)):
                out[offset + int(a)] = 1.0
                offset += int(n)
            return out
        return np.asarray(action, dtype=np.float32).reshape(-1)

    def _obs(self, obs: Any) -> Dict[str, Any]:
        stacked = list(self._actions)[:: -self._dilation][::-1]
        action_stack = np.concatenate(stacked, axis=0).astype(np.float32)
        if isinstance(obs, dict):
            obs = dict(obs)
        else:
            obs = {"obs": obs}
        obs["action_stack"] = action_stack
        return obs

    def step(self, action):
        obs, reward, done, truncated, info = self.env.step(action)
        self._actions.append(self._encode(action))
        return self._obs(obs), reward, done, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        self._actions.clear()
        for _ in range(self._num_stack * self._dilation):
            self._actions.append(self._noop.copy())
        return self._obs(obs), info


class GrayscaleRenderWrapper(gym.Wrapper):
    def render(self):
        frame = self.env.render()
        if isinstance(frame, np.ndarray) and frame.ndim == 2:
            frame = np.stack([frame] * 3, axis=-1)
        if isinstance(frame, np.ndarray) and frame.ndim == 3 and frame.shape[-1] == 1:
            frame = np.repeat(frame, 3, axis=-1)
        return frame
