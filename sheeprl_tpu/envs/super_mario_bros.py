"""Super Mario Bros adapter (reference: ``/root/reference/sheeprl/envs/super_mario_bros.py``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_SMB_AVAILABLE

if not _IS_SMB_AVAILABLE:
    raise ModuleNotFoundError("gym_super_mario_bros is not installed")

import gym_super_mario_bros  # noqa: E402
from gym_super_mario_bros.actions import COMPLEX_MOVEMENT, RIGHT_ONLY, SIMPLE_MOVEMENT  # noqa: E402
from nes_py.wrappers import JoypadSpace  # noqa: E402

ACTION_SPACES = {"right_only": RIGHT_ONLY, "simple": SIMPLE_MOVEMENT, "complex": COMPLEX_MOVEMENT}


class SuperMarioBrosWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(self, id: str = "SuperMarioBros-v0", action_space: str = "simple", render_mode: str = "rgb_array"):
        env = gym_super_mario_bros.make(id, render_mode=render_mode, apply_api_compatibility=True)
        self._env = JoypadSpace(env, ACTION_SPACES[action_space])
        obs_shape = self._env.observation_space.shape  # [H, W, C]
        self.observation_space = gym.spaces.Dict(
            {"rgb": gym.spaces.Box(0, 255, (obs_shape[2], obs_shape[0], obs_shape[1]), np.uint8)}
        )
        self.action_space = gym.spaces.Discrete(self._env.action_space.n)

    def _obs(self, obs) -> Dict[str, np.ndarray]:
        return {"rgb": np.transpose(np.asarray(obs), (2, 0, 1))}

    def step(self, action):
        obs, reward, done, truncated, info = self._env.step(int(action))
        return self._obs(obs), reward, done, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self._env.reset()
        return self._obs(obs), info

    def render(self):
        return self._env.render()

    def close(self):
        self._env.close()
