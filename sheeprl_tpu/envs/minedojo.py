"""MineDojo adapter (reference: ``/root/reference/sheeprl/envs/minedojo.py``).

MultiDiscrete(3) functional action space {movement/camera, use/attack, craft-arg} with
per-component **action masks** exposed in the observation (reference ``:168-183``),
pitch/yaw limits and sticky attack/jump."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

if not _IS_MINEDOJO_AVAILABLE:
    raise ModuleNotFoundError("minedojo is not installed")

import minedojo  # noqa: E402


class MineDojoWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: int = 30,
        sticky_jump: int = 10,
        **kwargs: Any,
    ):
        self._env = minedojo.make(
            task_id=id, image_size=(height, width), world_seed=seed, fast_reset=True, **kwargs
        )
        self._pitch_limits = pitch_limits
        self._sticky_attack = sticky_attack
        self._sticky_jump = sticky_jump
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        # Functional action space: 12 movement/camera combos x 3 fn x 8 craft args
        self.action_space = gym.spaces.MultiDiscrete([12, 3, 8])
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, (3, height, width), np.uint8),
                "inventory": gym.spaces.Box(-np.inf, np.inf, (36,), np.float32),
                "equipment": gym.spaces.Box(-np.inf, np.inf, (1,), np.float32),
                "life_stats": gym.spaces.Box(-np.inf, np.inf, (3,), np.float32),
                "mask_action_type": gym.spaces.Box(0, 1, (12,), bool),
                "mask_craft_smelt": gym.spaces.Box(0, 1, (8,), bool),
            }
        )

    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        """Map the functional MultiDiscrete(3) to MineDojo's native 8-dim action.

        Native layout: [fwd/back(3), left/right(3), jump/sneak/sprint(4),
        camera-pitch(25, 12=no-op), camera-yaw(25, 12=no-op), fn(8), craft(244→8), ...]."""
        native = np.zeros(8, dtype=np.int64)
        native[3] = native[4] = 12  # camera no-op is the centre index
        a0 = int(action[0])
        if a0 == 1:
            native[0] = 1  # forward
        elif a0 == 2:
            native[0] = 2  # back
        elif a0 == 3:
            native[1] = 1  # left
        elif a0 == 4:
            native[1] = 2  # right
        elif a0 == 5:
            native[2] = 1  # jump
        elif a0 == 6:
            native[3] = 11  # pitch down 15°
        elif a0 == 7:
            native[3] = 13  # pitch up 15°
        elif a0 == 8:
            native[4] = 11  # yaw left 15°
        elif a0 == 9:
            native[4] = 13  # yaw right 15°
        elif a0 == 10:
            native[2] = 2  # sneak
        elif a0 == 11:
            native[2] = 3  # sprint
        fn = int(action[1])
        if fn == 1:
            native[5] = 1  # use
        elif fn == 2:
            native[5] = 3  # attack
        native[6] = int(action[2])  # craft argument
        return native

    def _obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        masks = obs.get("masks", {})
        return {
            "rgb": np.asarray(obs["rgb"], dtype=np.uint8),
            "inventory": np.asarray(obs.get("inventory", {}).get("quantity", np.zeros(36)), dtype=np.float32),
            "equipment": np.zeros(1, dtype=np.float32),
            "life_stats": np.asarray(
                [
                    float(obs.get("life_stats", {}).get("life", 20)),
                    float(obs.get("life_stats", {}).get("food", 20)),
                    float(obs.get("life_stats", {}).get("oxygen", 300)),
                ],
                dtype=np.float32,
            ),
            "mask_action_type": np.asarray(masks.get("action_type", np.ones(12)), dtype=bool)[:12],
            "mask_craft_smelt": np.asarray(masks.get("craft_smelt", np.ones(8)), dtype=bool)[:8],
        }

    def step(self, action):
        obs, reward, done, info = self._env.step(self._convert_action(np.asarray(action)))
        return self._obs(obs), reward, done, False, info

    def reset(self, seed=None, options=None):
        return self._obs(self._env.reset()), {}

    def close(self):
        self._env.close()
