"""MineDojo adapter (reference: ``/root/reference/sheeprl/envs/minedojo.py``).

Functional ``MultiDiscrete(3)`` action space — (action-type, craft-arg, item-arg) —
mapped onto MineDojo's native 8-dim action, with:

* **action masks** in the observation (``mask_action_type`` / ``mask_equip_place`` /
  ``mask_destroy`` / ``mask_craft_smelt``, reference ``:168-182``) consumed by the
  hierarchical ``MinedojoActor``;
* **sticky attack/jump**: a selected attack (or jump) is repeated for the next
  ``sticky_attack`` (``sticky_jump``) steps unless a conflicting action is chosen
  (reference ``:184-214``);
* **pitch limits**: camera pitch commands that would leave ``pitch_limits`` are
  replaced with the no-op camera index (reference ``:243-248``);
* item-indexed inventory/equipment vectors over the full MineDojo item table.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, SupportsFloat, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

if not _IS_MINEDOJO_AVAILABLE:
    raise ModuleNotFoundError("minedojo is not installed")

import minedojo  # noqa: E402
import minedojo.tasks  # noqa: E402
from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS  # noqa: E402

N_ALL_ITEMS = len(ALL_ITEMS)
ITEM_ID_TO_NAME = dict(enumerate(ALL_ITEMS))
ITEM_NAME_TO_ID = dict(zip(ALL_ITEMS, range(N_ALL_ITEMS)))
_ALL_TASKS_SPECS = copy.deepcopy(minedojo.tasks.ALL_TASKS_SPECS)

# Functional action-type table (reference ``:20-40``): index → native 8-dim action.
# Native layout: [fwd/back, left/right, jump/sneak/sprint, pitch(25; 12=no-op),
# yaw(25; 12=no-op), fn(8), craft-arg, item-arg].
ACTION_MAP = {
    0: np.array([0, 0, 0, 12, 12, 0, 0, 0]),  # no-op
    1: np.array([1, 0, 0, 12, 12, 0, 0, 0]),  # forward
    2: np.array([2, 0, 0, 12, 12, 0, 0, 0]),  # back
    3: np.array([0, 1, 0, 12, 12, 0, 0, 0]),  # left
    4: np.array([0, 2, 0, 12, 12, 0, 0, 0]),  # right
    5: np.array([1, 0, 1, 12, 12, 0, 0, 0]),  # jump + forward
    6: np.array([1, 0, 2, 12, 12, 0, 0, 0]),  # sneak + forward
    7: np.array([1, 0, 3, 12, 12, 0, 0, 0]),  # sprint + forward
    8: np.array([0, 0, 0, 11, 12, 0, 0, 0]),  # pitch down (-15°)
    9: np.array([0, 0, 0, 13, 12, 0, 0, 0]),  # pitch up (+15°)
    10: np.array([0, 0, 0, 12, 11, 0, 0, 0]),  # yaw left (-15°)
    11: np.array([0, 0, 0, 12, 13, 0, 0, 0]),  # yaw right (+15°)
    12: np.array([0, 0, 0, 12, 12, 1, 0, 0]),  # use
    13: np.array([0, 0, 0, 12, 12, 2, 0, 0]),  # drop
    14: np.array([0, 0, 0, 12, 12, 3, 0, 0]),  # attack
    15: np.array([0, 0, 0, 12, 12, 4, 0, 0]),  # craft
    16: np.array([0, 0, 0, 12, 12, 5, 0, 0]),  # equip
    17: np.array([0, 0, 0, 12, 12, 6, 0, 0]),  # place
    18: np.array([0, 0, 0, 12, 12, 7, 0, 0]),  # destroy
}


class MineDojoWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        **kwargs: Any,
    ):
        self._break_speed_multiplier = kwargs.pop("break_speed_multiplier", 100)
        self._start_pos = copy.deepcopy(kwargs.get("start_position", None))
        self._pos = copy.deepcopy(self._start_pos)
        if self._pos is not None and not (pitch_limits[0] <= self._pos["pitch"] <= pitch_limits[1]):
            raise ValueError(
                f"The initial position must respect the pitch limits {pitch_limits}, given {self._pos['pitch']}"
            )
        self._env = minedojo.make(
            task_id=id,
            image_size=(height, width),
            world_seed=seed,
            fast_reset=True,
            break_speed_multiplier=self._break_speed_multiplier,
            **kwargs,
        )
        self._pitch_limits = pitch_limits
        # High break-speed already one-shots blocks; sticky attack would waste steps
        # (reference ``:74``).
        self._sticky_attack = 0 if self._break_speed_multiplier > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._inventory: Dict[str, list] = {}
        self._inventory_names = np.array([])
        self._inventory_max = np.zeros(N_ALL_ITEMS)

        self.action_space = gym.spaces.MultiDiscrete(
            np.array([len(ACTION_MAP), len(ALL_CRAFT_SMELT_ITEMS), N_ALL_ITEMS])
        )
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, (3, height, width), np.uint8),
                "inventory": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_max": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_delta": gym.spaces.Box(-np.inf, np.inf, (N_ALL_ITEMS,), np.float32),
                "equipment": gym.spaces.Box(0.0, 1.0, (N_ALL_ITEMS,), np.int32),
                "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
                "mask_action_type": gym.spaces.Box(0, 1, (len(ACTION_MAP),), bool),
                "mask_equip_place": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_destroy": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_craft_smelt": gym.spaces.Box(0, 1, (len(ALL_CRAFT_SMELT_ITEMS),), bool),
            }
        )
        self.render_mode = "rgb_array"
        minedojo.tasks.ALL_TASKS_SPECS = copy.deepcopy(_ALL_TASKS_SPECS)

    # -- conversions --------------------------------------------------------
    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        """Functional triple → native action, with sticky attack/jump
        (reference ``:184-224``)."""
        converted = ACTION_MAP[int(action[0])].copy()
        if self._sticky_attack:
            if converted[5] == 3:  # attack selected: arm the counter
                self._sticky_attack_counter = self._sticky_attack - 1
            if self._sticky_attack_counter > 0 and converted[5] == 0:
                converted[5] = 3  # repeat the attack while no other fn action chosen
                self._sticky_attack_counter -= 1
            elif converted[5] != 3:
                self._sticky_attack_counter = 0
        if self._sticky_jump:
            if converted[2] == 1:  # jump selected: arm the counter
                self._sticky_jump_counter = self._sticky_jump - 1
            if self._sticky_jump_counter > 0 and converted[0] == 0:
                converted[2] = 1
                # the sticky jump also moves forward unless another movement is chosen
                if converted[0] == converted[1] == 0:
                    converted[0] = 1
                self._sticky_jump_counter -= 1
            elif converted[2] != 1:
                self._sticky_jump_counter = 0
        # craft (fn=4) consumes the craft argument; equip/place/destroy (5/6/7) consume
        # the inventory slot of the selected item.
        converted[6] = int(action[1]) if converted[5] == 4 else 0
        if converted[5] in {5, 6, 7}:
            slots = self._inventory.get(ITEM_ID_TO_NAME[int(action[2])])
            if slots is None:
                # item not in inventory (e.g. unmasked random prefill): no-op instead
                # of crashing — the masked actor never requests these
                converted[5] = 0
                converted[7] = 0
            else:
                converted[7] = slots[0]
        else:
            converted[7] = 0
        return converted

    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        counts = np.zeros(N_ALL_ITEMS)
        self._inventory = {}
        self._inventory_names = np.array(["_".join(i.split(" ")) for i in inventory["name"].copy().tolist()])
        for i, (item, quantity) in enumerate(zip(inventory["name"], inventory["quantity"])):
            item = "_".join(item.split(" "))
            self._inventory.setdefault(item, []).append(i)
            counts[ITEM_NAME_TO_ID[item]] += 1 if item == "air" else quantity
        self._inventory_max = np.maximum(counts, self._inventory_max)
        return counts

    def _convert_inventory_delta(self, delta: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(N_ALL_ITEMS)
        for names_key, qty_key, sign in (
            ("inc_name_by_craft", "inc_quantity_by_craft", 1),
            ("dec_name_by_craft", "dec_quantity_by_craft", -1),
            ("inc_name_by_other", "inc_quantity_by_other", 1),
            ("dec_name_by_other", "dec_quantity_by_other", -1),
        ):
            for item, quantity in zip(delta[names_key], delta[qty_key]):
                out[ITEM_NAME_TO_ID["_".join(item.split(" "))]] += sign * quantity
        return out

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        equip = np.zeros(N_ALL_ITEMS, dtype=np.int32)
        equip[ITEM_NAME_TO_ID["_".join(equipment["name"][0].split(" "))]] = 1
        return equip

    def _convert_masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        equip_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        destroy_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        for item, eqp, dst in zip(self._inventory_names, masks["equip"], masks["destroy"]):
            idx = ITEM_NAME_TO_ID[item]
            equip_mask[idx] = eqp
            destroy_mask[idx] = dst
        masks["action_type"][5:7] *= np.any(equip_mask).item()
        masks["action_type"][7] *= np.any(destroy_mask).item()
        return {
            # movement/camera (first 12) are always allowed; fn actions follow the env mask
            "mask_action_type": np.concatenate((np.array([True] * 12), masks["action_type"][1:])),
            "mask_equip_place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": masks["craft_smelt"],
        }

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": np.asarray(obs["rgb"], dtype=np.uint8).copy(),
            "inventory": self._convert_inventory(obs["inventory"]).astype(np.float32),
            "inventory_max": self._inventory_max.astype(np.float32),
            "inventory_delta": self._convert_inventory_delta(obs["delta_inv"]).astype(np.float32),
            "equipment": self._convert_equipment(obs["equipment"]),
            "life_stats": np.concatenate(
                (obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["oxygen"])
            ).astype(np.float32),
            **self._convert_masks(obs["masks"]),
        }

    # -- gym API -------------------------------------------------------------
    def step(self, action: np.ndarray) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        action = self._convert_action(np.asarray(action))
        # Clamp the camera pitch to the limits (reference ``:246-248``).
        next_pitch = self._pos["pitch"] + (action[3] - 12) * 15 if self._pos else 0.0
        if self._pos and not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            action[3] = 12

        obs, reward, done, info = self._env.step(action)
        is_timelimit = info.get("TimeLimit.truncated", False)
        terminated = done and not is_timelimit
        truncated = done and is_timelimit
        self._pos = {
            "x": float(obs["location_stats"]["pos"][0]),
            "y": float(obs["location_stats"]["pos"][1]),
            "z": float(obs["location_stats"]["pos"][2]),
            "pitch": float(obs["location_stats"]["pitch"].item()),
            "yaw": float(obs["location_stats"]["yaw"].item()),
        }
        info = {**info, "location_stats": copy.deepcopy(self._pos)}
        return self._convert_obs(obs), reward, terminated, truncated, info

    def reset(self, seed=None, options=None):
        obs = self._env.reset()
        self._pos = {
            "x": float(obs["location_stats"]["pos"][0]),
            "y": float(obs["location_stats"]["pos"][1]),
            "z": float(obs["location_stats"]["pos"][2]),
            "pitch": float(obs["location_stats"]["pitch"].item()),
            "yaw": float(obs["location_stats"]["yaw"].item()),
        }
        self._sticky_jump_counter = 0
        self._sticky_attack_counter = 0
        self._inventory_max = np.zeros(N_ALL_ITEMS)
        return self._convert_obs(obs), {"location_stats": copy.deepcopy(self._pos)}

    def render(self):
        prev = getattr(self._env.unwrapped, "_prev_obs", None)
        if prev is not None and "rgb" in prev:
            return np.moveaxis(np.asarray(prev["rgb"]), 0, -1)
        return None

    def close(self):
        self._env.close()
