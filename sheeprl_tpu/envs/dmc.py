"""DeepMind Control adapter (reference: ``/root/reference/sheeprl/envs/dmc.py``).

dm_control physics tasks as gymnasium envs: spec→Box conversion, optional pixels
(``from_pixels``), dict {rgb, state} observations.  Import-gated — dm_control is an
optional dependency."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    raise ModuleNotFoundError("dm_control is not installed: `pip install dm_control`")

from dm_control import suite  # noqa: E402
from dm_env import specs  # noqa: E402


def _spec_to_box(spec, dtype=np.float32) -> gym.spaces.Box:
    def extract(s):
        dim = int(np.prod(s.shape))
        if type(s) is specs.Array:
            return np.full(dim, -np.inf, dtype), np.full(dim, np.inf, dtype)
        if type(s) is specs.BoundedArray:
            low = np.broadcast_to(s.minimum, s.shape).ravel().astype(dtype)
            high = np.broadcast_to(s.maximum, s.shape).ravel().astype(dtype)
            return low, high
        raise ValueError(f"Unsupported spec: {type(s)}")

    if isinstance(spec, (list, tuple)):
        mins, maxs = zip(*[extract(s) for s in spec])
        low, high = np.concatenate(mins), np.concatenate(maxs)
    else:
        low, high = extract(spec)
    return gym.spaces.Box(low, high, dtype=dtype)


def _flatten_obs(obs: Dict[str, Any]) -> np.ndarray:
    return np.concatenate([np.asarray([v]) if np.isscalar(v) else np.asarray(v).ravel() for v in obs.values()]).astype(
        np.float32
    )


class DMCWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(
        self,
        id: str,
        width: int = 64,
        height: int = 64,
        camera_id: int = 0,
        from_pixels: bool = True,
        from_vectors: bool = False,
        seed: Optional[int] = None,
        task_kwargs: Optional[Dict[str, Any]] = None,
    ):
        domain, task = id.split("_", 1)
        self._env = suite.load(domain, task, task_kwargs={"random": seed, **(task_kwargs or {})})
        self._width, self._height, self._camera_id = width, height, camera_id
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        if not (from_pixels or from_vectors):
            raise ValueError("At least one of from_pixels/from_vectors must be set")
        self.action_space = _spec_to_box(self._env.action_spec())
        spaces: Dict[str, gym.spaces.Space] = {}
        if from_pixels:
            spaces["rgb"] = gym.spaces.Box(0, 255, (3, height, width), np.uint8)
        if from_vectors:
            spaces["state"] = _spec_to_box(list(self._env.observation_spec().values()))
        self.observation_space = gym.spaces.Dict(spaces)

    def _obs(self, timestep) -> Dict[str, np.ndarray]:
        out = {}
        if self._from_pixels:
            frame = self.render()
            out["rgb"] = np.transpose(frame, (2, 0, 1))
        if self._from_vectors:
            out["state"] = _flatten_obs(timestep.observation)
        return out

    def step(self, action):
        timestep = self._env.step(np.asarray(action, dtype=self.action_space.dtype))
        reward = timestep.reward or 0.0
        terminated = timestep.last() and timestep.discount == 0.0
        truncated = timestep.last() and not terminated
        return self._obs(timestep), reward, terminated, truncated, {}

    def step_repeat(self, action, amount: int):
        """``amount`` physics steps, ONE observation: the :class:`~sheeprl_tpu.envs.
        wrappers.ActionRepeat` fast path.  Intermediate observations are discarded by
        the repeat loop anyway, and with software GL the 64×64 render dominates the
        step cost — rendering only the surviving frame halves the env wall-clock."""
        action = np.asarray(action, dtype=self.action_space.dtype)
        total = 0.0
        timestep = None
        for _ in range(max(int(amount), 1)):
            timestep = self._env.step(action)
            total += timestep.reward or 0.0
            if timestep.last():
                break
        terminated = timestep.last() and timestep.discount == 0.0
        truncated = timestep.last() and not terminated
        return self._obs(timestep), total, terminated, truncated, {}

    def reset(self, seed=None, options=None):
        timestep = self._env.reset()
        return self._obs(timestep), {}

    def render(self):
        return self._env.physics.render(height=self._height, width=self._width, camera_id=self._camera_id)

    def close(self):
        pass
