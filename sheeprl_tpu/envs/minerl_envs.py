"""Custom MineRL task specs (reference: ``/root/reference/sheeprl/envs/minerl_envs/``
— Navigate ``navigate.py``, Obtain ``obtain.py``, base spec ``backend.py``; themselves
adapted from the public minerllabs/minerl env definitions).

Table-driven re-derivation of the three custom tasks the reference ships for the
Minecraft results in BASELINE.md:

* ``CustomNavigate``: reach a diamond block ~64 m away using a compass; +100 sparse
  reward (plus per-block shaping in the dense variant);
* ``CustomObtainDiamond`` / ``CustomObtainIronPickaxe``: item-hierarchy tasks with the
  standard exponential reward schedule.

All specs share the DreamerV3-Minecraft conventions: 64×64 POV, a break-speed
multiplier (danijar/diamond_env's trick), no in-env time limit (the gymnasium
``TimeLimit`` wrapper distinguishes terminated/truncated instead).
"""

from __future__ import annotations

from abc import ABC
from typing import Any, Dict, List

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed")

import minerl.herobraine.hero.handlers as handlers  # noqa: E402
from minerl.herobraine.env_spec import EnvSpec  # noqa: E402
from minerl.herobraine.hero import handler  # noqa: E402
from minerl.herobraine.hero.mc import INVERSE_KEYMAP  # noqa: E402

MOVEMENT_KEYS = ("forward", "back", "left", "right", "jump", "sneak", "sprint", "attack")
NAVIGATE_STEPS = 6000

# The item hierarchy up to a diamond, with the standard exponential rewards.
DIAMOND_REWARD_SCHEDULE = [
    {"type": "log", "amount": 1, "reward": 1},
    {"type": "planks", "amount": 1, "reward": 2},
    {"type": "stick", "amount": 1, "reward": 4},
    {"type": "crafting_table", "amount": 1, "reward": 4},
    {"type": "wooden_pickaxe", "amount": 1, "reward": 8},
    {"type": "cobblestone", "amount": 1, "reward": 16},
    {"type": "furnace", "amount": 1, "reward": 32},
    {"type": "stone_pickaxe", "amount": 1, "reward": 32},
    {"type": "iron_ore", "amount": 1, "reward": 64},
    {"type": "iron_ingot", "amount": 1, "reward": 128},
    {"type": "iron_pickaxe", "amount": 1, "reward": 256},
    {"type": "diamond", "amount": 1, "reward": 1024},
]

OBTAIN_INVENTORY_ITEMS = [
    "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace",
    "stone_axe", "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe", "iron_pickaxe",
]
TOOL_ITEMS = ["wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe", "iron_axe", "iron_pickaxe"]


class BreakSpeedMultiplier(handler.Handler):
    """Malmo mission flag that scales block-breaking speed
    (danijar/diamond_env; reference ``backend.py:53-61``)."""

    def __init__(self, multiplier: float = 1.0):
        self.multiplier = multiplier

    def to_string(self) -> str:
        return f"break_speed({self.multiplier})"

    def xml_template(self) -> str:
        return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"


class _TpuEmbodimentSpec(EnvSpec, ABC):
    """Shared base: POV + location + life-stats observations, keyboard movement +
    camera actions, break-speed start handler (reference ``backend.py:19-50``)."""

    def __init__(self, name: str, *args: Any, resolution=(64, 64), break_speed: int = 100, **kwargs: Any):
        self.resolution = resolution
        self.break_speed = break_speed
        super().__init__(name, *args, **kwargs)

    def create_agent_start(self) -> List[handler.Handler]:
        return [BreakSpeedMultiplier(self.break_speed)]

    def create_observables(self) -> List[handler.Handler]:
        return [
            handlers.POVObservation(self.resolution),
            handlers.ObservationFromCurrentLocation(),
            handlers.ObservationFromLifeStats(),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        keyboard = [
            handlers.KeybasedCommandAction(key, binding)
            for key, binding in INVERSE_KEYMAP.items()
            if key in MOVEMENT_KEYS
        ]
        return keyboard + [handlers.CameraAction()]

    def create_monitors(self) -> List[handler.Handler]:
        return []


class CustomNavigate(_TpuEmbodimentSpec):
    """Compass navigation to a diamond block (reference ``navigate.py:18-97``)."""

    def __init__(self, dense: bool, extreme: bool, *args: Any, **kwargs: Any):
        self.dense, self.extreme = dense, extreme
        name = "CustomMineRLNavigate{}{}-v0".format("Extreme" if extreme else "", "Dense" if dense else "")
        # terminated/truncated are disambiguated by the outer TimeLimit wrapper.
        kwargs.pop("max_episode_steps", None)
        super().__init__(name, *args, max_episode_steps=None, **kwargs)

    def is_from_folder(self, folder: str) -> bool:
        return folder == ("navigateextreme" if self.extreme else "navigate")

    def create_observables(self) -> List[handler.Handler]:
        return super().create_observables() + [
            handlers.CompassObservation(angle=True, distance=False),
            handlers.FlatInventoryObservation(["dirt"]),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(["none", "dirt"], _other="none", _default="none")
        ]

    def create_rewardables(self) -> List[handler.Handler]:
        rewards: List[handler.Handler] = [
            handlers.RewardForTouchingBlockType(
                [{"type": "diamond_block", "behaviour": "onceOnly", "reward": 100.0}]
            )
        ]
        if self.dense:
            rewards.append(handlers.RewardForDistanceTraveledToCompassTarget(reward_per_block=1.0))
        return rewards

    def create_agent_start(self) -> List[handler.Handler]:
        return super().create_agent_start() + [
            handlers.SimpleInventoryAgentStart([{"type": "compass", "quantity": "1"}])
        ]

    def create_agent_handlers(self) -> List[handler.Handler]:
        return [handlers.AgentQuitFromTouchingBlockType(["diamond_block"])]

    def create_server_world_generators(self) -> List[handler.Handler]:
        if self.extreme:
            return [handlers.BiomeGenerator(biome=3, force_reset=True)]
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[handler.Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[handler.Handler]:
        return [
            handlers.NavigationDecorator(
                max_randomized_radius=64,
                min_randomized_radius=64,
                block="diamond_block",
                placement="surface",
                max_radius=8,
                min_radius=0,
                max_randomized_distance=8,
                min_randomized_distance=0,
                randomize_compass_location=True,
            )
        ]

    def create_server_initial_conditions(self) -> List[handler.Handler]:
        return [
            handlers.TimeInitialCondition(allow_passage_of_time=False, start_time=6000),
            handlers.WeatherInitialCondition("clear"),
            handlers.SpawningInitialCondition("false"),
        ]

    def get_docstring(self) -> str:
        flavour = "dense (per-block shaping)" if self.dense else "sparse (+100 at the goal)"
        biome = "an extreme-hills biome" if self.extreme else "a random survival map"
        return f"Navigate to a diamond block ~64m away using the compass; {flavour} reward; spawns on {biome}."

    def determine_success_from_rewards(self, rewards: list) -> bool:
        threshold = 100.0 + (60.0 if self.dense else 0.0)
        return sum(rewards) >= threshold


class CustomObtain(_TpuEmbodimentSpec):
    """Item-hierarchy task with GUI-free craft/smelt/equip actions
    (reference ``obtain.py:23-169``)."""

    def __init__(
        self,
        target_item: str,
        dense: bool,
        reward_schedule: List[Dict[str, Any]],
        *args: Any,
        max_episode_steps=None,
        **kwargs: Any,
    ):
        self.target_item = target_item
        self.dense = dense
        self.reward_schedule = reward_schedule
        camel = "".join(part.capitalize() for part in target_item.split("_"))
        name = "CustomMineRLObtain{}{}-v0".format(camel, "Dense" if dense else "")
        super().__init__(name, *args, max_episode_steps=max_episode_steps, **kwargs)

    def create_observables(self) -> List[handler.Handler]:
        return super().create_observables() + [
            handlers.FlatInventoryObservation(OBTAIN_INVENTORY_ITEMS),
            handlers.EquippedItemObservation(
                items=["air", *TOOL_ITEMS, "other"], _default="air", _other="other"
            ),
        ]

    def create_actionables(self) -> List[handler.Handler]:
        none = "none"
        return super().create_actionables() + [
            handlers.PlaceBlock(
                [none, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"],
                _other=none,
                _default=none,
            ),
            handlers.EquipAction([none, "air", *TOOL_ITEMS], _other=none, _default=none),
            handlers.CraftAction([none, "torch", "stick", "planks", "crafting_table"], _other=none, _default=none),
            handlers.CraftNearbyAction([none, *TOOL_ITEMS, "furnace"], _other=none, _default=none),
            handlers.SmeltItemNearby([none, "iron_ingot", "coal"], _other=none, _default=none),
        ]

    def create_rewardables(self) -> List[handler.Handler]:
        reward_cls = handlers.RewardForCollectingItems if self.dense else handlers.RewardForCollectingItemsOnce
        return [reward_cls(self.reward_schedule or {self.target_item: 1})]

    def create_agent_handlers(self) -> List[handler.Handler]:
        return [handlers.AgentQuitFromPossessingItem([{"type": "diamond", "amount": 1}])]

    def create_server_world_generators(self) -> List[handler.Handler]:
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[handler.Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[handler.Handler]:
        return []

    def create_server_initial_conditions(self) -> List[handler.Handler]:
        return [
            handlers.TimeInitialCondition(start_time=6000, allow_passage_of_time=True),
            handlers.SpawningInitialCondition(allow_spawning=True),
        ]

    def is_from_folder(self, folder: str) -> bool:
        return folder == f"o_{self.target_item}"

    def get_docstring(self) -> str:
        cadence = "every time it obtains an item" if self.dense else "once per distinct item"
        return f"Obtain a {self.target_item}; rewarded {cadence} along the item hierarchy."

    def determine_success_from_rewards(self, rewards: list) -> bool:
        # Success = the run hit (almost) every milestone reward at least once.  Counted
        # over UNIQUE reward values: the schedule reuses 4 and 32, so the reference's
        # len(schedule)-based threshold (obtain.py:160-169) could never be met.
        reward_values = {entry["reward"] for entry in self.reward_schedule}
        max_missing = round(len(reward_values) * 0.1)
        return len(set(rewards).intersection(reward_values)) >= len(reward_values) - max_missing


class CustomObtainDiamond(CustomObtain):
    def __init__(self, dense: bool, *args: Any, **kwargs: Any):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            target_item="diamond",
            dense=dense,
            reward_schedule=list(DIAMOND_REWARD_SCHEDULE),
            max_episode_steps=None,
            *args,
            **kwargs,
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_dia"


class CustomObtainIronPickaxe(CustomObtain):
    def __init__(self, dense: bool, *args: Any, **kwargs: Any):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            target_item="iron_pickaxe",
            dense=dense,
            reward_schedule=list(DIAMOND_REWARD_SCHEDULE[:-1]),  # up to the iron pickaxe
            max_episode_steps=None,
            *args,
            **kwargs,
        )

    def create_agent_handlers(self) -> List[handler.Handler]:
        return [handlers.AgentQuitFromCraftingItem([{"type": "iron_pickaxe", "amount": 1}])]

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_iron"
