"""Deterministic dummy environments — the CI workhorse.

Same contract as the reference (``/root/reference/sheeprl/envs/dummy.py:8-108``): dict
observation {rgb: uint8 [C,H,W], state: float} (or vector-only), fixed episode length,
frames filled with the step counter so pipelines are bit-checkable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np


class _DummyEnv(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        dict_obs_space: bool = True,
    ):
        self._dict_obs_space = dict_obs_space
        if dict_obs_space:
            self.observation_space = gym.spaces.Dict(
                {
                    "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                    "state": gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32),
                }
            )
        else:
            self.observation_space = gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32)
        self.reward_range = (-np.inf, np.inf)
        self._current_step = 0
        self._n_steps = n_steps

    def _get_obs(self):
        if self._dict_obs_space:
            return {
                "rgb": np.full(self.observation_space["rgb"].shape, self._current_step % 256, dtype=np.uint8),
                "state": np.full(self.observation_space["state"].shape, self._current_step, dtype=np.float32),
            }
        return np.full(self.observation_space.shape, self._current_step, dtype=np.float32)

    def step(self, action):
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self._get_obs(), 0.0, done, False, {}

    def reset(self, seed: Optional[int] = None, options=None):
        super().reset(seed=seed)
        self._current_step = 0
        return self._get_obs(), {}

    def render(self):
        if self._dict_obs_space:
            return np.transpose(self._get_obs()["rgb"], (1, 2, 0))
        return np.zeros((64, 64, 3), dtype=np.uint8)

    def close(self):
        pass


class LineWalkDummyEnv(gym.Env):
    """A tiny solvable MDP for learning tests (no reference counterpart; VERDICT r2
    items 1/5): the agent walks on a line of ``length`` cells and is paid +1 for every
    step it spends on the rightmost cell.

    * actions: ``Discrete(3)`` — 0 stay, 1 left, 2 right;
    * obs: ``{rgb, state}`` — ``state`` is the one-hot position, ``rgb`` renders the
      position as a white vertical bar on black, so the reward is a function of the
      VISIBLE state only.  A pixels-only agent (``cnn_keys=[rgb]``) can therefore
      improve its return only if the whole pixels → world model → imagination →
      policy loop works;
    * known returns over ``n_steps=16``, ``length=6``: optimal ≈ ``n_steps - length + 1``
      (walk right, then stay), random walk ≲ 1.5.

    Episode ends by TRUNCATION at ``n_steps`` (the step counter is not observable, so
    a termination there would be unlearnable for the continue model).
    """

    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(
        self,
        length: int = 6,
        n_steps: int = 16,
        image_size: Tuple[int, int, int] = (3, 64, 64),
    ):
        self._length = length
        self._n_steps = n_steps
        self._image_size = image_size
        self.action_space = gym.spaces.Discrete(3)
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                "state": gym.spaces.Box(0.0, 1.0, shape=(length,), dtype=np.float32),
            }
        )
        self.reward_range = (0.0, 1.0)
        self._pos = 0
        self._current_step = 0

    def _get_obs(self):
        c, h, w = self._image_size
        rgb = np.zeros((c, h, w), dtype=np.uint8)
        band = max(w // self._length, 1)
        start = self._pos * band
        rgb[:, :, start : start + band] = 255
        state = np.zeros((self._length,), dtype=np.float32)
        state[self._pos] = 1.0
        return {"rgb": rgb, "state": state}

    def step(self, action):
        action = int(np.asarray(action).reshape(-1)[0])
        if action == 1:
            self._pos = max(self._pos - 1, 0)
        elif action == 2:
            self._pos = min(self._pos + 1, self._length - 1)
        reward = 1.0 if self._pos == self._length - 1 else 0.0
        self._current_step += 1
        truncated = self._current_step >= self._n_steps
        return self._get_obs(), reward, False, truncated, {}

    def reset(self, seed: Optional[int] = None, options=None):
        super().reset(seed=seed)
        self._pos = 0
        self._current_step = 0
        return self._get_obs(), {}

    def render(self):
        return np.transpose(self._get_obs()["rgb"], (1, 2, 0))

    def close(self):
        pass


class ContinuousDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Box(-1.0, 1.0, shape=(action_dim,), dtype=np.float32)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)


class DiscreteDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 4,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Discrete(action_dim)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)


class MultiDiscreteDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dims: List[int] = [2, 2],
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.MultiDiscrete(action_dims)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)
