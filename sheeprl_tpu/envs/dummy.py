"""Deterministic dummy environments — the CI workhorse.

Same contract as the reference (``/root/reference/sheeprl/envs/dummy.py:8-108``): dict
observation {rgb: uint8 [C,H,W], state: float} (or vector-only), fixed episode length,
frames filled with the step counter so pipelines are bit-checkable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np


class _DummyEnv(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        dict_obs_space: bool = True,
    ):
        self._dict_obs_space = dict_obs_space
        if dict_obs_space:
            self.observation_space = gym.spaces.Dict(
                {
                    "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                    "state": gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32),
                }
            )
        else:
            self.observation_space = gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32)
        self.reward_range = (-np.inf, np.inf)
        self._current_step = 0
        self._n_steps = n_steps

    def _get_obs(self):
        if self._dict_obs_space:
            return {
                "rgb": np.full(self.observation_space["rgb"].shape, self._current_step % 256, dtype=np.uint8),
                "state": np.full(self.observation_space["state"].shape, self._current_step, dtype=np.float32),
            }
        return np.full(self.observation_space.shape, self._current_step, dtype=np.float32)

    def step(self, action):
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self._get_obs(), 0.0, done, False, {}

    def reset(self, seed: Optional[int] = None, options=None):
        super().reset(seed=seed)
        self._current_step = 0
        return self._get_obs(), {}

    def render(self):
        if self._dict_obs_space:
            return np.transpose(self._get_obs()["rgb"], (1, 2, 0))
        return np.zeros((64, 64, 3), dtype=np.uint8)

    def close(self):
        pass


class ContinuousDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Box(-1.0, 1.0, shape=(action_dim,), dtype=np.float32)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)


class DiscreteDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 4,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.Discrete(action_dim)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)


class MultiDiscreteDummyEnv(_DummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dims: List[int] = [2, 2],
        dict_obs_space: bool = True,
    ):
        self.action_space = gym.spaces.MultiDiscrete(action_dims)
        super().__init__(image_size=image_size, n_steps=n_steps, vector_shape=vector_shape, dict_obs_space=dict_obs_space)
