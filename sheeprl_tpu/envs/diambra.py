"""DIAMBRA arena adapter (reference: ``/root/reference/sheeprl/envs/diambra.py``).

Fighting-game envs; observations flattened into a dict of {rgb, flat vector keys}
(reference obs flattening ``diambra.py:123-128``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_DIAMBRA_AVAILABLE

if not _IS_DIAMBRA_AVAILABLE:
    raise ModuleNotFoundError("diambra is not installed: `pip install diambra diambra-arena`")

import diambra.arena  # noqa: E402


class DiambraWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        id: str,
        action_space: str = "DISCRETE",
        screen_size: int | tuple = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ):
        from diambra.arena import EnvironmentSettings, WrappersSettings

        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)
        if action_space not in {"DISCRETE", "MULTI_DISCRETE"}:
            raise ValueError(f"action_space must be 'DISCRETE' or 'MULTI_DISCRETE', got {action_space!r}")
        diambra_settings = dict(diambra_settings or {})
        diambra_wrappers = dict(diambra_wrappers or {})
        role = diambra_settings.pop("role", None)
        settings = EnvironmentSettings(
            **{
                **diambra_settings,
                "game_id": id,
                "action_space": getattr(diambra.arena.SpaceTypes, action_space),
                "n_players": 1,
                "role": getattr(diambra.arena.Roles, role) if role is not None else None,
                "render_mode": render_mode,
            }
        )
        if repeat_action > 1:
            # sticky actions need a 1:1 sim step ratio (reference diambra.py:64-69)
            settings.step_ratio = 1
        wrappers = WrappersSettings(**{**diambra_wrappers, "flatten": True, "repeat_action": repeat_action})
        # resize in-engine when possible: cheaper than a cv2 transform per step
        if increase_performance:
            settings.frame_shape = (*screen_size, int(grayscale))
        else:
            wrappers.frame_shape = (*screen_size, int(grayscale))
        self._env = diambra.arena.make(id, settings, wrappers, render_mode=render_mode, rank=rank, log_level=log_level)
        self.action_space = (
            gym.spaces.MultiDiscrete(self._env.action_space.nvec)
            if hasattr(self._env.action_space, "nvec")
            else gym.spaces.Discrete(self._env.action_space.n)
        )
        spaces: Dict[str, gym.spaces.Space] = {}
        for k, space in self._env.observation_space.spaces.items():
            if isinstance(space, gym.spaces.Box) and len(space.shape) == 3:
                h, w, c = space.shape
                spaces[k] = gym.spaces.Box(0, 255, (c, h, w), np.uint8)
            else:
                dim = int(np.prod(space.shape)) if hasattr(space, "shape") and space.shape else 1
                spaces[k] = gym.spaces.Box(-np.inf, np.inf, (dim,), np.float32)
        self.observation_space = gym.spaces.Dict(spaces)

    def _obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        out = {}
        for k, v in obs.items():
            v = np.asarray(v)
            if v.ndim == 3:
                out[k] = np.transpose(v, (2, 0, 1))
            else:
                out[k] = v.astype(np.float32).reshape(-1)
        return out

    def step(self, action):
        obs, reward, terminated, truncated, info = self._env.step(action)
        return self._obs(obs), reward, terminated, truncated, info

    def reset(self, seed=None, options=None):
        obs, info = self._env.reset(seed=seed)
        return self._obs(obs), info

    def render(self):
        return self._env.render()

    def close(self):
        self._env.close()
