"""MineRL adapter (reference: ``/root/reference/sheeprl/envs/minerl.py:48`` + custom
Navigate/Obtain task definitions under ``envs/minerl_envs/``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed")

import minerl  # noqa: E402, F401


class MineRLWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        seed: Optional[int] = None,
        break_speed_multiplier: int = 100,
        **kwargs: Any,
    ):
        import gym as old_gym

        self._env = old_gym.make(id)
        if seed is not None:
            self._env.seed(seed)
        self._height, self._width = height, width
        # Discretised functional action space mirroring the reference's mapping.
        self.action_space = gym.spaces.MultiDiscrete([12, 3, 8])
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, (3, height, width), np.uint8),
                "compass": gym.spaces.Box(-180, 180, (1,), np.float32),
                "inventory": gym.spaces.Box(-np.inf, np.inf, (1,), np.float32),
            }
        )

    def _obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        pov = np.asarray(obs.get("pov", np.zeros((self._height, self._width, 3))), dtype=np.uint8)
        compass = obs.get("compass", {}).get("angle", 0.0) if isinstance(obs.get("compass"), dict) else 0.0
        inventory = obs.get("inventory", {})
        dirt = float(inventory.get("dirt", 0)) if isinstance(inventory, dict) else 0.0
        return {
            "rgb": np.transpose(pov, (2, 0, 1)),
            "compass": np.asarray([compass], dtype=np.float32),
            "inventory": np.asarray([dirt], dtype=np.float32),
        }

    def _convert_action(self, action: np.ndarray) -> Dict[str, Any]:
        act = self._env.action_space.no_op()
        a0 = int(action[0])
        if a0 == 1:
            act["forward"] = 1
        elif a0 == 2:
            act["back"] = 1
        elif a0 == 3:
            act["left"] = 1
        elif a0 == 4:
            act["right"] = 1
        elif a0 == 5:
            act["jump"] = 1
            act["forward"] = 1
        elif a0 >= 6:
            act["camera"] = [[-15, 0], [15, 0], [0, -15], [0, 15], [0, 0], [0, 0]][a0 - 6]
        if int(action[1]) == 1:
            act["attack"] = 1
        return act

    def step(self, action):
        obs, reward, done, info = self._env.step(self._convert_action(np.asarray(action)))
        return self._obs(obs), reward, done, False, info

    def reset(self, seed=None, options=None):
        return self._obs(self._env.reset()), {}

    def close(self):
        self._env.close()
