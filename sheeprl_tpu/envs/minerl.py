"""MineRL adapter (reference: ``/root/reference/sheeprl/envs/minerl.py``).

Wraps the custom Navigate/Obtain env specs (``sheeprl_tpu/envs/minerl_envs.py``) behind
a flat ``Discrete`` action space built DYNAMICALLY from the task's action handlers
(reference ``:100-141``): one index per keyboard/camera primitive plus one per non-none
enum value of every craft/place/equip/smelt action.  Sticky attack/jump, pitch/yaw
limits and a multihot (full Minecraft item table) inventory/equipment encoding match
the MineDojo adapter's conventions.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, SupportsFloat, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed")

import minerl  # noqa: E402
from minerl.herobraine.hero import mc  # noqa: E402

from sheeprl_tpu.envs.minerl_envs import (  # noqa: E402
    CustomNavigate,
    CustomObtainDiamond,
    CustomObtainIronPickaxe,
)

CUSTOM_ENVS = {
    "custom_navigate": CustomNavigate,
    "custom_obtain_diamond": CustomObtainDiamond,
    "custom_obtain_iron_pickaxe": CustomObtainIronPickaxe,
}

N_ALL_ITEMS = len(mc.ALL_ITEMS)
ITEM_NAME_TO_ID = dict(zip(mc.ALL_ITEMS, range(N_ALL_ITEMS)))
NOOP_ACTION: Dict[str, Any] = {
    "camera": (0, 0),
    "forward": 0,
    "back": 0,
    "left": 0,
    "right": 0,
    "attack": 0,
    "sprint": 0,
    "jump": 0,
    "sneak": 0,
    "craft": "none",
    "nearbyCraft": "none",
    "nearbySmelt": "none",
    "place": "none",
    "equip": "none",
}
CAMERA_DELTAS = (
    np.array([-15, 0]),  # pitch down
    np.array([15, 0]),  # pitch up
    np.array([0, -15]),  # yaw left
    np.array([0, 15]),  # yaw right
)


class MineRLWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        break_speed_multiplier: Optional[int] = 100,
        multihot_inventory: bool = True,
        **kwargs: Any,
    ):
        self._height, self._width = height, width
        self._pitch_limits = pitch_limits
        self._sticky_attack = 0 if (break_speed_multiplier or 1) > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._multihot = multihot_inventory
        if "navigate" not in id.lower():
            kwargs.pop("extreme", None)
        self._env = CUSTOM_ENVS[id.lower()](
            break_speed=break_speed_multiplier, resolution=(height, width), **kwargs
        ).make()
        if seed is not None and hasattr(self._env, "seed"):
            self._env.seed(seed)

        # Discrete action table: index 0 = no-op; binary keys contribute one entry,
        # the camera four (±15° pitch/yaw), enum actions one per non-"none" value.
        self._actions: Dict[int, Dict[str, Any]] = {0: {}}
        idx = 1
        for name in self._env.action_space:
            space = self._env.action_space[name]
            if isinstance(space, minerl.herobraine.hero.spaces.Enum):
                values = sorted(set(space.values.tolist()) - {"none"})
                entries = [{name: v} for v in values]
            elif name == "camera":
                entries = [{name: delta} for delta in CAMERA_DELTAS]
            else:
                entries = [{name: 1}]
            for entry in entries:
                if name in {"jump", "sneak", "sprint"}:
                    entry["forward"] = 1  # match the MineDojo movement combos
                self._actions[idx] = entry
                idx += 1
        self.action_space = gym.spaces.Discrete(len(self._actions))

        if multihot_inventory:
            self._inventory_item_to_id = ITEM_NAME_TO_ID
            self._inventory_size = N_ALL_ITEMS
        else:
            names = list(self._env.observation_space["inventory"])
            self._inventory_item_to_id = dict(zip(names, range(len(names))))
            self._inventory_size = len(names)

        obs_space: Dict[str, gym.spaces.Space] = {
            "rgb": gym.spaces.Box(0, 255, (3, height, width), np.uint8),
            "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
            "inventory": gym.spaces.Box(0.0, np.inf, (self._inventory_size,), np.float32),
            "max_inventory": gym.spaces.Box(0.0, np.inf, (self._inventory_size,), np.float32),
        }
        if "compass" in self._env.observation_space.spaces:
            obs_space["compass"] = gym.spaces.Box(-180, 180, (1,), np.float32)
        self._has_equipment = "equipped_items" in self._env.observation_space.spaces
        if self._has_equipment:
            if multihot_inventory:
                self._equip_item_to_id = ITEM_NAME_TO_ID
                self._equip_size = N_ALL_ITEMS
            else:
                values = self._env.observation_space["equipped_items"]["mainhand"]["type"].values.tolist()
                self._equip_item_to_id = dict(zip(values, range(len(values))))
                self._equip_size = len(values)
            obs_space["equipment"] = gym.spaces.Box(0.0, 1.0, (self._equip_size,), np.int32)
        self.observation_space = gym.spaces.Dict(obs_space)

        self._pos = {"pitch": 0.0, "yaw": 0.0}
        self._max_inventory = np.zeros(self._inventory_size)
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # -- conversions --------------------------------------------------------
    def _convert_action(self, action: np.ndarray) -> Dict[str, Any]:
        out = copy.deepcopy(NOOP_ACTION)
        out.update(self._actions[int(np.asarray(action).item())])
        # Sticky attack/jump (reference ``:237-251``): a selected attack (jump) keeps
        # firing for the next N steps; attack suppresses jumping, jumping moves forward.
        if self._sticky_attack:
            if out["attack"]:
                self._sticky_attack_counter = self._sticky_attack
            if self._sticky_attack_counter > 0:
                out["attack"] = 1
                out["jump"] = 0
                self._sticky_attack_counter -= 1
        if self._sticky_jump:
            if out["jump"]:
                self._sticky_jump_counter = self._sticky_jump
            if self._sticky_jump_counter > 0:
                out["jump"] = 1
                out["forward"] = 1
                self._sticky_jump_counter -= 1
        return out

    def _convert_inventory(self, inventory: Dict[str, Any]) -> Dict[str, np.ndarray]:
        counts = np.zeros(self._inventory_size)
        for item, quantity in inventory.items():
            counts[self._inventory_item_to_id[item]] += 1 if item == "air" else quantity
        self._max_inventory = np.maximum(counts, self._max_inventory)
        return {"inventory": counts.astype(np.float32), "max_inventory": self._max_inventory.astype(np.float32)}

    def _convert_equipment(self, equipped: Dict[str, Any]) -> np.ndarray:
        equip = np.zeros(self._equip_size, dtype=np.int32)
        equip[self._equip_item_to_id.get(equipped["mainhand"]["type"], self._equip_item_to_id["air"])] = 1
        return equip

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        out = {
            "rgb": np.asarray(obs["pov"], dtype=np.uint8).transpose(2, 0, 1).copy(),
            "life_stats": np.asarray(
                [obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["air"]],
                dtype=np.float32,
            ),
            **self._convert_inventory(obs["inventory"]),
        }
        if self._has_equipment:
            out["equipment"] = self._convert_equipment(obs["equipped_items"])
        if "compass" in self.observation_space.spaces:
            out["compass"] = np.asarray(obs["compass"]["angle"], dtype=np.float32).reshape(-1)
        return out

    # -- gym API -------------------------------------------------------------
    def step(self, action: np.ndarray) -> Tuple[Any, SupportsFloat, bool, bool, Dict[str, Any]]:
        converted = self._convert_action(action)
        # Clamp the camera pitch to the limits (reference ``:295-299``).
        next_pitch = self._pos["pitch"] + converted["camera"][0]
        next_yaw = ((self._pos["yaw"] + converted["camera"][1]) + 180) % 360 - 180
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted["camera"] = np.array([0, converted["camera"][1]])
            next_pitch = self._pos["pitch"]

        obs, reward, done, info = self._env.step(converted)
        self._pos = {"pitch": next_pitch, "yaw": next_yaw}
        return self._convert_obs(obs), reward, done, False, info

    def reset(self, seed=None, options=None):
        obs = self._env.reset()
        self._max_inventory = np.zeros(self._inventory_size)
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        return self._convert_obs(obs), {}

    def render(self):
        return self._env.render(self.render_mode)

    def close(self):
        self._env.close()
