"""Crafter adapter (reference: ``/root/reference/sheeprl/envs/crafter.py:17-66``)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np

from sheeprl_tpu.utils.imports import _IS_CRAFTER_AVAILABLE

if not _IS_CRAFTER_AVAILABLE:
    raise ModuleNotFoundError("crafter is not installed: `pip install crafter`")

import crafter  # noqa: E402


class CrafterWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"], "render_fps": 30}

    def __init__(
        self, id: str = "crafter_reward", screen_size: Tuple[int, int] | int = (64, 64), seed: Optional[int] = None
    ):
        if id not in {"crafter_reward", "crafter_nonreward"}:
            raise ValueError(f"id must be 'crafter_reward' or 'crafter_nonreward', got {id!r}")
        if isinstance(screen_size, int):
            screen_size = (screen_size, screen_size)
        self._env = crafter.Env(size=screen_size, reward=(id == "crafter_reward"), seed=seed)
        self.observation_space = gym.spaces.Dict(
            {"rgb": gym.spaces.Box(0, 255, (3, *screen_size), np.uint8)}
        )
        self.action_space = gym.spaces.Discrete(self._env.action_space.n)
        self.reward_range = (-np.inf, np.inf)

    def _obs(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        return {"rgb": np.transpose(obs, (2, 0, 1))}

    def step(self, action):
        obs, reward, done, info = self._env.step(int(action))
        truncated = bool(info.get("discount", 1.0) != 0.0) and done
        terminated = done and not truncated
        return self._obs(obs), reward, terminated, truncated, info

    def reset(self, seed=None, options=None):
        if seed is not None:
            self._env._seed = seed
        return self._obs(self._env.reset()), {}

    def render(self):
        return self._env.render()

    def close(self):
        pass
