"""CLI entry points (reference: ``/root/reference/sheeprl/cli.py``).

``python -m sheeprl_tpu exp=dreamer_v3 env=atari algo.learning_rate=1e-4`` composes the
config tree, dispatches to the registered algorithm entrypoint and runs it under a
device-mesh context.  There is no process-per-device launch (the reference's
``fabric.launch``, ``cli.py:199``): JAX is single-controller, one process per *host*,
with all local devices driven through the mesh.
"""

from __future__ import annotations

import datetime
import importlib
import os
import sys
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional

from sheeprl_tpu.config.core import DotDict, compose, load_config, print_config, save_config
from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry, get_algorithm, get_evaluation
from sheeprl_tpu.utils.timer import timer


def _honor_platform_env() -> None:
    """Make ``JAX_PLATFORMS=cpu python -m sheeprl_tpu ...`` actually select the
    platform.  Accelerator images may pin ``jax_platforms`` from ``sitecustomize``
    at interpreter start, which silently wins over the environment variable; state
    -based runs whose per-step policy calls would otherwise pay a device round
    trip per env step need a working CPU escape hatch.  Must run before the first
    backend initialisation (i.e. before mesh setup touches ``jax.devices``)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            already_initialized = bool(getattr(jax._src.xla_bridge, "_backends", None))
        except Exception:
            already_initialized = False
        requested = [p.strip() for p in plat.split(",") if p.strip()]
        if already_initialized and jax.default_backend() not in requested:
            # Too late to honor the request: some import (sitecustomize, a plugin, an
            # eager device query) already initialised a backend, and jax_platforms is
            # read only at first initialisation.  Warn instead of failing silently.
            warnings.warn(
                f"JAX_PLATFORMS={plat!r} is set but a JAX backend is already initialized "
                f"(devices on {jax.default_backend()!r}); the platform request may be "
                "ignored for this run. Set JAX_PLATFORMS before anything imports and "
                "uses JAX (e.g. avoid eager jax.devices() calls in sitecustomize).",
                stacklevel=2,
            )
        jax.config.update("jax_platforms", plat)


def _import_algorithms() -> None:
    """Populate the registries (reference imports every algo in ``sheeprl/__init__.py:18-47``)."""
    import sheeprl_tpu.algos  # noqa: F401  (registers everything on import)


def resume_from_checkpoint(cfg: DotDict) -> DotDict:
    """Merge the checkpoint run's config, protecting training-critical keys
    (reference ``cli.py:23-58``)."""
    ckpt_path = Path(cfg.checkpoint.resume_from)
    run_dir = ckpt_path.parent.parent if ckpt_path.is_dir() else ckpt_path.parent
    old_cfg_path = run_dir / "config.yaml"
    if not old_cfg_path.is_file():
        old_cfg_path = ckpt_path.parent / "config.yaml"
    if not old_cfg_path.is_file():
        raise FileNotFoundError(
            f"Cannot resume from {ckpt_path}: no config.yaml found alongside the checkpoint"
        )
    old_cfg = load_config(old_cfg_path)
    for key in ("env", "algo", "buffer", "distribution", "exp_name", "seed"):
        if key in old_cfg:
            cfg[key] = old_cfg[key]
    cfg.checkpoint.resume_from = str(ckpt_path)
    return cfg


def check_configs(cfg: DotDict) -> None:
    """Config validation (reference ``cli.py:271-345``)."""
    algo = cfg.get("algo", {})
    if not algo or "name" not in algo:
        raise ValueError("No algorithm selected: choose one with 'exp=<preset>' or 'algo=<name>'")
    entry = get_algorithm(algo["name"])
    decoupled = entry["decoupled"]
    if decoupled and cfg.env.get("sync_env", False) is False and cfg.env.num_envs <= 0:
        raise ValueError("Decoupled algorithms need at least one environment")
    cnn_keys = algo.get("cnn_keys", {}).get("encoder", [])
    mlp_keys = algo.get("mlp_keys", {}).get("encoder", [])
    if not isinstance(cnn_keys, list) or not isinstance(mlp_keys, list):
        raise ValueError("algo.cnn_keys.encoder and algo.mlp_keys.encoder must be lists")
    if cfg.metric.get("log_level", 1) not in (0, 1):
        raise ValueError(f"Invalid metric.log_level: {cfg.metric.log_level}")
    capture = cfg.get("obs", {}).get("capture_steps")
    if capture is not None:
        if not (isinstance(capture, (list, tuple)) and len(capture) == 2):
            raise ValueError(f"obs.capture_steps must be [start_update, end_update]; got {capture!r}")
        start, end = int(capture[0]), int(capture[1])
        if start < 1 or end < start:
            raise ValueError(
                f"obs.capture_steps window must satisfy 1 <= start <= end; got [{start}, {end}]"
            )
    # DV1/DV2 (and their P2E variants) pin the decoder geometry to 64×64 single-frame
    # (reference dreamer_v2.py:399-400).  Validate instead of silently overwriting the
    # user's config, so the saved config.yaml never contradicts the CLI.
    if str(algo.get("name", "")).startswith(("dreamer_v1", "dreamer_v2", "p2e_dv1", "p2e_dv2")) and cnn_keys:
        if int(cfg.env.get("screen_size") or 64) != 64 or int(cfg.env.get("frame_stack") or 1) > 1:
            raise ValueError(
                f"{algo['name']} pixel observations require env.screen_size=64 and "
                f"env.frame_stack<=1 (the decoder geometry is pinned to one 64x64 frame); "
                f"got screen_size={cfg.env.get('screen_size')}, "
                f"frame_stack={cfg.env.get('frame_stack')}."
            )
    # Sequence-sampling algorithms: the prefill must leave every env's sub-buffer with
    # at least one full sequence, or the first train iteration dies mid-run with a
    # sampling error.  Prefill iterations (= rows per env) are
    # learning_starts // (num_envs * world * action_repeat) — the loops' own divisor.
    # World size comes from the config, NOT jax.process_count(): touching jax here
    # would initialize the backend before jax.distributed.initialize() runs.
    seq_len = int(algo.get("per_rank_sequence_length", 0) or 0)
    learning_starts = int(algo.get("learning_starts", 0) or 0)
    buffer_prefilled = bool(cfg.checkpoint.get("resume_from")) or bool(
        cfg.get("buffer", {}).get("load_from_exploration", False)
    )
    if seq_len > 1 and learning_starts > 0 and not buffer_prefilled and not cfg.get("dry_run", False):
        dist = cfg.get("mesh", {}).get("distributed", {}) or {}
        # Multi-process launches configured through a cluster launcher leave
        # num_processes null and let jax.distributed auto-detect: fall back to the
        # launcher env vars so the guard doesn't underestimate world as 1.  Only
        # trust them when a coordinator_address shows this run IS distributed —
        # a single-process run inside a SLURM/MPI allocation must not be rejected.
        world = int(dist.get("num_processes") or 1)
        if dist.get("coordinator_address") and not dist.get("num_processes"):
            world = int(
                os.environ.get("SLURM_NTASKS") or os.environ.get("OMPI_COMM_WORLD_SIZE") or 1
            )
        steps_per_iter = max(cfg.env.num_envs * world * max(cfg.env.action_repeat, 1), 1)
        rows_per_env = learning_starts // steps_per_iter
        if rows_per_env < seq_len:
            raise ValueError(
                f"algo.learning_starts={learning_starts} prefills only ~{rows_per_env} steps per "
                f"environment ({cfg.env.num_envs} envs x {world} process(es) x action_repeat "
                f"{cfg.env.action_repeat}), but algo.per_rank_sequence_length={seq_len} needs at "
                f"least {seq_len} steps per env before the first gradient step. Raise "
                f"learning_starts to >= {seq_len * steps_per_iter} or lower the sequence "
                f"length / env count."
            )


def run_algorithm(cfg: DotDict) -> None:
    """Registry lookup + mesh-context construction + entrypoint call
    (reference ``cli.py:60-199``)."""
    from sheeprl_tpu.parallel.mesh import make_mesh_context, maybe_init_distributed
    from sheeprl_tpu.utils.metric import MetricAggregator

    entry = get_algorithm(cfg.algo.name)
    kwargs: Dict[str, Any] = {}
    if "finetuning" in cfg.algo.name and "p2e" in entry["module"]:
        # Load + merge the exploration run's env config (reference cli.py:117-148).
        from sheeprl_tpu.algos.p2e import load_exploration_config

        kwargs["exploration_cfg"] = load_exploration_config(cfg)
    precision = cfg.get("float32_matmul_precision")
    if precision:
        # reference: torch.set_float32_matmul_precision(cfg.float32_matmul_precision)
        import jax

        algo_precision = str(cfg.algo.get("precision", "mesh")).lower()
        if any(t in algo_precision for t in ("bf16", "fp16", "16-mixed", "16-true")):
            # jax_default_matmul_precision only governs f32 dots; with an
            # explicit 16-bit algo.precision the knob is dead weight and
            # silently proceeding hides that (howto/precision.md).
            warnings.warn(
                f"float32_matmul_precision={precision!r} has no effect: "
                f"algo.precision={algo_precision!r} runs the matmuls in 16-bit "
                "compute, so the f32 dot precision knob never applies — set "
                "algo.precision=f32 if you want full-precision matmuls",
                stacklevel=2,
            )
        jax.config.update("jax_default_matmul_precision", str(precision))
    # Persistent XLA compilation cache (ROADMAP item 3's cold-start story, shared
    # with the serve startup): see utils/compile_cache.py.
    from sheeprl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(cfg.get("compile_cache", {}) or {})
    # Fault layer (sheeprl_tpu/fault, howto/fault_tolerance.md): SIGTERM/SIGINT
    # become a sticky flag every training loop polls at its safe boundary (one
    # final checkpoint + PREEMPTED marker + exit 75), and any scheduled chaos
    # faults are parsed before EnvPool forks its workers so the worker-fault spec
    # rides the fork.
    from sheeprl_tpu.fault import chaos as fault_chaos
    from sheeprl_tpu.fault import install_signal_handlers
    from sheeprl_tpu.fault.preemption import Preempted

    install_signal_handlers(grace_seconds=cfg.get("fault", {}).get("grace_seconds", 0))
    fault_chaos.install(cfg)

    # Concurrency race detector (jaxlint-threads runtime half,
    # sheeprl_tpu/analysis/threads/runtime.py): opt-in lock instrumentation
    # installed at the same boundary as chaos/signals so every lock the run
    # creates afterwards is observed; its JSONL report lands in
    # <log_dir>/races/ at the exit/crash boundary below.
    from sheeprl_tpu.analysis.threads import runtime as race_runtime

    race_detector = race_runtime.maybe_install(cfg)

    maybe_init_distributed(cfg.get("mesh", {}))
    ctx = make_mesh_context(cfg)

    if cfg.metric.get("disable_timer", False):
        timer.disabled = True
    MetricAggregator.disabled = cfg.metric.get("log_level", 1) == 0

    # Flight-recorder crash boundary (sheeprl_tpu/obs/flight_recorder.py): any
    # exception escaping the algorithm — including strict-mode NonFiniteError/
    # SignatureDriftError/RecompileError and RolloutAbortError — dumps the black
    # box (<log_dir>/blackbox/) before propagating.  The recorder is installed by
    # the entry point's TrainingMonitor and cleared here so back-to-back runs in
    # one process never cross-contaminate.
    from sheeprl_tpu.obs import flight_recorder
    from sheeprl_tpu.obs import fleet as obs_fleet

    try:
        entry["entrypoint"](ctx, cfg, **kwargs)
    except Preempted:
        # Graceful preemption is not a crash: the boundary checkpoint and the
        # PREEMPTED marker are already on disk — no blackbox dump.
        raise
    except Exception as exc:
        dump = flight_recorder.dump_active("crash", exc)
        if dump:
            print(f"flight recorder: black box dumped to {dump}", file=sys.stderr)
        # A crashing process with a private in-process aggregator (obs.fleet.dir
        # mode) flags the crash in its final snapshot before the plane goes down.
        obs_fleet.close_active(error=exc)
        raise
    finally:
        # Race report first: its headline counts merge into the flight recorder
        # and the fleet exporter's final flush before those planes close.  The
        # run's log dir is only resolved inside the entry point (the logger owns
        # the version_N subdir), so the detector borrows the flight recorder's.
        if race_detector is not None:
            if race_detector.log_dir is None:
                recorder = flight_recorder.get_active()
                if recorder is not None:
                    race_detector.log_dir = recorder.log_dir
            race_runtime.dump_active("run-end")
            race_runtime.uninstall()
        flight_recorder.install(None)
        obs_fleet.close_active()
        # Cost-model registry is process-global: clear it between multirun jobs
        # so one job's lowered FLOPs never leak into the next job's MFU.
        from sheeprl_tpu.obs import perf as obs_perf

        obs_perf.reset()


def eval_algorithm(cfg: DotDict) -> None:
    """Evaluation dispatch (reference ``cli.py:202-268``).  ``cfg`` is the run's saved
    config with the user's CLI overrides already merged on top
    (``_load_checkpoint_cfg``), so structural keys (algorithm, model sizes, obs keys)
    match the checkpoint unless the user explicitly overrides them.  Evaluation always
    uses a single process with one environment."""
    from sheeprl_tpu.parallel.mesh import make_mesh_context

    ckpt_path = Path(cfg.checkpoint_path)
    if "capture_video" in cfg:  # top-level convenience alias for env.capture_video
        cfg.env.capture_video = bool(cfg.capture_video)  # jaxlint: disable=JL006
    cfg.env.num_envs = 1
    cfg.run_name = cfg.get("run_name") or _default_run_name(cfg)

    evaluate_fn = get_evaluation(cfg.algo.name)
    ctx = make_mesh_context(cfg)
    evaluate_fn(ctx, cfg, str(ckpt_path))


def _default_run_name(cfg: Dict[str, Any]) -> str:
    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    return f"{stamp}_{cfg.get('exp_name', 'run')}_{cfg.get('seed', 0)}"


def expand_multirun(overrides: List[str]) -> List[List[str]]:
    """Hydra-multirun semantics (reference ``cli.py:358`` ``@hydra.main`` with ``-m``):
    every override whose value is a bare comma-separated list becomes a sweep axis,
    and the grid is their cartesian product, e.g. ``algo.lr=1e-4,3e-4 seed=1,2`` →
    4 jobs.  Bracketed/quoted values (``cnn_keys.encoder=[rgb,depth]``) are single
    values, never axes."""
    import itertools

    axes: List[List[str]] = []
    for ov in overrides:
        key, eq, val = ov.partition("=")
        if eq and "," in val and not val.lstrip().startswith(("[", "{", "(", "'", '"')):
            axes.append([f"{key}={v}" for v in val.split(",")])
        else:
            axes.append([ov])
    return [list(combo) for combo in itertools.product(*axes)]


def run(args: Optional[List[str]] = None) -> None:
    """Train entry: ``python -m sheeprl_tpu exp=... key=value ...``

    ``-m`` / ``--multirun`` sweeps comma-separated override values as a grid
    (sequential execution), mirroring the reference's Hydra multirun: each job's
    ``run_name`` gains a ``multirun_<stamp>/job<i>`` prefix so the sweep lands in
    one directory tree."""
    _honor_platform_env()
    _import_algorithms()
    overrides = list(args if args is not None else sys.argv[1:])
    multirun = False
    for flag in ("-m", "--multirun"):
        if flag in overrides:
            multirun = True
            overrides = [ov for ov in overrides if ov != flag]
    jobs = expand_multirun(overrides) if multirun else [overrides]
    if multirun and len(jobs) > 1:
        stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
        print(f"multirun: {len(jobs)} jobs")
    for i, job_overrides in enumerate(jobs):
        cfg = compose(overrides=job_overrides)
        if cfg.checkpoint.get("resume_from"):
            cfg = resume_from_checkpoint(cfg)
        if multirun and len(jobs) > 1:
            base = cfg.get("run_name") or _default_run_name(cfg)
            cfg.run_name = f"multirun_{stamp}/job{i}_{base}"
            print(f"multirun job {i}/{len(jobs) - 1}: {' '.join(job_overrides)}")
        elif not cfg.get("run_name"):
            cfg.run_name = _default_run_name(cfg)
        check_configs(cfg)
        if os.environ.get("SHEEPRL_TPU_QUIET", "0") != "1":
            print_config(cfg)
        _run_with_autoresume(cfg)


def _run_with_autoresume(cfg: DotDict) -> None:
    """Run one job under the fault policy (``fault`` config group).

    Without ``fault.autoresume``: a graceful preemption exits with the resumable
    code 75 (EX_TEMPFAIL) so fleet schedulers / ``sheeprl_tpu.supervise`` relaunch
    it; every other exception propagates as usual (after the blackbox dump).

    With ``fault.autoresume=True``: preemptions resume immediately from the
    boundary checkpoint and retryable crashes relaunch from the latest *valid*
    checkpoint with bounded exponential backoff — the in-process mirror of
    ``python -m sheeprl_tpu.supervise`` (which alone survives SIGKILL/OOM).
    """
    import time

    from sheeprl_tpu.fault import classify as fault_classify
    from sheeprl_tpu.fault import counters as fault_counters
    from sheeprl_tpu.fault import preemption as fault_preemption
    from sheeprl_tpu.fault.supervisor import (
        backoff_seconds,
        fault_cfg,
        find_resume_checkpoint,
        run_dir_for,
    )

    f_cfg = fault_cfg(cfg)
    autoresume = bool(f_cfg.get("autoresume", False))
    max_retries = int(f_cfg.get("max_retries", 3))
    retries = 0
    while True:
        try:
            run_algorithm(cfg)
            return
        except fault_preemption.Preempted as p:
            if not autoresume:
                print(
                    f"preempted at step {p.step}; resumable checkpoint: "
                    f"{p.ckpt_path or 'none'} (exit {fault_preemption.RESUMABLE_EXIT_CODE})",
                    file=sys.stderr,
                )
                raise SystemExit(fault_preemption.RESUMABLE_EXIT_CODE)
            fault_preemption.clear_preemption()
            fault_counters.bump("Fault/restarts")
            resume = p.ckpt_path or find_resume_checkpoint(run_dir_for(cfg))
            print(
                f"fault.autoresume: preempted at step {p.step}; resuming"
                + (f" from {resume}" if resume else " from scratch"),
                file=sys.stderr,
            )
        except Exception as exc:
            if not autoresume:
                raise
            if fault_classify.classify_exception(exc) == fault_classify.FATAL:
                print(
                    f"fault.autoresume: {type(exc).__name__} is deterministic — not retrying",
                    file=sys.stderr,
                )
                raise
            retries += 1
            if retries > max_retries:
                print(f"fault.autoresume: exceeded fault.max_retries={max_retries}", file=sys.stderr)
                raise
            fault_counters.bump("Fault/restarts")
            delay = backoff_seconds(
                retries, float(f_cfg.get("backoff_s", 2.0)), float(f_cfg.get("backoff_max_s", 60.0))
            )
            print(
                f"fault.autoresume: {type(exc).__name__}; retry {retries}/{max_retries} "
                f"in {delay:.1f}s",
                file=sys.stderr,
            )
            time.sleep(delay)
            resume = find_resume_checkpoint(run_dir_for(cfg))
        if resume:
            cfg.checkpoint.resume_from = str(resume)


def _load_checkpoint_cfg(overrides: List[str], path_key: str) -> tuple:
    """Extract ``<path_key>=...`` from the overrides, load the checkpoint run's
    config.yaml and apply the remaining overrides on top (reference ``cli.py:369-401``).

    The value may also be a registry spec ``name[:version|stage|latest]`` instead
    of a filesystem path: it resolves through the model registry
    (``model_manager.registry_dir`` override, or the default ``models_registry``)
    to the registered payload, whose dir carries its own ``config.yaml``."""
    ckpt = None
    rest = []
    for ov in overrides:
        if ov.startswith(f"{path_key}="):
            ckpt = ov.split("=", 1)[1]
        else:
            rest.append(ov)
    if ckpt is None:
        raise ValueError(f"this entry point requires {path_key}=<path>")
    ckpt_path = Path(ckpt)
    if not ckpt_path.exists() and not ckpt.startswith(("/", ".", "~")):
        from sheeprl_tpu.serve.router import resolve_registry_checkpoint

        name, version, ckpt_path = resolve_registry_checkpoint(ckpt, rest)
        print(f"resolved {ckpt!r} -> {name} v{version} ({ckpt_path})")
    run_dir = ckpt_path.parent.parent if ckpt_path.is_dir() else ckpt_path.parent
    cfg_path = run_dir / "config.yaml"
    if not cfg_path.is_file():
        cfg_path = ckpt_path.parent / "config.yaml"
    if not cfg_path.is_file() and ckpt_path.is_dir():
        # Registry payloads are self-contained: config.yaml lives INSIDE the dir.
        cfg_path = ckpt_path / "config.yaml"
    if not cfg_path.is_file():
        raise FileNotFoundError(f"No config.yaml found alongside checkpoint {ckpt}")
    cfg = load_config(cfg_path)
    from sheeprl_tpu.config.core import _parse_value, _set_dotted

    for ov in rest:
        if "=" not in ov:
            raise ValueError(f"Malformed override {ov!r}")
        key, _, val = ov.partition("=")
        _set_dotted(cfg, key.lstrip("+"), _parse_value(val))
    return DotDict.wrap(cfg), ckpt_path


def evaluate(args: Optional[List[str]] = None) -> None:
    """Eval entry: ``python -m sheeprl_tpu.eval checkpoint_path=... [overrides]``"""
    _honor_platform_env()
    _import_algorithms()
    overrides = list(args if args is not None else sys.argv[1:])
    cfg, ckpt_path = _load_checkpoint_cfg(overrides, "checkpoint_path")
    cfg.checkpoint_path = str(ckpt_path)
    # Eval records a video by default regardless of the training run's setting
    # (reference cli.py:378); an explicit override still wins.
    overridden = {ov.partition("=")[0].lstrip("+") for ov in overrides}
    if not overridden & {"env.capture_video", "capture_video"}:
        cfg.env.capture_video = True
    eval_algorithm(cfg)


def registration(args: Optional[List[str]] = None) -> None:
    """Model-registration entry (reference ``cli.py:408`` / ``sheeprl-registration``):
    ``python -m sheeprl_tpu.registration checkpoint_path=<ckpt_dir> [model_manager.name=...]``
    registers a training checkpoint's models in the configured registry."""
    from sheeprl_tpu.utils.model_manager import build_model_manager

    overrides = list(args if args is not None else sys.argv[1:])
    cfg, ckpt_path = _load_checkpoint_cfg(overrides, "checkpoint_path")

    mm_cfg = cfg.get("model_manager", {}) or {}
    name = mm_cfg.get("name") or f"{cfg.algo.name}_{cfg.env.id}"
    manager = build_model_manager(cfg)
    version = manager.register_model(
        str(ckpt_path),
        name,
        model_keys=list(mm_cfg.get("models", {}) or []),
        metadata={"algo": cfg.algo.name, "env": cfg.env.id, "seed": cfg.seed},
    )
    print(f"Registered {name} version {version}")


def available_algorithms() -> List[str]:
    _import_algorithms()
    return sorted(algorithm_registry)


def agents(args: Optional[List[str]] = None) -> None:
    """List registered agents (reference ``sheeprl-agents`` /
    ``available_agents.py``): one row per entry point, with its module, whether it
    runs decoupled, and whether an evaluation entry is registered."""
    _import_algorithms()
    rows = []
    for name in sorted(algorithm_registry):
        entry = algorithm_registry[name]
        rows.append(
            (
                name,
                entry["module"],
                "yes" if entry.get("decoupled") else "no",
                "yes" if name in evaluation_registry else "no",
            )
        )
    headers = ("algorithm", "module", "decoupled", "evaluable")
    widths = [max((len(r[i]) for r in rows), default=0) for i in range(len(headers))]
    widths = [max(w, len(h)) for w, h in zip(widths, headers)]
    line = "  ".join(h.ljust(w) for h, w in zip(headers, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
