"""Device-mesh context: the TPU-native replacement for Lightning Fabric.

The reference's L0 substrate is ``Fabric(devices, strategy, accelerator, precision)``
plus NCCL collectives (``/root/reference/sheeprl/cli.py:101,149``).  Here the substrate
is a ``jax.sharding.Mesh`` over ICI/DCN:

* data parallelism = shard the batch over the ``data`` axis; XLA/GSPMD inserts the
  gradient ``psum`` when params are replicated and the loss is a global mean;
* an optional ``model`` (tensor-parallel) axis is free with GSPMD sharding rules —
  something the reference never had (SURVEY §2.4);
* multi-host runs initialise ``jax.distributed`` and the same code path scales over DCN.

``MeshContext`` carries mesh + shardings + precision policy + process topology, and is
passed to every algorithm ``main`` the way ``fabric`` is in the reference.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_distributed_initialized = False

#: Default multi-host barrier timeout (seconds); override with
#: ``SHEEPRL_TPU_BARRIER_TIMEOUT_S`` (<=0 disables the timeout entirely).
DEFAULT_BARRIER_TIMEOUT_S = 600.0


class BarrierTimeoutError(RuntimeError):
    """A multi-host barrier did not complete in time: a peer process is likely dead
    (preempted, OOM-killed, crashed before reaching the barrier).  Raised instead of
    hanging forever so the supervisor can classify and relaunch the run."""


def _wait_with_timeout(fn, name: str, timeout_s: float) -> None:
    """Run blocking ``fn`` on a side thread and give up after ``timeout_s``.

    ``sync_global_devices`` has no cancellation API, so the orphaned thread is left
    to die with the process — acceptable, because the only caller reaction to a
    barrier timeout is to tear the process down and let the supervisor relaunch."""
    import threading

    result: Dict[str, Any] = {}

    def target() -> None:
        try:
            fn()
            result["ok"] = True
        except Exception as e:  # pragma: no cover - backend-specific failures
            result["error"] = e

    t = threading.Thread(target=target, name=f"barrier-{name}", daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise BarrierTimeoutError(
            f"multi-host barrier {name!r} timed out after {timeout_s:.0f}s: a peer "
            "process is likely dead or preempted (this rank would otherwise hang "
            "forever). Restart the run from the latest checkpoint — "
            "`python -m sheeprl_tpu.supervise` automates this — or raise/disable the "
            "timeout with SHEEPRL_TPU_BARRIER_TIMEOUT_S (<=0 disables)."
        )
    if "error" in result:
        raise result["error"]


def sync_global_devices_with_timeout(name: str, timeout_s: Optional[float] = None) -> None:
    """``multihost_utils.sync_global_devices`` with a deadline and an actionable
    error.  No-op in single-process runs; the env var
    ``SHEEPRL_TPU_BARRIER_TIMEOUT_S`` overrides the default (read per call, so a
    long planned stall — e.g. one rank compiling — can widen it mid-run)."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    if timeout_s is None:
        timeout_s = float(os.environ.get("SHEEPRL_TPU_BARRIER_TIMEOUT_S", DEFAULT_BARRIER_TIMEOUT_S))
    if timeout_s <= 0:
        multihost_utils.sync_global_devices(name)
        return
    _wait_with_timeout(lambda: multihost_utils.sync_global_devices(name), name, timeout_s)


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` moved out of ``jax.experimental`` only in newer releases;
    dispatch to whichever spelling this jax has so shard_map consumers (the
    sharded replay mirror, ring attention) work on both (0.4.x ships
    ``jax.experimental.shard_map.shard_map`` only)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


#: Env-var spellings of ``mesh.distributed.*`` so the Sebulba launcher and
#: hand-started processes share one init path with config-driven runs (config
#: wins when both are set — an explicit override beats ambient environment).
COORDINATOR_ADDRESS_ENV_VAR = "SHEEPRL_TPU_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV_VAR = "SHEEPRL_TPU_NUM_PROCESSES"
PROCESS_ID_ENV_VAR = "SHEEPRL_TPU_PROCESS_ID"


def maybe_init_distributed(mesh_cfg: Dict[str, Any], timeout_s: Optional[float] = None) -> None:
    """Initialise multi-host JAX when requested (replaces Fabric ``num_nodes``).
    Takes the ``mesh`` sub-config (not the root config).  Idempotent:
    ``jax.distributed.initialize`` may only run once per process, and multirun
    sweeps call this once per job.

    Coordinator address / process count / process id come from the config or —
    when the config leaves them unset — from ``SHEEPRL_TPU_COORDINATOR_ADDRESS``
    / ``SHEEPRL_TPU_NUM_PROCESSES`` / ``SHEEPRL_TPU_PROCESS_ID``, so a launcher
    can stamp the rendezvous on child environments without config surgery.  The
    init itself runs under the barrier-timeout machinery: a peer that never
    shows up raises :class:`BarrierTimeoutError` instead of hanging this process
    forever (``SHEEPRL_TPU_BARRIER_TIMEOUT_S`` overrides, <=0 disables)."""
    global _distributed_initialized
    dist = mesh_cfg.get("distributed", {}) or {}
    coordinator = dist.get("coordinator_address") or os.environ.get(COORDINATOR_ADDRESS_ENV_VAR)
    if not coordinator or _distributed_initialized:
        return

    def pick(key: str, env_var: str) -> Optional[int]:
        value = dist.get(key)
        if value is None and os.environ.get(env_var):
            value = os.environ[env_var]
        return None if value is None else int(value)

    num_processes = pick("num_processes", NUM_PROCESSES_ENV_VAR)
    process_id = pick("process_id", PROCESS_ID_ENV_VAR)
    if timeout_s is None:
        timeout_s = float(os.environ.get("SHEEPRL_TPU_BARRIER_TIMEOUT_S", DEFAULT_BARRIER_TIMEOUT_S))

    def init() -> None:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )

    if timeout_s <= 0:
        init()
    else:
        _wait_with_timeout(init, "jax_distributed_initialize", timeout_s)
    _distributed_initialized = True


def build_mesh(
    data: int = -1,
    model: int = 1,
    sequence: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, model, sequence)`` mesh. ``data=-1`` consumes remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = model * sequence
    if data == -1:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by model*sequence={fixed}")
        data = n // fixed
    if data * model * sequence != n:
        raise ValueError(f"mesh {data}x{model}x{sequence} != {n} devices")
    dev_array = np.asarray(devices).reshape(data, model, sequence)
    return Mesh(dev_array, axis_names=("data", "model", "sequence"))


@dataclass
class MeshContext:
    mesh: Mesh
    precision: str = "bf16-mixed"
    seed: int = 42
    _rng_key: Optional[jax.Array] = field(default=None, repr=False)
    _local_rng_key: Optional[jax.Array] = field(default=None, repr=False)
    _rng_buf: list = field(default_factory=list, repr=False)
    _local_rng_buf: list = field(default_factory=list, repr=False)
    _warned_replication: bool = field(default=False, repr=False)

    # -- topology -----------------------------------------------------------
    @property
    def world_size(self) -> int:
        return self.mesh.devices.size

    @property
    def data_parallel_size(self) -> int:
        return self.mesh.shape["data"]

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    @property
    def device(self) -> jax.Device:
        return self.mesh.devices.flat[0]

    # -- precision ----------------------------------------------------------
    @property
    def compute_dtype(self) -> jnp.dtype:
        if self.precision in ("bf16-mixed", "bf16-true", "bf16"):
            return jnp.bfloat16
        if self.precision in ("16-mixed", "fp16"):
            return jnp.float16
        return jnp.float32

    @property
    def param_dtype(self) -> jnp.dtype:
        # "-true" stores params in the low-precision dtype as well.
        if self.precision == "bf16-true":
            return jnp.bfloat16
        return jnp.float32

    # -- shardings ----------------------------------------------------------
    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self.sharding()

    def batch_sharding(self, batch_axis: int = 0) -> NamedSharding:
        """Shard the given axis over 'data', replicate the rest."""
        spec = [None] * batch_axis + ["data"]
        return self.sharding(*spec)

    def put_batch(self, tree: Any, batch_axis: int = 0) -> Any:
        """Host→device transfer with the batch axis sharded over ``data``.

        This is what makes every training loop actually data-parallel (the reference
        gets this implicitly from DDP's per-process batches).

        Single process: the whole per-rank batch is the global batch, sharded over
        the local data axis (replication fallback, with a once-per-run warning, when
        it doesn't divide — e.g. tiny dry-run batches on the 8-device CI mesh).

        Multi process: each rank's batch is its LOCAL CHUNK of the global batch
        (global = world × per-rank, exactly the reference's per-rank DDP batches);
        the global array is assembled with ``make_array_from_process_local_data`` —
        a plain ``device_put`` would require every process to pass identical data.
        The per-rank batch must divide the LOCAL device count; anything else raises
        (a silent per-process fallback would let replicas train on different
        "replicated" data and diverge).
        """
        dp = self.data_parallel_size
        sh = self.batch_sharding(batch_axis)
        rep = self.replicated

        if jax.process_count() > 1:
            if dp < jax.process_count():
                # With no data axis spanning the processes there is nothing to
                # shard the per-rank batches over: a "replicated" global array
                # built from different per-rank data would silently diverge the
                # replicas (JAX does not value-check process-local assembly).
                raise ValueError(
                    f"Multi-process runs need the data mesh axis to span the "
                    f"processes (data={dp} < processes={jax.process_count()}); "
                    f"lower mesh.model/mesh.sequence or add devices."
                )
            if dp % jax.process_count() != 0:
                raise ValueError(
                    f"The data mesh axis ({dp}) must divide evenly across the "
                    f"{jax.process_count()} processes for per-rank batch assembly."
                )
            local_dp = dp // jax.process_count()

            def _put(x):
                x = np.asarray(x)
                if x.ndim > batch_axis and x.shape[batch_axis] % local_dp == 0:
                    return jax.make_array_from_process_local_data(sh, x)
                raise ValueError(
                    f"Multi-process data parallelism needs the per-rank batch axis "
                    f"{batch_axis} (shape {x.shape}) to divide the {local_dp} local "
                    f"data-axis device(s); adjust per_rank_batch_size/num_envs."
                )

            return jax.tree.map(_put, tree)

        leaves = jax.tree.leaves(tree)
        all_divisible = all(
            getattr(x, "ndim", 0) > batch_axis and x.shape[batch_axis] % dp == 0 for x in leaves
        )
        if dp <= 1 or all_divisible:
            # ONE pytree device_put — per-leaf dispatches would each pay the
            # round-trip overhead on remote accelerators.
            return jax.device_put(tree, sh if dp > 1 else rep)

        def _put(x):
            divisible = x.ndim > batch_axis and x.shape[batch_axis] % dp == 0
            if not divisible:
                self.warn_replication_fallback(
                    f"batch axis {batch_axis} of shape {getattr(x, 'shape', '?')}"
                )
            return jax.device_put(x, sh if divisible else rep)

        return jax.tree.map(_put, tree)

    def warn_replication_fallback(self, what: str) -> None:
        """Emit the 1-chip-scaling warning at most once per context."""
        if self._warned_replication:
            return
        self._warned_replication = True
        import logging

        logging.getLogger(__name__).warning(
            "put_batch: %s does not divide the data mesh axis (data=%d); the batch is "
            "REPLICATED, so training scales like a single chip. Make the batch size a "
            "multiple of the data axis (or shrink mesh.data) to restore data-parallel "
            "scaling.",
            what,
            self.data_parallel_size,
        )

    def replicate(self, tree: Any) -> Any:
        return jax.device_put(tree, self.replicated)

    @property
    def model_parallel_size(self) -> int:
        return self.mesh.shape["model"]

    def shard_params(self, tree: Any, min_dim: int = 128) -> Any:
        """Tensor-parallel parameter placement over the ``model`` mesh axis.

        Every matrix leaf (``ndim >= 2``) whose output dimension divides the axis and
        is at least ``min_dim`` gets its LAST dim sharded over ``model``; everything
        else (biases, scales, small heads) is replicated.  GSPMD then propagates the
        sharding through the jitted train step: matmuls against a column-sharded kernel
        produce column-sharded activations, and the all-reduces land on ICI — no
        per-layer annotations in the model code (SURVEY §2.4's "free with GSPMD").
        With ``model=1`` (the default mesh) this is exactly ``replicate``.
        """
        mp = self.model_parallel_size
        if mp <= 1:
            return self.replicate(tree)

        def _put(x):
            if getattr(x, "ndim", 0) >= 2 and x.shape[-1] >= min_dim and x.shape[-1] % mp == 0:
                spec = [None] * (x.ndim - 1) + ["model"]
                return jax.device_put(x, self.sharding(*spec))
            return jax.device_put(x, self.replicated)

        return jax.tree.map(_put, tree)

    # -- rng ----------------------------------------------------------------
    # Keys are drawn in batches of _RNG_BATCH: jax.random.split is an eager device
    # op, and on a remote accelerator one dispatch per key would cost a round trip
    # per training-loop iteration.  Amortised, the chain stays deterministic:
    # refill r of a chain yields keys split(chain_r)[1:], chain_{r+1}=split(chain_r)[0].
    _RNG_BATCH = 64

    def _draw(self, chain_attr: str, buf_attr: str, seed_fn) -> jax.Array:
        buf = getattr(self, buf_attr)
        if not buf:
            chain = getattr(self, chain_attr)
            if chain is None:
                chain = seed_fn()
            keys = jax.random.split(chain, self._RNG_BATCH + 1)
            setattr(self, chain_attr, keys[0])
            buf = [keys[i] for i in range(self._RNG_BATCH, 0, -1)]  # pop() keeps order
        sub = buf.pop()
        setattr(self, buf_attr, buf)
        return sub

    def rng(self) -> jax.Array:
        """Draw a fresh key off the PROCESS-IDENTICAL chain (seeded with ``seed``
        alone).  Use for parameter initialisation and jitted train-step keys: with
        replicated params, every process must feed the SPMD program the same
        replicated inputs, or the replicas diverge (and ``device_put`` with a
        replicated sharding asserts on the mismatch)."""
        return self._draw("_rng_key", "_rng_buf", lambda: jax.random.PRNGKey(self.seed))

    def local_rng(self) -> jax.Array:
        """Draw a fresh key off the PER-PROCESS chain (``seed + process_index``).
        Use for env-side action sampling and anything that should explore
        differently on each rank (the analogue of the reference's per-rank torch
        seeding)."""
        # fold_in decorrelates this chain from the shared one even on process 0
        # (a bare ``seed + process_index`` would alias the shared chain there).
        return self._draw(
            "_local_rng_key",
            "_local_rng_buf",
            lambda: jax.random.fold_in(jax.random.PRNGKey(self.seed), 0x5EED + jax.process_index()),
        )

    # -- host-object exchange (reference: TorchCollective over gloo) --------
    def broadcast_obj(self, obj: Any) -> Any:
        if jax.process_count() == 1:
            return obj
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(obj)

    def barrier(self) -> None:
        sync_global_devices_with_timeout("sheeprl_tpu_barrier")

    @contextlib.contextmanager
    def default_mesh(self):
        # Mesh is itself a context manager (the ambient mesh for shard_map/pjit).
        with self.mesh:
            yield


def make_mesh_context(cfg: Dict[str, Any]) -> MeshContext:
    """Build the MeshContext from the ``mesh`` config group (analogue of the reference's
    ``fabric`` group, ``configs/fabric/default.yaml``)."""
    mesh_cfg = cfg.get("mesh", {}) or {}
    n_devices = mesh_cfg.get("devices")
    devices = jax.devices()
    if n_devices not in (None, -1, "auto"):
        devices = devices[: int(n_devices)]
    mesh = build_mesh(
        data=mesh_cfg.get("data", -1),
        model=mesh_cfg.get("model", 1),
        sequence=mesh_cfg.get("sequence", 1),
        devices=devices,
    )
    return MeshContext(mesh=mesh, precision=mesh_cfg.get("precision", "bf16-mixed"), seed=cfg.get("seed", 42))
