"""jaxlint engine: the AST tier's suppressions and file walker.

The rules themselves live in :mod:`sheeprl_tpu.analysis.rules`; the machinery
shared with the IR tier (:class:`~sheeprl_tpu.analysis.core.Finding`, baseline
load/write/filter) lives in :mod:`sheeprl_tpu.analysis.core` and is re-exported
here for backwards compatibility.  This module owns what is AST-specific:

* suppression comments — ``# jaxlint: disable=JL001`` (or ``disable=JL001,JL004`` /
  ``disable=all``) on the offending line, or on a standalone comment line directly
  above it;
* :func:`run_lint` — parse every ``.py`` file under the given paths, run the file
  rules per module and the project rules (config drift) once over the whole set.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

from sheeprl_tpu.analysis.core import (  # noqa: F401  (re-exported API)
    BASELINE_HEADER,
    Finding,
    filter_baseline,
    load_baseline,
    write_baseline,
)

_SUPPRESS_MARKER = "jaxlint:"


@dataclass
class Module:
    """A parsed source file handed to the rules."""

    path: str  # repo-relative
    abspath: Path
    source: str
    tree: ast.AST
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    def suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line, ())
        return finding.rule in rules or "all" in rules


class Rule:
    """Base class.  ``scope`` is ``"file"`` (checked per module) or ``"project"``
    (checked once with every module, e.g. config drift)."""

    id: str = "JL000"
    name: str = ""
    scope: str = "file"

    def check_module(self, module: Module) -> List[Finding]:  # file-scope rules
        return []

    def check_project(self, modules: Sequence[Module], config_dir: Optional[Path]) -> List[Finding]:
        return []


# --------------------------------------------------------------------- suppressions
def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> set of suppressed rule ids (or ``{"all"}``).

    A trailing comment suppresses its own line; a comment-only line suppresses the
    next line that contains code.
    """
    out: Dict[int, Set[str]] = {}
    lines = source.splitlines()

    def code_on_line(lineno: int) -> bool:
        text = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        before_comment = text.split("#", 1)[0]
        return bool(before_comment.strip())

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        body = tok.string.lstrip("#").strip()
        if not body.startswith(_SUPPRESS_MARKER):
            continue
        directive = body[len(_SUPPRESS_MARKER) :].strip()
        if not directive.startswith("disable"):
            continue
        _, _, spec = directive.partition("=")
        rules = set()
        for token in spec.split(","):
            token = token.strip().split()[0] if token.strip() else ""  # tolerate trailing prose
            if token:
                rules.add("all" if token == "all" else token.upper())
        if not rules:
            continue
        lineno = tok.start[0]
        if code_on_line(lineno):
            target = lineno
        else:  # standalone comment: applies to the next line holding code
            target = lineno + 1
            while target <= len(lines) and not code_on_line(target):
                target += 1
        out.setdefault(target, set()).update(rules)
    return out


# -------------------------------------------------------------------------- walker
def _iter_py_files(paths: Sequence[os.PathLike]) -> Iterable[Path]:
    for entry in paths:
        p = Path(entry)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(p.rglob("*.py"))


def _relpath(p: Path, root: Path) -> str:
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def load_modules(paths: Sequence[os.PathLike], root: Optional[os.PathLike] = None) -> List[Module]:
    root_path = Path(root) if root is not None else Path.cwd()
    modules: List[Module] = []
    for p in _iter_py_files(paths):
        try:
            source = p.read_text()
            tree = ast.parse(source, filename=str(p))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # unparseable files are not lintable; leave them to the test suite
        modules.append(
            Module(
                path=_relpath(p, root_path),
                abspath=p,
                source=source,
                tree=tree,
                suppressions=parse_suppressions(source),
            )
        )
    return modules


def run_lint(
    paths: Sequence[os.PathLike],
    rules: Optional[Sequence[Rule]] = None,
    config_dir: Optional[os.PathLike] = None,
    baseline: Optional[Set[str]] = None,
    root: Optional[os.PathLike] = None,
) -> List[Finding]:
    """Lint ``paths`` and return findings (suppressions and baseline already applied)."""
    if rules is None:
        from sheeprl_tpu.analysis.rules import default_rules

        rules = default_rules()
    modules = load_modules(paths, root=root)
    by_path = {m.path: m for m in modules}
    findings: List[Finding] = []
    for rule in rules:
        if rule.scope == "file":
            for module in modules:
                findings.extend(rule.check_module(module))
        else:
            findings.extend(rule.check_project(modules, Path(config_dir) if config_dir else None))
    findings = [f for f in findings if not (f.path in by_path and by_path[f.path].suppressed(f))]
    if baseline:
        findings = filter_baseline(findings, baseline)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
