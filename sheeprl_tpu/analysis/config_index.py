"""Merged-YAML-tree index for the JL006 config-drift rule.

Mirrors the composition semantics of :mod:`sheeprl_tpu.config.core` *unionally*: every
group file merges its keys under the group's mount key (last path component of its
directory), ``exp/`` and ``_global_: true`` files merge at the root, and the root
``config.yaml`` merges at the root.  The union over all options per group (rather than
any single composition) is the right "defined" set for drift checks: a key is only
*undefined* if **no** selectable option defines it.

Also records every ``${a.b.c}`` interpolation in the YAML text as an *access*, so
config keys consumed only by other config values don't show up as dead.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Set, Tuple

import yaml

PathTuple = Tuple[str, ...]

_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


@dataclass
class ConfigIndex:
    #: every defined dotted path -> (yaml file relpath, line) of its first definition
    defined: Dict[PathTuple, Tuple[str, int]] = field(default_factory=dict)
    #: paths referenced by ${...} interpolation inside the YAML tree
    interp_accessed: Set[PathTuple] = field(default_factory=set)
    #: mount keys of the config groups (algo, env, ...)
    groups: Set[str] = field(default_factory=set)

    def is_defined(self, path: PathTuple) -> bool:
        return path in self.defined

    def longest_defined_prefix(self, path: PathTuple) -> PathTuple:
        for i in range(len(path), 0, -1):
            if path[:i] in self.defined:
                return path[:i]
        return ()


def _collect_paths(node: yaml.Node, prefix: PathTuple, out: Dict[PathTuple, int]) -> None:
    if not isinstance(node, yaml.MappingNode):
        return
    for key_node, value_node in node.value:
        if not isinstance(key_node, yaml.ScalarNode):
            continue
        key = str(key_node.value)
        path = prefix + (key,)
        out.setdefault(path, key_node.start_mark.line + 1)
        _collect_paths(value_node, path, out)


def build_config_index(config_dir: Path, root: Path | None = None) -> ConfigIndex:
    index = ConfigIndex()
    config_dir = Path(config_dir)
    rel_root = root or config_dir
    for yaml_path in sorted(config_dir.rglob("*.yaml")):
        rel_dir = yaml_path.parent.relative_to(config_dir).as_posix()
        group = "" if rel_dir == "." else rel_dir
        text = yaml_path.read_text()
        try:
            node = yaml.compose(text, Loader=yaml.SafeLoader)
        except yaml.YAMLError:
            continue
        try:
            relpath = yaml_path.resolve().relative_to(Path(rel_root).resolve()).as_posix()
        except ValueError:
            relpath = yaml_path.as_posix()

        paths: Dict[PathTuple, int] = {}
        if node is not None:
            _collect_paths(node, (), paths)

        raw_global = False
        if ("_global_",) in paths:
            # honour the file's actual value, not mere key presence
            try:
                raw_global = bool((yaml.safe_load(text) or {}).get("_global_", False))
            except yaml.YAMLError:
                raw_global = False
        is_global = group.split("/")[0] == "exp" or raw_global

        mount: PathTuple = ()
        if group and not is_global:
            mount = (group.split("/")[-1],)
            index.groups.add(mount[0])

        for path, line in paths.items():
            if path[0] in ("defaults", "_global_"):
                continue
            index.defined.setdefault(mount + path, (relpath, line))
        if mount:
            index.defined.setdefault(mount, (relpath, 1))

        for m in _INTERP_RE.finditer(text):
            target = m.group(1).strip()
            if target.startswith(("oc.env:", "env:")):
                continue
            index.interp_accessed.add(tuple(target.split(".")))
    return index
