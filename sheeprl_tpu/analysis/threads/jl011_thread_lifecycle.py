"""JL011: thread lifecycle hazards.

Three checks over every ``threading.Thread(...)`` construction site and its
target body:

* **never-joined** — a non-daemon thread (no ``daemon=True``) whose binding is
  never ``.join()``-ed anywhere in the module: interpreter shutdown blocks on
  it, and nothing observes its death.  Daemon threads are exempt by design.
* **start-before-init** — ``__init__`` starts a thread at statement *i* whose
  target body reads ``self`` attributes only assigned after statement *i*: the
  thread can observe a half-constructed object.
* **unstoppable-daemon-loop** — a thread target whose body is ``while True:``
  with no ``break``/``return``/``raise`` inside and no stop-``Event`` consulted
  in the loop test: the thread can only die with the process, so shutdown
  paths (and tests) cannot reclaim it deterministically.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.engine import Module, Rule
from sheeprl_tpu.analysis.threads.common import (
    ScopeModel,
    ThreadCreation,
    build_scope_models,
    reads_of_self,
)


def _has_join(tree: ast.AST, binding: Optional[str]) -> bool:
    """True when ``<binding>.join(...)`` (or any ``.join`` on an unknown
    binding) appears anywhere in the module — deliberately loose: joins through
    a collection (``for t in threads: t.join()``) count."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr != "join":
            continue
        if binding is None:
            return True
        recv = node.func.value
        if binding.startswith("self."):
            attr = binding[len("self.") :]
            if (
                isinstance(recv, ast.Attribute)
                and recv.attr == attr
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
            ):
                return True
        elif isinstance(recv, ast.Name):
            # local bindings are commonly renamed/aggregated; any Name.join matches
            return True
    return False


def _loop_exits(loop: ast.While) -> bool:
    """Does this loop body contain any way out (break/return/raise), ignoring
    nested loops' own breaks?  Nested defs don't count."""
    stack: List[ast.AST] = list(loop.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, (ast.Return, ast.Raise)):
            return True
        if isinstance(node, ast.Break):
            return True
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            # a break inside a nested loop exits only that loop; but a
            # return/raise still exits — recurse without Break counting
            if _inner_returns(node):
                return True
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _inner_returns(loop: ast.AST) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, (ast.Return, ast.Raise)):
            return True
    return False


def _is_while_true(loop: ast.While) -> bool:
    return isinstance(loop.test, ast.Constant) and bool(loop.test.value) is True


class ThreadLifecycle(Rule):
    id = "JL011"
    name = "thread-lifecycle"
    scope = "file"

    def check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        models, _ = build_scope_models(module.tree)
        for scope in models:
            for creation in scope.thread_creations:
                findings.extend(self._check_creation(module, scope, creation))
            for target, creation in sorted(scope.thread_targets.items()):
                findings.extend(self._check_loop(module, scope, target))
            findings.extend(self._check_init_order(module, scope))
        return findings

    # ------------------------------------------------------------ never-joined
    def _check_creation(self, module: Module, scope: ScopeModel, creation: ThreadCreation) -> List[Finding]:
        if creation.daemon is True:
            return []
        if _has_join(module.tree, creation.binding):
            return []
        who = creation.binding or creation.target or "<unbound>"
        return [
            Finding(
                rule=self.id,
                path=module.path,
                line=creation.call.lineno,
                col=creation.call.col_offset,
                message=f"non-daemon thread {who} is never joined (and not daemon=True)",
                detail=f"{scope.name}:never-joined:{who}",
            )
        ]

    # --------------------------------------------------------- daemon-loop-stop
    def _check_loop(self, module: Module, scope: ScopeModel, target: str) -> List[Finding]:
        info = scope.funcs.get(target)
        if info is None:
            return []
        findings: List[Finding] = []
        for stmt in ast.walk(info.node):
            if not isinstance(stmt, ast.While) or not _is_while_true(stmt):
                continue
            if _loop_exits(stmt):
                continue
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=stmt.lineno,
                    col=stmt.col_offset,
                    message=(
                        f"thread body {target}() loops forever with no stop Event, "
                        "break, or return — unreclaimable except by process exit"
                    ),
                    detail=f"{scope.name}:unstoppable-loop:{target}",
                )
            )
        return findings

    # -------------------------------------------------------- start-before-init
    def _check_init_order(self, module: Module, scope: ScopeModel) -> List[Finding]:
        if not scope.is_class():
            return []
        init = scope.funcs.get("__init__")
        if init is None:
            return []
        findings: List[Finding] = []
        # map statement order in __init__: starts and attr assignments
        stmts = list(ast.walk(init.node))
        start_lines = []  # (line, target)
        for node in stmts:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "start"
            ):
                recv = node.func.value
                target: Optional[str] = None
                if (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                ):
                    binding = f"self.{recv.attr}"
                    for c in scope.thread_creations:
                        if c.binding == binding:
                            target = c.target
                elif isinstance(recv, ast.Name):
                    for c in scope.thread_creations:
                        if c.binding == recv.id and c.func_name == "__init__":
                            target = c.target
                if target:
                    start_lines.append((node.lineno, target))
        if not start_lines:
            return []
        assigns = {}  # attr -> first assignment line in __init__
        for node in stmts:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        assigns.setdefault(tgt.attr, node.lineno)
        for line, target in start_lines:
            info = scope.funcs.get(target)
            if info is None:
                continue
            needed = reads_of_self(info.node)
            late = sorted(a for a in needed if assigns.get(a, 0) > line)
            if late:
                findings.append(
                    Finding(
                        rule=self.id,
                        path=module.path,
                        line=line,
                        col=0,
                        message=(
                            f"__init__ starts thread target {target}() before assigning "
                            f"attribute(s) it reads: {', '.join('self.' + a for a in late)}"
                        ),
                        detail=f"{scope.name}:start-before-init:{target}:{','.join(late)}",
                    )
                )
        return findings
