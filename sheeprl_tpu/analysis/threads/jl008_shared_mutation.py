"""JL008: unguarded shared mutation.

An instance attribute written both from a thread body (``Thread(target=...)``
method or anything it calls on ``self``) and from another method, with no lock
guarding *every* one of those writes, is a data race waiting for a scheduler
interleaving.  ``__init__`` writes are exempt — construction happens-before
``Thread.start()`` (start-order violations are JL011's job).

The guard test is canonical-lock intersection: each write site records the set
of locks held at the statement (``with self._lock:`` regions, ``Condition``
canonicalised to its backing lock, best-effort ``.acquire()`` pairs); the rule
fires when the intersection over all non-``__init__`` write sites is empty.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.engine import Module, Rule
from sheeprl_tpu.analysis.threads.common import (
    ScopeModel,
    build_scope_models,
    multi_instance_reachable,
    thread_reachable,
    walk_held,
)

_EXEMPT_METHODS = {"__init__", "__new__", "__enter__"}


def _attr_writes(stmt: ast.stmt) -> List[Tuple[str, bool]]:
    """``(self.X, is_read_modify_write)`` targets written by this statement."""
    targets: List[ast.AST] = []
    rmw = isinstance(stmt, ast.AugAssign)
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: List[Tuple[str, bool]] = []
    for tgt in targets:
        nodes = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
        for node in nodes:
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                out.append((node.attr, rmw))
    return out


class UnguardedSharedMutation(Rule):
    id = "JL008"
    name = "unguarded-shared-mutation"
    scope = "file"

    def check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        models, _ = build_scope_models(module.tree)
        for scope in models:
            if scope.is_class() and scope.thread_targets:
                findings.extend(self._check_class(module, scope))
        return findings

    def _check_class(self, module: Module, scope: ScopeModel) -> List[Finding]:
        reachable = thread_reachable(scope)
        if not reachable:
            return []
        multi = multi_instance_reachable(scope)
        # attr -> list of (method, guard-set, line, is_read_modify_write)
        writes: Dict[str, List[Tuple[str, Set[str], int, bool]]] = {}
        for name, info in scope.funcs.items():
            if name in _EXEMPT_METHODS:
                continue

            def visit(stmt: ast.stmt, held, _name=name) -> None:
                guards = {h.name for h in held}
                for attr, rmw in _attr_writes(stmt):
                    if attr in scope.prims:
                        continue  # rebinding a primitive is lifecycle, not data
                    writes.setdefault(attr, []).append((_name, guards, stmt.lineno, rmw))

            walk_held(scope, info.node, visit)

        findings: List[Finding] = []
        for attr, sites in sorted(writes.items()):
            methods = {m for m, _, _, _ in sites}
            line = min(ln for _, _, ln, _ in sites)
            common = set.intersection(*(g for _, g, _, _ in sites))
            if (methods & reachable) and len(methods) >= 2 and not common:
                thread_side = sorted(methods & reachable)
                other_side = sorted(methods - reachable) or thread_side
                findings.append(
                    Finding(
                        rule=self.id,
                        path=module.path,
                        line=line,
                        col=0,
                        message=(
                            f"self.{attr} written from thread body {thread_side[0]}() and from "
                            f"{other_side[0]}() with no common lock held"
                        ),
                        detail=f"{scope.name}.{attr}:writers={','.join(sorted(methods))}",
                    )
                )
                continue
            # Same-method races: a read-modify-write (+=) in a method that runs
            # on one thread PER connection/worker races against its own copies.
            rmw_unguarded = [
                (m, ln) for m, g, ln, rmw in sites if rmw and not g and m in multi
            ]
            if rmw_unguarded:
                m, ln = min(rmw_unguarded, key=lambda t: t[1])
                findings.append(
                    Finding(
                        rule=self.id,
                        path=module.path,
                        line=ln,
                        col=0,
                        message=(
                            f"self.{attr} += ... in {m}(), which runs on one thread per "
                            "connection/worker — unguarded read-modify-write loses updates"
                        ),
                        detail=f"{scope.name}.{attr}:rmw:{m}",
                    )
                )
        return findings
