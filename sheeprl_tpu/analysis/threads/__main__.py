"""``python -m sheeprl_tpu.analysis.threads [paths...]`` — the jaxlint-threads CLI.

Exit status: 0 when no findings survive the baseline, 1 otherwise, 2 on usage
errors — same contract as jaxlint/jaxlint-ir.

    python -m sheeprl_tpu.analysis.threads sheeprl_tpu/        # vs threads.baseline
    python -m sheeprl_tpu.analysis.threads --no-baseline src/  # everything
    python -m sheeprl_tpu.analysis.threads --write-baseline sheeprl_tpu/
    python -m sheeprl_tpu.analysis.threads --select JL009 sheeprl_tpu/
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from sheeprl_tpu.analysis.engine import load_baseline, run_lint, write_baseline
from sheeprl_tpu.analysis.threads import default_thread_rules

DEFAULT_BASELINE = "threads.baseline"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sheeprl_tpu.analysis.threads",
        description="jaxlint-threads: concurrency static analysis (rules JL008-JL012) for sheeprl-tpu.",
    )
    parser.add_argument("paths", nargs="*", default=["sheeprl_tpu"], help="files or directories to lint")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE, help="baseline file of accepted fingerprints")
    parser.add_argument("--no-baseline", action="store_true", help="ignore the baseline entirely")
    parser.add_argument(
        "--write-baseline", action="store_true", help="write all current findings to the baseline and exit 0"
    )
    parser.add_argument("--select", default=None, help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--root", default=".", help="directory paths are reported relative to")
    parser.add_argument("-q", "--quiet", action="store_true", help="suppress the summary line")
    args = parser.parse_args(argv)

    try:
        rules = default_thread_rules(args.select.split(",")) if args.select else default_thread_rules()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline = None if (args.no_baseline or args.write_baseline) else load_baseline(args.baseline)
    findings = run_lint(args.paths, rules=rules, baseline=baseline, root=args.root)

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        if not args.quiet:
            print(f"jaxlint-threads: wrote {len(findings)} fingerprint(s) to {args.baseline}")
        return 0

    for f in findings:
        print(f.render())
    if not args.quiet:
        n_base = len(baseline) if baseline else 0
        status = "ok" if not findings else f"{len(findings)} finding(s)"
        print(f"jaxlint-threads: {status} ({n_base} baselined)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
