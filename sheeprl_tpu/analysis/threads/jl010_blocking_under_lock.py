"""JL010: blocking call under a held lock.

A lock held across a call that can park the thread — socket/channel I/O,
``jax.device_get`` / ``.block_until_ready()`` host syncs, blocking
``queue.get/put``, subprocess waits, ``time.sleep``, ``Event.wait`` — turns
every other thread contending for that lock into a convoy (and, for locks the
hot path takes, stalls the learner).  The fix is almost always to snapshot
state under the lock and do the slow call outside it.

Receiver-sensitive matching keeps this precise:

* ``.get``/``.put`` only fire on receivers inferred to be queues (``self.q =
  queue.Queue()`` / local equivalents), never on dicts, and never for the
  ``_nowait`` variants or ``block=False``;
* ``.wait`` only fires on inferred ``Event``/``Condition`` receivers — and a
  ``Condition.wait`` is exempt when the only lock held is the condition's own
  backing lock (that is how conditions work; JL012 polices the predicate loop);
* bare attribute names (``send``/``recv``/``accept``/``connect``/``sendall``)
  match any receiver — in this codebase those are sockets and framed channels.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.engine import Module, Rule
from sheeprl_tpu.analysis.rules.common import call_qualname
from sheeprl_tpu.analysis.threads.common import (
    ScopeModel,
    build_scope_models,
    canonical_lock,
    stmt_own_calls,
    walk_held,
)

_SOCKET_ATTRS = {"send", "sendall", "recv", "recvfrom", "recv_into", "accept", "connect"}
_BLOCKING_QUALNAMES = {
    "time.sleep",
    "jax.device_get",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
}
_BLOCKING_ATTRS = {"block_until_ready", "communicate"}


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_nonblocking_queue_call(call: ast.Call) -> bool:
    blk = _kw(call, "block")
    if isinstance(blk, ast.Constant) and blk.value is False:
        return True
    if call.args:
        first = call.args[0]
        # q.get(False) / q.put(item, False)
        idx = 0 if isinstance(call.func, ast.Attribute) and call.func.attr == "get" else 1
        if idx < len(call.args):
            arg = call.args[idx]
            if isinstance(arg, ast.Constant) and arg.value is False:
                return True
    return False


class BlockingCallUnderLock(Rule):
    id = "JL010"
    name = "blocking-call-under-lock"
    scope = "file"

    def check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        models, aliases = build_scope_models(module.tree)
        for scope in models:
            findings.extend(self._check_scope(module, scope, aliases))
        return findings

    def _check_scope(self, module: Module, scope: ScopeModel, aliases) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        for name, info in scope.funcs.items():

            def visit(stmt: ast.stmt, held, _name=name, _info=info) -> None:
                if not held:
                    return
                for call in stmt_own_calls(stmt):
                    desc = self._blocking_desc(scope, _info, call, held, aliases)
                    if desc is None:
                        continue
                    lock_names = ",".join(h.name for h in held)
                    detail = f"{scope.name}.{_name}:{desc}:under:{lock_names}"
                    if detail in seen:
                        continue
                    seen.add(detail)
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=module.path,
                            line=getattr(call, "lineno", stmt.lineno),
                            col=getattr(call, "col_offset", 0),
                            message=f"blocking call {desc} while holding {lock_names}",
                            detail=detail,
                        )
                    )

            walk_held(scope, info.node, visit)
        return findings

    def _blocking_desc(self, scope, info, call: ast.Call, held, aliases) -> Optional[str]:
        qn = call_qualname(call, aliases)
        if qn in _BLOCKING_QUALNAMES:
            return qn
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv_ref = canonical_lock(scope, info, func.value)
        if attr in ("get", "put"):
            if recv_ref is None or recv_ref.kind != "Queue":
                return None
            if _is_nonblocking_queue_call(call):
                return None
            return f"{recv_ref.name}.{attr}"
        if attr == "wait":
            if recv_ref is None:
                return None
            if recv_ref.kind == "Event":
                return f"{recv_ref.name}.wait"
            # Condition canonicalises to its backing mutex; holding only that
            # mutex is the documented wait protocol.
            if recv_ref.kind in ("Lock", "RLock", "Condition"):
                others = [h.name for h in held if h.name != recv_ref.name]
                if others:
                    return f"{recv_ref.name}.wait"
            return None
        if attr == "join":
            if recv_ref is not None and recv_ref.kind == "Thread":
                return f"{recv_ref.name}.join"
            return None
        if attr in _SOCKET_ATTRS:
            target = ast.unparse(func.value) if hasattr(ast, "unparse") else "?"
            return f"{target}.{attr}"
        if attr in _BLOCKING_ATTRS:
            return f".{attr}"
        return None
