"""JL012: ``Condition.wait()`` without a predicate re-check loop.

``Condition.wait`` can return spuriously, and between ``notify`` and wake-up
another thread may have consumed the state change — the documented protocol is

    with cond:
        while not predicate():
            cond.wait(timeout)

A ``cond.wait()`` that is not (lexically) inside a ``while`` loop acts on a
one-shot signal it has no right to trust.  ``Event.wait`` is exempt (events
latch); ``cond.wait_for(pred)`` is exempt (the loop is built in).  Any
enclosing ``while`` counts — ``while True: cond.wait(); if pred: break`` is a
predicate loop too.
"""

from __future__ import annotations

import ast
from typing import List

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.engine import Module, Rule
from sheeprl_tpu.analysis.threads.common import build_scope_models, canonical_lock


class ConditionWaitWithoutLoop(Rule):
    id = "JL012"
    name = "condition-wait-no-predicate-loop"
    scope = "file"

    def check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        models, _ = build_scope_models(module.tree)
        for scope in models:
            for name, info in scope.funcs.items():
                findings.extend(self._check_func(module, scope, name, info))
        return findings

    def _check_func(self, module, scope, name, info) -> List[Finding]:
        findings: List[Finding] = []

        def walk(node: ast.AST, in_while: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                child_in_while = in_while or isinstance(child, ast.While)
                if (
                    isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "wait"
                    and not in_while
                ):
                    ref = canonical_lock(scope, info, child.func.value)
                    if ref is not None and ref.kind in ("Condition", "Lock", "RLock"):
                        # Lock/RLock kinds appear when the Condition canonicalised
                        # to its backing mutex; the receiver is still a Condition.
                        recv = ast.unparse(child.func.value) if hasattr(ast, "unparse") else ref.name
                        findings.append(
                            Finding(
                                rule=self.id,
                                path=module.path,
                                line=child.lineno,
                                col=child.col_offset,
                                message=(
                                    f"{recv}.wait() outside a while predicate loop — "
                                    "spurious wake-ups and missed notifies go unchecked"
                                ),
                                detail=f"{scope.name}.{name}:{recv}.wait",
                            )
                        )
                walk(child, child_in_while)

        walk(info.node, False)
        return findings
