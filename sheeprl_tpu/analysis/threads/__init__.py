"""jaxlint-threads: the concurrency analysis tier.

Static rules (AST, same Finding/baseline/suppression contract as jaxlint):

| ID    | name                              | catches                                        |
|-------|-----------------------------------|------------------------------------------------|
| JL008 | unguarded-shared-mutation         | attr written from a thread body and another    |
|       |                                   | method with no common lock held                |
| JL009 | lock-order-inversion              | cycles in the static lock-acquisition graph    |
|       |                                   | (nested ``with`` + cross-method call edges)    |
| JL010 | blocking-call-under-lock          | socket/channel I/O, device_get /               |
|       |                                   | block_until_ready, blocking queue get/put,     |
|       |                                   | subprocess waits, sleep inside a held lock     |
| JL011 | thread-lifecycle                  | non-daemon thread never joined; start in       |
|       |                                   | __init__ before dependent attrs; unstoppable   |
|       |                                   | ``while True`` thread loop                     |
| JL012 | condition-wait-no-predicate-loop  | ``Condition.wait()`` not re-checked in a while |

The runtime half lives in :mod:`sheeprl_tpu.analysis.threads.runtime`: an
opt-in instrumented-lock layer (``analysis.race_detect=True`` /
``SHEEPRL_TPU_RACE_DETECT=1``) that observes the *dynamic* lock-order graph
and dumps a JSONL race report into ``<log_dir>/races/``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from sheeprl_tpu.analysis.engine import Rule
from sheeprl_tpu.analysis.threads.jl008_shared_mutation import UnguardedSharedMutation
from sheeprl_tpu.analysis.threads.jl009_lock_order import LockOrderInversion
from sheeprl_tpu.analysis.threads.jl010_blocking_under_lock import BlockingCallUnderLock
from sheeprl_tpu.analysis.threads.jl011_thread_lifecycle import ThreadLifecycle
from sheeprl_tpu.analysis.threads.jl012_condition_wait import ConditionWaitWithoutLoop

_RULE_CLASSES = [
    UnguardedSharedMutation,
    LockOrderInversion,
    BlockingCallUnderLock,
    ThreadLifecycle,
    ConditionWaitWithoutLoop,
]


def default_thread_rules(select: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the concurrency rule set, optionally restricted by id."""
    rules = [cls() for cls in _RULE_CLASSES]
    if select:
        wanted = {s.strip().upper() for s in select}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}; known: {[r.id for r in rules]}")
        rules = [r for r in rules if r.id in wanted]
    return rules


__all__ = [
    "default_thread_rules",
    "UnguardedSharedMutation",
    "LockOrderInversion",
    "BlockingCallUnderLock",
    "ThreadLifecycle",
    "ConditionWaitWithoutLoop",
]
