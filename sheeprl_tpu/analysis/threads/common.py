"""Shared concurrency model for the jaxlint-threads rules (JL008-JL012).

Everything here is inference over a single module's AST — no imports are
followed.  The rules share one picture of a module:

* which attributes / globals hold synchronisation primitives
  (``threading.Lock`` / ``RLock`` / ``Condition`` / ``Event``, ``queue.Queue``,
  ``threading.Thread``), including ``Condition(self._lock)`` aliasing back to
  its backing lock;
* which methods run on their own thread (``threading.Thread(target=self._x)``
  bodies, plus everything they call on ``self``, transitively);
* a statement walker that tracks the set of held locks through ``with`` blocks
  (``with a, b:`` acquires left-to-right) and bare ``.acquire()``/``.release()``
  pairs.

Locks are identified by *canonical names*: ``self.<attr>`` for instance
attributes (a ``Condition`` wrapping a lock canonicalises to that lock),
module-level names for globals, and ``<func>:<name>`` for function locals.
Canonical names are line-free, so they are stable inside baseline fingerprints.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.rules.common import call_qualname, collect_aliases

# Constructor qualnames -> primitive kind.
_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "multiprocessing.Lock": "Lock",
    "multiprocessing.RLock": "RLock",
}
_CONDITION_CTORS = {"threading.Condition", "multiprocessing.Condition"}
_EVENT_CTORS = {"threading.Event", "multiprocessing.Event"}
_QUEUE_CTORS = {
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "queue.SimpleQueue",
    "multiprocessing.Queue",
    "collections.deque",
}
_THREAD_CTORS = {"threading.Thread", "threading.Timer"}


@dataclass(frozen=True)
class LockRef:
    """A resolved reference to a synchronisation primitive."""

    name: str  # canonical, line-free (e.g. "self._lock", "_ACTIVE_LOCK", "f:a")
    kind: str  # "Lock" | "RLock" | "Condition" | "Event" | "Queue" | "Thread"


@dataclass
class FuncInfo:
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    local_prims: Dict[str, LockRef] = field(default_factory=dict)


@dataclass
class ScopeModel:
    """One lock namespace: a class (``self.*`` attrs + module globals visible)
    or the module itself (globals + top-level functions)."""

    name: str  # class name, or "<module>" for the pseudo-class of globals
    node: ast.AST
    module_aliases: Dict[str, str]
    prims: Dict[str, LockRef] = field(default_factory=dict)  # attr/global -> ref
    cond_backing: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    thread_targets: Dict[str, "ThreadCreation"] = field(default_factory=dict)
    thread_creations: List["ThreadCreation"] = field(default_factory=list)

    def is_class(self) -> bool:
        return isinstance(self.node, ast.ClassDef)


@dataclass
class ThreadCreation:
    """One ``threading.Thread(...)`` construction site."""

    call: ast.Call
    func_name: str  # enclosing function
    target: Optional[str]  # method/function name of target=..., when resolvable
    daemon: Optional[bool]  # True/False when a literal, None when unknown/absent
    binding: Optional[str]  # "self.X" / local var name the thread was bound to
    in_loop: bool = False  # construction site inside a for/while: multi-instance


def _func_defs(body: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt


def iter_own_calls(func: ast.AST) -> Iterator[ast.Call]:
    """Calls lexically inside ``func`` but not inside nested defs/lambdas."""
    stack: List[ast.AST] = list(func.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def stmt_own_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Calls in ``stmt``'s own expressions — not in nested statements (those are
    visited separately by :func:`walk_held`) and not in lambdas."""
    stack: List[ast.AST] = [
        child
        for child in ast.iter_child_nodes(stmt)
        if not isinstance(child, (ast.stmt, ast.ExceptHandler))
    ]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _ctor_kind(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    qn = call_qualname(call, aliases)
    if qn is None:
        return None
    if qn in _LOCK_CTORS:
        return _LOCK_CTORS[qn]
    if qn in _CONDITION_CTORS:
        return "Condition"
    if qn in _EVENT_CTORS:
        return "Event"
    if qn in _QUEUE_CTORS:
        return "Queue"
    if qn in _THREAD_CTORS:
        return "Thread"
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _literal_bool(node: Optional[ast.AST]) -> Optional[bool]:
    if isinstance(node, ast.Constant) and isinstance(node.value, bool):
        return node.value
    return None


def _thread_creation(
    call: ast.Call, func_name: str, binding: Optional[str], in_loop: bool = False
) -> ThreadCreation:
    target: Optional[str] = None
    daemon: Optional[bool] = None
    for kw in call.keywords:
        if kw.arg == "target":
            attr = _self_attr(kw.value)
            if attr is not None:
                target = attr
            elif isinstance(kw.value, ast.Name):
                target = kw.value.id
        elif kw.arg == "daemon":
            daemon = _literal_bool(kw.value)
    return ThreadCreation(
        call=call, func_name=func_name, target=target, daemon=daemon, binding=binding, in_loop=in_loop
    )


def _calls_under_loops(func: ast.AST) -> Set[ast.Call]:
    """Call nodes lexically inside a for/while anywhere in ``func``."""
    out: Set[ast.Call] = set()
    for node in ast.walk(func):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    out.add(sub)
    return out


def _scan_assignments(scope: ScopeModel, func: ast.AST, aliases: Dict[str, str], *, attr_owner: bool) -> None:
    """Record primitive bindings (``self.x = Lock()`` / ``x = Lock()``) and
    thread creations found in ``func``."""
    info = scope.funcs.setdefault(
        getattr(func, "name", "<lambda>"), FuncInfo(name=getattr(func, "name", "<lambda>"), node=func)
    )
    looped_calls = _calls_under_loops(func)
    for stmt in ast.walk(func):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt is not func:
            continue
        value: Optional[ast.AST] = None
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value, targets = stmt.value, [stmt.target]
        if not isinstance(value, ast.Call):
            continue
        kind = _ctor_kind(value, aliases)
        if kind is None:
            continue
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is not None and attr_owner:
                name = f"self.{attr}"
                scope.prims[attr] = LockRef(name=name, kind=kind)
                if kind == "Condition" and value.args:
                    backing = _self_attr(value.args[0])
                    if backing is not None:
                        scope.cond_backing[attr] = backing
            elif isinstance(tgt, ast.Name):
                info.local_prims[tgt.id] = LockRef(name=f"{info.name}:{tgt.id}", kind=kind)
        if kind == "Thread":
            binding = None
            for tgt in targets:
                attr = _self_attr(tgt)
                binding = f"self.{attr}" if attr is not None else (tgt.id if isinstance(tgt, ast.Name) else None)
            creation = _thread_creation(value, info.name, binding, in_loop=value in looped_calls)
            scope.thread_creations.append(creation)
            if creation.target:
                scope.thread_targets.setdefault(creation.target, creation)
    # Thread(...) used without being bound (e.g. Thread(...).start())
    for call in iter_own_calls(func):
        if _ctor_kind(call, aliases) == "Thread":
            already = any(c.call is call for c in scope.thread_creations)
            if not already:
                creation = _thread_creation(call, info.name, None, in_loop=call in looped_calls)
                scope.thread_creations.append(creation)
                if creation.target:
                    scope.thread_targets.setdefault(creation.target, creation)


def build_scope_models(tree: ast.AST) -> Tuple[List[ScopeModel], Dict[str, str]]:
    """Return (models, aliases): one ScopeModel per class plus one for the
    module's top-level functions/globals."""
    aliases = collect_aliases(tree)
    models: List[ScopeModel] = []

    module_scope = ScopeModel(name="<module>", node=tree, module_aliases=aliases)
    # Module-level primitive globals: X = threading.Lock() at top level.
    for stmt in tree.body:
        value: Optional[ast.AST] = None
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value, targets = stmt.value, [stmt.target]
        if isinstance(value, ast.Call):
            kind = _ctor_kind(value, aliases)
            if kind is not None:
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        module_scope.prims[tgt.id] = LockRef(name=tgt.id, kind=kind)
    for func in _func_defs(tree.body):
        _scan_assignments(module_scope, func, aliases, attr_owner=False)
    models.append(module_scope)

    for stmt in ast.walk(tree):
        if not isinstance(stmt, ast.ClassDef):
            continue
        scope = ScopeModel(name=stmt.name, node=stmt, module_aliases=aliases)
        # Inherit visibility of module globals so `with _ACTIVE_LOCK:` resolves
        # inside methods (shared dict reference is intentional).
        scope.prims.update(module_scope.prims)
        for func in _func_defs(stmt.body):
            _scan_assignments(scope, func, aliases, attr_owner=True)
        models.append(scope)
    return models, aliases


# ----------------------------------------------------------------- resolution
def canonical_lock(scope: ScopeModel, func: Optional[FuncInfo], expr: ast.AST) -> Optional[LockRef]:
    """Resolve an expression naming a lock-like primitive to its canonical ref.

    A ``Condition`` wrapping ``self._lock`` canonicalises to ``self._lock`` so
    guard/ordering analysis treats them as one mutex (kind stays "Condition"
    when unbacked, since it owns a private RLock)."""
    attr = _self_attr(expr)
    if attr is not None:
        ref = scope.prims.get(attr)
        if ref is None:
            return None
        if ref.kind == "Condition":
            backing = scope.cond_backing.get(attr)
            if backing is not None and backing in scope.prims:
                base = scope.prims[backing]
                return LockRef(name=f"self.{backing}", kind=base.kind)
        return ref
    if isinstance(expr, ast.Name):
        if func is not None and expr.id in func.local_prims:
            return func.local_prims[expr.id]
        return scope.prims.get(expr.id)
    return None


_MUTEX_KINDS = ("Lock", "RLock", "Condition")


def is_mutex(ref: Optional[LockRef]) -> bool:
    return ref is not None and ref.kind in _MUTEX_KINDS


# ------------------------------------------------------------- held-lock walk
def walk_held(
    scope: ScopeModel,
    func: ast.AST,
    visit: Callable[[ast.stmt, Tuple[LockRef, ...]], None],
    on_acquire: Optional[Callable[[LockRef, Tuple[LockRef, ...], ast.AST], None]] = None,
) -> None:
    """Walk ``func``'s statements in order, calling ``visit(stmt, held)`` with
    the tuple of locks held at that statement (outermost first) and
    ``on_acquire(lock, held_before, site)`` at each acquisition.

    Tracks ``with <lock>:`` (including multi-item ``with a, b:``) and
    best-effort ``<lock>.acquire()`` ... ``<lock>.release()`` straight-line
    pairs within one statement list.  Does not descend into nested defs."""
    info = scope.funcs.get(getattr(func, "name", ""), None)

    def resolve(expr: ast.AST) -> Optional[LockRef]:
        ref = canonical_lock(scope, info, expr)
        return ref if is_mutex(ref) else None

    def handle_block(stmts: Sequence[ast.stmt], held: Tuple[LockRef, ...]) -> None:
        acquired_here: List[LockRef] = []
        current = held
        for stmt in stmts:
            visit(stmt, current)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = current
                for item in stmt.items:
                    # `with lock:` / `with cond:` / `with a, b:` (left to right)
                    ref = resolve(item.context_expr)
                    if ref is not None:
                        if on_acquire is not None:
                            on_acquire(ref, inner, item.context_expr)
                        if all(h.name != ref.name for h in inner):
                            inner = inner + (ref,)
                handle_block(stmt.body, inner)
                continue
            if isinstance(stmt, (ast.If,)):
                handle_block(stmt.body, current)
                handle_block(stmt.orelse, current)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                handle_block(stmt.body, current)
                handle_block(stmt.orelse, current)
                continue
            if isinstance(stmt, ast.Try):
                handle_block(stmt.body, current)
                for handler in stmt.handlers:
                    handle_block(handler.body, current)
                handle_block(stmt.orelse, current)
                handle_block(stmt.finalbody, current)
                continue
            # Bare acquire()/release() calls as expression statements.
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute):
                    if call.func.attr == "acquire":
                        ref = resolve(call.func.value)
                        if ref is not None:
                            if on_acquire is not None:
                                on_acquire(ref, current, call)
                            if all(h.name != ref.name for h in current):
                                current = current + (ref,)
                                acquired_here.append(ref)
                    elif call.func.attr == "release":
                        ref = resolve(call.func.value)
                        if ref is not None:
                            current = tuple(h for h in current if h.name != ref.name)
                            acquired_here = [a for a in acquired_here if a.name != ref.name]

    handle_block(func.body, ())


def reads_of_self(func: ast.AST) -> Set[str]:
    """Attributes of ``self`` read (Load context) anywhere in ``func``."""
    out: Set[str] = set()
    for node in ast.walk(func):
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):  # type: ignore[attr-defined]
            out.add(attr)
    return out


def self_calls(func: ast.AST) -> Set[str]:
    """Names of methods invoked as ``self.m(...)`` inside ``func`` (own calls only)."""
    out: Set[str] = set()
    for call in iter_own_calls(func):
        attr = _self_attr(call.func)
        if attr is not None:
            out.add(attr)
    return out


def _closure_over_self_calls(scope: ScopeModel, seeds: Set[str]) -> Set[str]:
    reachable = set(s for s in seeds if s in scope.funcs)
    frontier = list(reachable)
    while frontier:
        name = frontier.pop()
        info = scope.funcs.get(name)
        if info is None:
            continue
        for callee in self_calls(info.node):
            if callee in scope.funcs and callee not in reachable:
                reachable.add(callee)
                frontier.append(callee)
    return reachable


def thread_reachable(scope: ScopeModel) -> Set[str]:
    """Method names that may execute on a spawned thread: declared
    ``Thread(target=...)`` bodies plus their transitive ``self.*()`` callees."""
    return _closure_over_self_calls(scope, set(scope.thread_targets))


def multi_instance_reachable(scope: ScopeModel) -> Set[str]:
    """Method names that may execute on SEVERAL threads at once: targets whose
    ``Thread(...)`` construction sits inside a loop (one thread per connection /
    per worker), plus their transitive ``self.*()`` callees."""
    seeds = {c.target for c in scope.thread_creations if c.in_loop and c.target}
    return _closure_over_self_calls(scope, seeds)
