"""Runtime lock-order race detector — the dynamic half of jaxlint-threads.

Opt-in (``analysis.race_detect=True`` config or ``SHEEPRL_TPU_RACE_DETECT=1``),
installed at the same boundary as the flight recorder: :func:`install` swaps
``threading.Lock`` / ``RLock`` / ``Condition`` for instrumented wrappers (and
shims ``time.sleep``), so every lock created afterwards reports to the active
:class:`RaceDetector`, which maintains:

* a per-thread held-lock stack (RLock re-entry counts, never double-pushes);
* the dynamic lock-order graph — acquiring B while holding A adds edge A→B;
  any cycle across the whole run is a potential deadlock (two threads took the
  same locks in opposite orders), reported even when the timing never actually
  deadlocked;
* held-longer-than-threshold sections (``race_hold_ms``) and blocking calls
  observed while holding a lock (``time.sleep``, ``Condition.wait`` with extra
  locks held) — the runtime mirror of JL010.

The report is JSONL under ``<log_dir>/races/`` (one object per line: summary,
then edges / cycles / long-holds / blocking events); headline counts also merge
into the flight recorder (``race_report`` event) and the fleet exporter
(``race_*`` gauges) when those planes are up.

Locks are named by construction site (``Lock#3@obs/fleet.py:481``) so reports
are stable across runs of the same build.  Everything here is stdlib-only and
single-purpose: the detector observes, it never changes blocking semantics.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.analysis.threads.jl009_lock_order import _cycles

ENV_VAR = "SHEEPRL_TPU_RACE_DETECT"

# Real factories, captured at import (before any install can patch them).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_SLEEP = time.sleep

#: Cap per-category event lists so a pathological run cannot OOM the detector.
_MAX_EVENTS = 256


def _caller_site(skip: int = 2) -> str:
    """``path:lineno`` of the first frame outside this module and threading/queue."""
    try:
        for frame in reversed(traceback.extract_stack(limit=12)[:-skip]):
            fn = frame.filename.replace("\\", "/")
            if fn.endswith(("analysis/threads/runtime.py",)) or "/threading.py" in fn or "/queue.py" in fn:
                continue
            parts = fn.split("/")
            return f"{'/'.join(parts[-2:])}:{frame.lineno}"
    except Exception:  # pragma: no cover - never let naming break a lock
        pass
    return "?:0"


class _InstrumentedLock:
    """Duck-typed Lock/RLock proxy; also implements the private protocol
    ``threading.Condition`` probes for (``_is_owned`` / ``_release_save`` /
    ``_acquire_restore``), keeping the detector's held-set exact across
    ``Condition.wait``."""

    __slots__ = ("_inner", "_det", "name", "kind")

    def __init__(self, inner: Any, det: "RaceDetector", name: str, kind: str):
        self._inner = inner
        self._det = det
        self.name = name
        self.kind = kind

    # ------------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._det._on_acquired(self, blocking=blocking)
        return got

    def release(self) -> None:
        self._det._on_release(self)
        self._inner.release()

    def __enter__(self) -> "_InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else self._is_owned()

    # -------------------------------------------- Condition interop protocol
    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):  # plain Lock probe, bypasses the detector
            inner.release()
            return False
        return True

    def _release_save(self) -> Any:
        self._det._on_release(self, full=True, waiting=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state: Any) -> None:
        inner = self._inner
        if state is not None and hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._det._on_acquired(self, blocking=True)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<instrumented {self.kind} {self.name}>"


class RaceDetector:
    """Collects the dynamic lock-order graph and JL010-style runtime events."""

    def __init__(self, log_dir: Optional[str] = None, held_threshold_ms: float = 200.0):
        self.log_dir = log_dir
        self.held_threshold_s = max(float(held_threshold_ms), 0.0) / 1000.0
        self._tls = threading.local()
        self._meta = _REAL_LOCK()  # raw: guards everything below
        self._seq = 0
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._long_holds: List[Dict[str, Any]] = []
        self._blocking: List[Dict[str, Any]] = []
        self._locks_created = 0
        self._acquisitions = 0

    # ------------------------------------------------------------- factories
    def make_lock(self) -> _InstrumentedLock:
        return self._wrap(_REAL_LOCK(), "Lock")

    def make_rlock(self) -> _InstrumentedLock:
        return self._wrap(_REAL_RLOCK(), "RLock")

    def make_condition(self, lock: Any = None) -> Any:
        if lock is None:
            lock = self.make_rlock()
        return _REAL_CONDITION(lock)

    def _wrap(self, inner: Any, kind: str) -> _InstrumentedLock:
        with self._meta:
            self._seq += 1
            self._locks_created += 1
            seq = self._seq
        name = f"{kind}#{seq}@{_caller_site()}"
        return _InstrumentedLock(inner, self, name, kind)

    # ---------------------------------------------------------- held tracking
    def _stack(self) -> List[Dict[str, Any]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def held_names(self) -> List[str]:
        return [e["lock"].name for e in self._stack()]

    def _on_acquired(self, lock: _InstrumentedLock, blocking: bool = True) -> None:
        stack = self._stack()
        for entry in stack:
            if entry["lock"] is lock:  # RLock re-entry: count, no new edge
                entry["count"] += 1
                return
        if blocking and stack:
            with self._meta:
                self._acquisitions += 1
                for held in stack:
                    key = (held["lock"].name, lock.name)
                    rec = self._edges.get(key)
                    if rec is None:
                        # stack walk only on a never-seen edge: the steady state
                        # is one dict hit + int bump per nested acquisition
                        self._edges[key] = {
                            "count": 1,
                            "thread": threading.current_thread().name,
                            "site": _caller_site(),
                        }
                    else:
                        rec["count"] += 1
        else:
            with self._meta:
                self._acquisitions += 1
        stack.append({"lock": lock, "t0": time.monotonic(), "count": 1})

    def _on_release(self, lock: _InstrumentedLock, full: bool = False, waiting: bool = False) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            entry = stack[i]
            if entry["lock"] is not lock:
                continue
            if not full and entry["count"] > 1:
                entry["count"] -= 1
                return
            held_s = time.monotonic() - entry["t0"]
            del stack[i]
            if held_s >= self.held_threshold_s > 0:
                self._record(
                    self._long_holds,
                    {
                        "lock": lock.name,
                        "held_ms": round(held_s * 1000.0, 3),
                        "thread": threading.current_thread().name,
                        "site": _caller_site(),
                    },
                )
            if waiting and stack:
                # Condition.wait while still holding OTHER locks: runtime JL010.
                self.note_blocking(f"{lock.name}.wait", kind="condition-wait-under-lock")
            return

    # ------------------------------------------------------------ observations
    def note_blocking(self, desc: str, kind: str = "blocking-under-lock") -> None:
        stack = self._stack()
        if not stack:
            return
        self._record(
            self._blocking,
            {
                "call": desc,
                "kind": kind,
                "held": [e["lock"].name for e in stack],
                "thread": threading.current_thread().name,
                "site": _caller_site(),
            },
        )

    def _record(self, bucket: List[Dict[str, Any]], item: Dict[str, Any]) -> None:
        with self._meta:
            if len(bucket) < _MAX_EVENTS:
                bucket.append(item)

    # ---------------------------------------------------------------- reports
    def cycles(self) -> List[List[str]]:
        with self._meta:
            graph: Dict[str, set] = {}
            for (a, b), _ in self._edges.items():
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        return _cycles(graph)

    def counts(self) -> Dict[str, int]:
        cycles = self.cycles()
        with self._meta:
            return {
                "locks_created": self._locks_created,
                "acquisitions": self._acquisitions,
                "edges": len(self._edges),
                "cycles": len(cycles),
                "long_holds": len(self._long_holds),
                "blocking_under_lock": len(self._blocking),
            }

    def report(self) -> Dict[str, Any]:
        cycles = self.cycles()
        with self._meta:
            edges = [
                {"from": a, "to": b, **rec} for (a, b), rec in sorted(self._edges.items())
            ]
            long_holds = list(self._long_holds)
            blocking = list(self._blocking)
        return {
            "counts": self.counts(),
            "cycles": cycles,
            "edges": edges,
            "long_holds": long_holds,
            "blocking": blocking,
        }

    def dump(self, reason: str = "report") -> Optional[str]:
        """Write the JSONL report into ``<log_dir>/races/`` and merge headline
        counts into whatever telemetry planes are active.  Never raises."""
        rep = self.report()
        path: Optional[str] = None
        try:
            races_dir = os.path.join(self.log_dir or ".", "races")
            os.makedirs(races_dir, exist_ok=True)
            path = os.path.join(races_dir, f"races_{os.getpid()}.jsonl")
            with open(path, "w") as f:
                f.write(json.dumps({"kind": "summary", "reason": reason, **rep["counts"]}) + "\n")
                for cyc in rep["cycles"]:
                    f.write(json.dumps({"kind": "cycle", "locks": cyc}) + "\n")
                for edge in rep["edges"]:
                    f.write(json.dumps({"kind": "edge", **edge}) + "\n")
                for item in rep["long_holds"]:
                    f.write(json.dumps({"kind": "long_hold", **item}) + "\n")
                for item in rep["blocking"]:
                    f.write(json.dumps({"kind": "blocking", **item}) + "\n")
        except OSError as e:  # pragma: no cover - disk full etc.
            print(f"race detector: could not write report: {e}", file=sys.stderr)
            path = None
        try:  # flight recorder + fleet merge (best effort, planes may be down)
            from sheeprl_tpu.obs import flight_recorder

            flight_recorder.record_event("race_report", reason=reason, **rep["counts"])
            from sheeprl_tpu.obs import fleet as obs_fleet

            exporter = obs_fleet.get_active()
            if exporter is not None:
                for key in ("cycles", "long_holds", "blocking_under_lock", "edges"):
                    exporter.gauge(f"race_{key}", float(rep["counts"][key]))
        except Exception:  # pragma: no cover - telemetry must never break the run
            pass
        return path


# ----------------------------------------------------------------- installing
_ACTIVE: Optional[RaceDetector] = None
_INSTALL_LOCK = _REAL_LOCK()


def get_active() -> Optional[RaceDetector]:
    return _ACTIVE


def install(detector: RaceDetector) -> Optional[RaceDetector]:
    """Patch the ``threading`` lock factories (and ``time.sleep``) so locks
    created from now on report to ``detector``.  Returns the previous one."""
    global _ACTIVE
    with _INSTALL_LOCK:
        prev = _ACTIVE
        _ACTIVE = detector

        def _lock() -> Any:
            det = _ACTIVE
            return det.make_lock() if det is not None else _REAL_LOCK()

        def _rlock() -> Any:
            det = _ACTIVE
            return det.make_rlock() if det is not None else _REAL_RLOCK()

        def _condition(lock: Any = None) -> Any:
            det = _ACTIVE
            return det.make_condition(lock) if det is not None else _REAL_CONDITION(lock)

        def _sleep(seconds: float) -> None:
            det = _ACTIVE
            if det is not None:
                det.note_blocking(f"time.sleep({seconds})")
            _REAL_SLEEP(seconds)

        threading.Lock = _lock  # type: ignore[assignment]
        threading.RLock = _rlock  # type: ignore[assignment]
        threading.Condition = _condition  # type: ignore[assignment]
        time.sleep = _sleep  # type: ignore[assignment]
    return prev


def uninstall() -> Optional[RaceDetector]:
    """Restore the real factories; already-created instrumented locks keep
    working (their inner locks are real), they just stop growing the graph
    once the detector is detached."""
    global _ACTIVE
    with _INSTALL_LOCK:
        prev = _ACTIVE
        _ACTIVE = None
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]
        time.sleep = _REAL_SLEEP  # type: ignore[assignment]
    return prev


def dump_active(reason: str = "report") -> Optional[str]:
    det = _ACTIVE
    return det.dump(reason) if det is not None else None


def enabled_by_env() -> bool:
    return os.environ.get(ENV_VAR, "0") not in ("", "0")


def maybe_install(cfg: Any = None, log_dir: Optional[str] = None) -> Optional[RaceDetector]:
    """Gate + install, mirroring the flight recorder boundary: env var wins,
    else ``analysis.race_detect`` in the run config.  Returns the detector (or
    ``None`` when disabled) — callers pair it with :func:`dump_active` +
    :func:`uninstall` in their shutdown path."""
    enabled = enabled_by_env()
    hold_ms = 200.0
    if cfg is not None:
        try:
            analysis_cfg = cfg.get("analysis", {}) or {}
            enabled = enabled or bool(analysis_cfg.get("race_detect", False))
            hold_ms = float(analysis_cfg.get("race_hold_ms", hold_ms))
        except Exception:
            pass
    if not enabled:
        return None
    detector = RaceDetector(log_dir=log_dir, held_threshold_ms=hold_ms)
    install(detector)
    return detector
