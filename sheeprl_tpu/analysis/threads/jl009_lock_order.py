"""JL009: lock-order inversion.

Builds a static lock-acquisition graph per lock namespace (class, or the
module's top-level functions): acquiring B while holding A adds edge A->B,
both from lexically nested ``with`` blocks (``with a: with b:`` and
``with a, b:``) and from cross-method call edges (``with a: self.m()`` where
``m`` transitively acquires ``b``).  Any cycle in that graph is a potential
deadlock between two threads taking the locks in opposite orders.

Re-acquiring the *same* ``RLock`` (or a ``Condition`` canonicalised to one)
is reentrant, not a cycle; a self-edge on a plain ``Lock`` is reported — that
is a single-thread self-deadlock.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.engine import Module, Rule
from sheeprl_tpu.analysis.threads.common import (
    LockRef,
    ScopeModel,
    build_scope_models,
    stmt_own_calls,
    walk_held,
)


class LockOrderInversion(Rule):
    id = "JL009"
    name = "lock-order-inversion"
    scope = "file"

    def check_module(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        models, _ = build_scope_models(module.tree)
        for scope in models:
            findings.extend(self._check_scope(module, scope))
        return findings

    def _check_scope(self, module: Module, scope: ScopeModel) -> List[Finding]:
        if not scope.funcs:
            return []
        kinds: Dict[str, str] = {}
        # direct acquisition edges + per-method summaries
        edges: Dict[Tuple[str, str], int] = {}  # (a, b) -> earliest line
        acquires: Dict[str, Set[str]] = {}  # method -> locks acquired anywhere in it
        calls_held: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}  # method -> (callee, held)

        for name, info in scope.funcs.items():
            acquired: Set[str] = set()
            calls: List[Tuple[str, Tuple[str, ...]]] = []

            def on_acquire(ref: LockRef, held, site) -> None:
                kinds[ref.name] = ref.kind
                acquired.add(ref.name)
                line = getattr(site, "lineno", 1)
                for h in held:
                    if h.name == ref.name and ref.kind == "RLock":
                        continue  # reentrant re-acquire, not an ordering edge
                    key = (h.name, ref.name)
                    edges[key] = min(edges.get(key, line), line)

            def visit(stmt, held) -> None:
                pass

            walk_held(scope, info.node, visit, on_acquire=on_acquire)
            # cross-method call sites with their held sets
            def visit_calls(stmt, held) -> None:
                if not held:
                    return
                for call in stmt_own_calls(stmt):
                    callee = _self_callee(call)
                    if callee is not None and callee in scope.funcs:
                        calls.append((callee, tuple(h.name for h in held)))

            walk_held(scope, info.node, visit_calls)
            acquires[name] = acquired
            calls_held[name] = calls

        # transitive closure of per-method acquisitions through self-calls
        trans: Dict[str, Set[str]] = {m: set(a) for m, a in acquires.items()}
        changed = True
        while changed:
            changed = False
            for m, calls in calls_held.items():
                for callee, _ in calls:
                    extra = trans.get(callee, set()) - trans[m]
                    if extra:
                        trans[m] |= extra
                        changed = True
        for m, calls in calls_held.items():
            for callee, held in calls:
                for b in trans.get(callee, ()):
                    for a in held:
                        if a == b and kinds.get(b) == "RLock":
                            continue
                        key = (a, b)
                        if key not in edges:
                            edges[key] = 1

        # cycle detection over the edge graph
        graph: Dict[str, Set[str]] = {}
        for (a, b), _ in edges.items():
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        findings: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for cycle in _cycles(graph):
            if len(cycle) == 1 and kinds.get(cycle[0]) == "RLock":
                continue  # reentrancy is legal
            key = tuple(sorted(cycle))
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            line = min(
                (edges[(a, b)] for a in cycle for b in cycle if (a, b) in edges),
                default=1,
            )
            desc = "<->".join(key) if len(key) > 1 else f"{key[0]} (self-deadlock)"
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=line,
                    col=0,
                    message=f"lock-order cycle in {scope.name}: {desc}",
                    detail=f"{scope.name}:{'|'.join(key)}",
                )
            )
        return findings


def _self_callee(call: ast.Call) -> Optional[str]:
    func = call.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
    ):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node, plus self-loops."""
    index: Dict[str, int] = {}
    lowlink: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = lowlink[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = lowlink[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    lowlink[node] = min(lowlink[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, ()):
                    out.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out
