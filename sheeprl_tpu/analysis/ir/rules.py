"""IR-level audit rules over the lowered jaxpr and compiled HLO.

| ID    | name                   | catches                                               |
|-------|------------------------|-------------------------------------------------------|
| IR001 | donation-not-applied   | ``donate_argnums`` buffers XLA did not alias (the     |
|       |                        | silent 2x-HBM bug class)                              |
| IR002 | dtype-promotion        | f64 anywhere; f32 dot/conv under a declared bf16/fp16 |
|       |                        | compute precision                                     |
| IR003 | callback-in-scan       | io_callback/debug.callback/pure_callback inside a     |
|       |                        | scan/while body without the obs/strict gate           |
| IR004 | collective-in-single-mesh | cross-device collectives (psum/all_gather/...) or  |
|       |                        | host transfers compiled into a single-mesh graph      |
| IR005 | oversize-constant      | constants above a size threshold folded into the      |
|       |                        | executable                                            |
| IR006 | budget-drift           | compile-memory budgets (arg+out+temp bytes) vs the    |
|       |                        | checked-in ``irbudgets.json`` baseline                |

Rules IR001-IR005 run on the artifacts of one AOT lowering; IR006 lives in
:mod:`sheeprl_tpu.analysis.ir.budgets` because it needs the checked-in baseline.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.ir.types import AuditEntry

#: primitives that execute host python from inside the compiled graph
CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback", "callback"}

#: cross-device collective primitives (jaxpr level; GSPMD-inserted collectives
#: only exist post-SPMD-partitioning, which a single-mesh graph never runs)
COLLECTIVE_PRIMS = {
    "psum",
    "pmax",
    "pmin",
    "pmean",
    "ppermute",
    "pshuffle",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "psum_scatter",
    "collective_permute",
    "pgather",
}

#: loop-carrying primitives whose bodies IR003 treats as the hot path
LOOP_PRIMS = {"scan", "while", "fori_loop"}


@dataclass
class LoweredArtifacts:
    """Everything one audit entry's AOT pipeline produced."""

    entry: AuditEntry
    jaxpr: Any  # ClosedJaxpr of the whole program
    lowered: Any  # jax.stages.Lowered
    compiled: Any  # jax.stages.Compiled
    memory: Optional[Any]  # CompiledMemoryStats or None (backend-dependent)

    @property
    def donated_bytes(self) -> int:
        return sum(_aval_bytes(a._aval) for a in _flat_args_info(self.lowered) if a.donated)

    @property
    def donated_count(self) -> int:
        return sum(1 for a in _flat_args_info(self.lowered) if a.donated)


def lower_entry(entry: AuditEntry) -> LoweredArtifacts:
    """AOT-lower and compile one entry; every IR rule runs off these artifacts."""
    traced = entry.fn.trace(*entry.args, **entry.kwargs)
    lowered = traced.lower()
    compiled = lowered.compile()
    try:
        memory = compiled.memory_analysis()
    except Exception:  # backend without memory stats: IR006 degrades gracefully
        memory = None
    return LoweredArtifacts(
        entry=entry, jaxpr=traced.jaxpr, lowered=lowered, compiled=compiled, memory=memory
    )


# ------------------------------------------------------------------ jaxpr walking
def _subjaxprs(eqn) -> Iterator[Tuple[Any, Optional[str]]]:
    """Yield ``(inner_jaxpr, loop_kind)`` for every subjaxpr in an eqn's params;
    ``loop_kind`` is the eqn's primitive name when the body re-executes (scan /
    while), else None."""
    kind = eqn.primitive.name if eqn.primitive.name in LOOP_PRIMS else None
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            if hasattr(v, "eqns"):  # open Jaxpr
                yield v, kind
            elif hasattr(v, "jaxpr"):  # ClosedJaxpr
                yield v.jaxpr, kind


def iter_eqns(jaxpr, _in_loop: bool = False) -> Iterator[Tuple[Any, bool]]:
    """Depth-first ``(eqn, inside_loop_body)`` over a (Closed)Jaxpr."""
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in inner.eqns:
        yield eqn, _in_loop
        for sub, kind in _subjaxprs(eqn):
            yield from iter_eqns(sub, _in_loop or kind is not None)


def iter_consts(jaxpr) -> Iterator[Any]:
    """Every constant captured by the program (top level and nested closed
    jaxprs) — these get folded into the executable."""
    closed = jaxpr if hasattr(jaxpr, "consts") else None
    if closed is not None:
        yield from closed.consts
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for eqn in inner.eqns:
        for val in eqn.params.values():
            vals = val if isinstance(val, (list, tuple)) else [val]
            for v in vals:
                if hasattr(v, "jaxpr"):  # ClosedJaxpr carries its own consts
                    yield from iter_consts(v)
                elif hasattr(v, "eqns"):
                    yield from iter_consts(v)


def _flat_args_info(lowered) -> List[Any]:
    import jax

    return jax.tree.leaves(lowered.args_info, is_leaf=lambda a: hasattr(a, "donated"))


def _aval_bytes(aval) -> int:
    import numpy as np

    try:
        return int(np.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:
        return 0


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


# ------------------------------------------------------------------------- rules
#: IR001 ignores shortfalls below this many bytes: dispatch programs legitimately
#: refresh a few scalar counters (e.g. the Anakin per-window episode sums) whose
#: donated 4-byte buffers XLA then cannot reuse — the bug class is the KB..GB
#: state (params, optimizer moments, replay rings) held twice, not loose scalars.
DONATION_SLACK_BYTES = 1024


def check_donation(art: LoweredArtifacts, slack_bytes: int = DONATION_SLACK_BYTES) -> List[Finding]:
    """IR001: every ``donate_argnums`` buffer must be aliased to an output by XLA.

    The aggregate check is byte-exact: ``memory_analysis().alias_size_in_bytes``
    counts only donation-established input/output aliases, so any shortfall vs
    the donated argument bytes (beyond ``slack_bytes``) means at least one donated
    buffer was NOT reused — the program silently holds both copies live (2x HBM
    on the donated state).  The compiled HLO's ``input_output_alias`` header
    refines the message with the aliased parameter count when it parses.
    """
    entry = art.entry
    donated = art.donated_bytes
    if donated == 0:
        return []
    aliased = int(getattr(art.memory, "alias_size_in_bytes", 0) or 0) if art.memory else None
    if aliased is None:
        return []  # no memory stats on this backend: nothing to compare against
    if aliased + slack_bytes >= donated:
        return []
    n_aliased = len(
        re.findall(r"\(\d+, \{[^}]*\}, (?:may|must)-alias\)", art.compiled.as_text()[:20000])
    )
    return [
        Finding(
            rule="IR001",
            path=entry.name,
            line=0,
            col=0,
            message=(
                f"donation not applied: {donated - aliased} B of {_fmt_bytes(donated)} "
                f"donated buffers were NOT aliased by XLA ({n_aliased} parameter(s) "
                f"aliased of {art.donated_count} donated) — the un-aliased donated "
                "state is held TWICE in device memory; check that donated inputs "
                "match an output's shape/dtype and are not read after the call"
            ),
            detail="donation-not-applied",
        )
    ]


def check_dtype_promotion(art: LoweredArtifacts) -> List[Finding]:
    """IR002: dtype promotion against the declared compute precision — f64
    anywhere (this repo never declares fp64), and dot/conv ops whose float
    operands are ALL f32 when the config declares bf16/fp16 compute (the
    promotion that silently doubles the FLOP cost on chip)."""
    import jax.numpy as jnp

    entry = art.entry
    low_precision = any(t in str(entry.precision).lower() for t in ("bf16", "fp16", "16-mixed"))
    findings: List[Finding] = []
    seen = set()
    f64 = jnp.dtype("float64")
    f32 = jnp.dtype("float32")
    for eqn, _ in iter_eqns(art.jaxpr):
        prim = eqn.primitive.name
        for v in list(eqn.outvars):
            dtype = getattr(getattr(v, "aval", None), "dtype", None)
            if dtype == f64 and ("f64", prim) not in seen:
                seen.add(("f64", prim))
                findings.append(
                    Finding(
                        rule="IR002",
                        path=entry.name,
                        line=0,
                        col=0,
                        message=f"float64 output of '{prim}' in a graph declared {entry.precision}",
                        detail=f"f64:{prim}",
                    )
                )
        if low_precision and prim in ("dot_general", "conv_general_dilated"):
            fdtypes = [
                getattr(getattr(v, "aval", None), "dtype", None)
                for v in eqn.invars
                if getattr(getattr(getattr(v, "aval", None), "dtype", None), "kind", "") == "f"
            ]
            if fdtypes and all(d == f32 for d in fdtypes) and ("f32", prim) not in seen:
                seen.add(("f32", prim))
                findings.append(
                    Finding(
                        rule="IR002",
                        path=entry.name,
                        line=0,
                        col=0,
                        message=(
                            f"'{prim}' computes entirely in float32 although the config "
                            f"declares {entry.precision} compute precision — the input "
                            "cast to the low-precision dtype never happened"
                        ),
                        detail=f"f32:{prim}",
                    )
                )
    return findings


def check_callbacks(art: LoweredArtifacts) -> List[Finding]:
    """IR003: host callbacks inside scan/while bodies.  A callback in the hot
    loop synchronizes device->host EVERY iteration; only the obs/strict flags may
    put one there, and the audit build keeps those off (``callbacks_gated``
    declares an intentional exception)."""
    entry = art.entry
    if entry.callbacks_gated:
        return []
    findings: List[Finding] = []
    seen = set()
    for eqn, in_loop in iter_eqns(art.jaxpr):
        prim = eqn.primitive.name
        if in_loop and prim in CALLBACK_PRIMS and prim not in seen:
            seen.add(prim)
            findings.append(
                Finding(
                    rule="IR003",
                    path=entry.name,
                    line=0,
                    col=0,
                    message=(
                        f"host callback '{prim}' compiled inside a scan/while body "
                        "without the obs/health/strict gate: it synchronizes with the "
                        "host on every loop iteration"
                    ),
                    detail=f"callback:{prim}",
                )
            )
    return findings


def check_collectives(art: LoweredArtifacts) -> List[Finding]:
    """IR004: cross-device collectives (or explicit host transfers) in a graph
    built for a single mesh: nothing to communicate with, so the op is either
    dead weight or a latent multi-chip semantics bug."""
    entry = art.entry
    if not entry.single_mesh:
        return []
    findings: List[Finding] = []
    seen = set()
    for eqn, _ in iter_eqns(art.jaxpr):
        prim = eqn.primitive.name
        if prim in seen:
            continue
        # shard_map lowers psum to the rewrite-capable "psum2" spelling
        if prim in COLLECTIVE_PRIMS or prim.rstrip("2") in COLLECTIVE_PRIMS:
            seen.add(prim)
            findings.append(
                Finding(
                    rule="IR004",
                    path=entry.name,
                    line=0,
                    col=0,
                    message=f"cross-device collective '{prim}' in a single-mesh graph",
                    detail=f"collective:{prim}",
                )
            )
        elif prim == "device_put":
            kinds = [str(d) for d in eqn.params.get("devices", [])]
            if any("host" in k.lower() for k in kinds):
                seen.add(prim)
                findings.append(
                    Finding(
                        rule="IR004",
                        path=entry.name,
                        line=0,
                        col=0,
                        message="device-to-host transfer compiled into the graph",
                        detail="d2h:device_put",
                    )
                )
    return findings


def check_constants(art: LoweredArtifacts, max_const_bytes: int = 128 * 1024) -> List[Finding]:
    """IR005: oversize constants baked into the executable.  Closure-captured
    arrays become jaxpr consts and ship INSIDE the compiled program: replay
    rings, weight tables or env data folded this way bloat every executable copy
    and silently re-upload on each recompile — pass them as arguments instead."""
    entry = art.entry
    findings: List[Finding] = []
    total = 0
    worst: Optional[int] = None
    count = 0
    for const in iter_consts(art.jaxpr):
        nbytes = int(getattr(const, "nbytes", 0) or 0)
        total += nbytes
        if nbytes > max_const_bytes:
            count += 1
            worst = max(worst or 0, nbytes)
    if count:
        findings.append(
            Finding(
                rule="IR005",
                path=entry.name,
                line=0,
                col=0,
                message=(
                    f"{count} constant(s) above {_fmt_bytes(max_const_bytes)} baked into "
                    f"the executable (largest {_fmt_bytes(worst)}, total consts "
                    f"{_fmt_bytes(total)}): pass large arrays as arguments, not closures"
                ),
                detail="oversize-const",
            )
        )
    return findings


def run_ir_rules(art: LoweredArtifacts, max_const_bytes: int = 128 * 1024) -> List[Finding]:
    """IR001-IR005 over one entry's artifacts (IR006 runs in ``budgets``)."""
    findings: List[Finding] = []
    findings.extend(check_donation(art))
    findings.extend(check_dtype_promotion(art))
    findings.extend(check_callbacks(art))
    findings.extend(check_collectives(art))
    findings.extend(check_constants(art, max_const_bytes))
    return findings


def measured_budget(art: LoweredArtifacts) -> Dict[str, int]:
    """The IR006 measurement for one entry (bytes; zeros when the backend has no
    memory stats)."""
    m = art.memory
    if m is None:
        return {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0, "alias_bytes": 0, "total_bytes": 0}
    arg = int(m.argument_size_in_bytes)
    out = int(m.output_size_in_bytes)
    temp = int(m.temp_size_in_bytes)
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": int(m.alias_size_in_bytes),
        "total_bytes": arg + out + temp,
    }
