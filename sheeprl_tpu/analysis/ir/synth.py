"""Shared helpers for the ``lower_for_audit()`` hooks: tiny configs, synthetic
spaces and batches.

The audit's contract is "lower the REAL builder with the SMALLEST shapes it
accepts": every hook composes a config through the same
:func:`sheeprl_tpu.config.core.compose` path the CLI uses (so config-derived
trace-time constants — precision, loss reductions, cadences — are the production
code paths), swaps the env for synthetic ``gymnasium`` spaces, and feeds
zero-filled batches.  Values never matter to lowering; only shapes, dtypes and
trace-time constants do.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def tiny_ctx(cfg, seed: int = 0):
    """A single-device MeshContext at the config's declared precision — the same
    context shape every training loop builds, pinned to one device so the audit
    graph is the single-mesh program IR004 checks."""
    import jax

    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

    precision = (cfg.get("mesh") or {}).get("precision", "fp32")
    return MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision=precision, seed=seed)


def compose_tiny(overrides: Sequence[str]):
    """Compose a config for an audit build.  The analysis flags that inject host
    callbacks (strict-mode ``nan_scan``) or fault injection stay OFF so the
    audited program is the default production graph — IR003 then treats ANY
    in-scan callback as a violation.  ``obs.health`` keeps its default (on):
    the in-jit diagnostics are part of the graph production compiles."""
    from sheeprl_tpu.config.core import compose

    return compose(
        overrides=[
            *overrides,
            "analysis.strict=False",
            "analysis.inject_nan=False",
            "dry_run=True",
        ]
    )


def vector_space(dim: int = 5, key: str = "state"):
    import gymnasium as gym

    return gym.spaces.Dict({key: gym.spaces.Box(-20.0, 20.0, (dim,), np.float32)})


def pixel_space(channels: int = 3, size: int = 32, key: str = "rgb"):
    import gymnasium as gym

    return gym.spaces.Dict({key: gym.spaces.Box(0, 255, (channels, size, size), np.uint8)})


def box_act_space(dim: int = 2):
    import gymnasium as gym

    return gym.spaces.Box(-1.0, 1.0, (dim,), np.float32)


def discrete_act_space(n: int = 3):
    import gymnasium as gym

    return gym.spaces.Discrete(n)


def zeros(shape: Tuple[int, ...], dtype="float32"):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype)


def sequence_batch(
    obs_shapes: Dict[str, Tuple[int, ...]],
    act_dim: int,
    T: int = 3,
    B: int = 2,
    uint8_keys: Optional[Sequence[str]] = None,
):
    """A Dreamer-family ``[T, B, ...]`` sequence batch (the sampled-replay layout
    every ``make_train_step`` consumes): obs keys + actions/rewards/is_first/
    terminated/truncated."""
    uint8_keys = set(uint8_keys or ())
    batch = {
        k: zeros((T, B, *shape), "uint8" if k in uint8_keys else "float32")
        for k, shape in obs_shapes.items()
    }
    batch.update(
        {
            "actions": zeros((T, B, act_dim)),
            "rewards": zeros((T, B, 1)),
            "is_first": zeros((T, B, 1)),
            "terminated": zeros((T, B, 1)),
            "truncated": zeros((T, B, 1)),
        }
    )
    return batch


def transition_ring(obs_dim: int, act_dim: int, n_envs: int = 2, capacity: int = 16, steps: int = 8):
    """A tiny filled :class:`~sheeprl_tpu.data.device_buffer.DeviceTransitionRing`
    for the SAC-family fused-block audits; returns ``(ring, filled, rows_added)``."""
    import jax.numpy as jnp

    from sheeprl_tpu.data.device_buffer import DeviceTransitionRing

    ring = DeviceTransitionRing(
        capacity,
        n_envs,
        {
            "obs": ((obs_dim,), jnp.float32),
            "next_obs": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "dones": ((1,), jnp.float32),
        },
    )
    for t in range(steps):
        ring.add_step(
            {
                "obs": np.zeros((1, n_envs, obs_dim), np.float32),
                "next_obs": np.zeros((1, n_envs, obs_dim), np.float32),
                "actions": np.zeros((1, n_envs, act_dim), np.float32),
                "rewards": np.zeros((1, n_envs, 1), np.float32),
                "dones": np.zeros((1, n_envs, 1), np.float32),
            },
            t % capacity,
            t,
        )
    return ring, min(steps, capacity), steps


#: shared Dreamer-family shrink: MLP-only, minimal widths.  Compile time is what
#: bounds the audit (<2 min on one CPU core across every entry point), and the
#: bug classes IR001-IR006 catch are structural, not width-dependent.
DREAMER_TINY_OVERRIDES: List[str] = [
    "algo.cnn_keys.encoder=[]",
    "algo.mlp_keys.encoder=[state]",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=3",
    "algo.horizon=2",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.representation_model.hidden_size=8",
]

#: extra shrink for the discrete-latent variants (DV2/DV3/P2E)
DREAMER_DISCRETE_OVERRIDES: List[str] = ["algo.world_model.discrete_size=4"]
