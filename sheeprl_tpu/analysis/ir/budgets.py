"""IR006 — compile-memory budgets against the checked-in ``irbudgets.json``.

Same new-violations-only philosophy as ``jaxlint.baseline``: the baseline records
each audit entry's compile-memory footprint (argument + output + temp bytes from
``compiled.memory_analysis()``) at tiny audit shapes; CI fails only when an entry
drifts past the tolerance, appears with no baseline row, or when a baselined
entry disappears unnoticed.  Regenerate with::

    python -m sheeprl_tpu.analysis.ir --write-budgets

and commit the diff — the review of that diff IS the budget sign-off.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List, Optional

from sheeprl_tpu.analysis.core import Finding

DEFAULT_BUDGETS_FILE = "irbudgets.json"
#: relative drift allowed before IR006 fires; tiny-shape footprints jitter a few
#: percent across XLA releases, real regressions (an un-donated ring, a doubled
#: buffer) jump 2x
DEFAULT_TOLERANCE = 0.25
#: absolute slack so KB-sized graphs don't trip on layout-padding noise
DEFAULT_ABS_SLACK = 8 * 1024


def load_budgets(path: os.PathLike) -> Optional[Dict]:
    p = Path(path)
    if not p.is_file():
        return None
    with open(p) as f:
        return json.load(f)


def write_budgets(
    measurements: Dict[str, Dict[str, int]],
    path: os.PathLike,
    tolerance: float = DEFAULT_TOLERANCE,
    abs_slack: int = DEFAULT_ABS_SLACK,
) -> None:
    import jax

    doc = {
        "meta": {
            "tolerance": tolerance,
            "abs_slack_bytes": abs_slack,
            "jax": jax.__version__,
            "comment": "compile-memory budgets per audit entry at tiny audit shapes; "
            "regenerate with: python -m sheeprl_tpu.analysis.ir --write-budgets",
        },
        "entries": {name: dict(m) for name, m in sorted(measurements.items())},
    }
    Path(path).write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")


def check_budgets(
    measurements: Dict[str, Dict[str, int]],
    baseline: Optional[Dict],
    tolerance: Optional[float] = None,
) -> List[Finding]:
    """IR006 findings: per-entry total-bytes drift beyond tolerance, entries with
    no baseline row, and stale baseline rows for entries that no longer exist."""
    findings: List[Finding] = []
    if baseline is None:
        findings.append(
            Finding(
                rule="IR006",
                path="<budgets>",
                line=0,
                col=0,
                message=(
                    "no irbudgets.json baseline found: generate one with "
                    "'python -m sheeprl_tpu.analysis.ir --write-budgets' and commit it"
                ),
                detail="missing-baseline",
            )
        )
        return findings

    meta = baseline.get("meta", {})
    tol = float(tolerance if tolerance is not None else meta.get("tolerance", DEFAULT_TOLERANCE))
    slack = int(meta.get("abs_slack_bytes", DEFAULT_ABS_SLACK))
    base_entries = baseline.get("entries", {})

    for name, m in sorted(measurements.items()):
        base = base_entries.get(name)
        if base is None:
            findings.append(
                Finding(
                    rule="IR006",
                    path=name,
                    line=0,
                    col=0,
                    message=(
                        "new audit entry with no compile-memory budget baseline: "
                        "regenerate irbudgets.json (--write-budgets) and commit it"
                    ),
                    detail="no-budget-row",
                )
            )
            continue
        measured = int(m.get("total_bytes", 0))
        budget = int(base.get("total_bytes", 0))
        allowed = budget * (1.0 + tol) + slack
        if measured > allowed:
            findings.append(
                Finding(
                    rule="IR006",
                    path=name,
                    line=0,
                    col=0,
                    message=(
                        f"compile-memory budget exceeded: {measured} bytes measured vs "
                        f"{budget} baselined (+{(measured - budget) / max(budget, 1) * 100:.0f}%, "
                        f"tolerance {tol * 100:.0f}% + {slack} B) — if intentional, "
                        "regenerate irbudgets.json with --write-budgets"
                    ),
                    detail="budget-exceeded",
                )
            )

    for name in sorted(set(base_entries) - set(measurements)):
        findings.append(
            Finding(
                rule="IR006",
                path=name,
                line=0,
                col=0,
                message=(
                    "stale budget baseline row: this audit entry no longer exists — "
                    "regenerate irbudgets.json with --write-budgets"
                ),
                detail="stale-budget-row",
            )
        )
    return findings
