"""Registry of auditable programs: every train-step builder's ``lower_for_audit``.

Each value is a ``"module:function"`` hook resolved lazily (importing an algo
module pulls in jax/flax — the CLI only pays for what it audits).  A hook returns
a list of :class:`~sheeprl_tpu.analysis.ir.types.AuditEntry`; one builder may
expose several programs (e.g. SAC's host-batch scan AND its donated fused ring
block are both real dispatch shapes).

``EXPECTED_COVERAGE`` pins the audit's floor: the union of ``covers`` over all
entries must include every CLI entry point's jitted update plus both Anakin
dispatches — the audit fails closed (IR000) if a registry edit drops one.
"""

from __future__ import annotations

import importlib
from typing import Dict, Iterator, List, Optional, Sequence

from sheeprl_tpu.analysis.core import Finding
from sheeprl_tpu.analysis.ir.types import AuditEntry

#: audit-unit name -> lower_for_audit hook
REGISTRY: Dict[str, str] = {
    "ppo": "sheeprl_tpu.algos.ppo.ppo:lower_for_audit",
    "ppo_recurrent": "sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent:lower_for_audit",
    "a2c": "sheeprl_tpu.algos.a2c.a2c:lower_for_audit",
    "sac": "sheeprl_tpu.algos.sac.sac:lower_for_audit",
    "sac_ae": "sheeprl_tpu.algos.sac_ae.sac_ae:lower_for_audit",
    "droq": "sheeprl_tpu.algos.droq.droq:lower_for_audit",
    "dreamer_v1": "sheeprl_tpu.algos.dreamer_v1.dreamer_v1:lower_for_audit",
    "dreamer_v2": "sheeprl_tpu.algos.dreamer_v2.dreamer_v2:lower_for_audit",
    "dreamer_v3": "sheeprl_tpu.algos.dreamer_v3.dreamer_v3:lower_for_audit",
    "p2e_dv1": "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration:lower_for_audit",
    "p2e_dv2": "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration:lower_for_audit",
    "p2e_dv3": "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration:lower_for_audit",
    "anakin": "sheeprl_tpu.engine.anakin:lower_for_audit",
    "serve": "sheeprl_tpu.serve.precompile:lower_for_audit",
}

#: the 14 CLI entry points whose jitted updates the audit must cover, plus the
#: four Anakin dispatch programs — plain AND population (``algo.population``)
#: for each algo family (p2e finetuning rides the dreamer-family
#: make_train_step builders, so the exploration entries cover it)
EXPECTED_COVERAGE = frozenset(
    {
        "ppo",
        "ppo_decoupled",
        "ppo_recurrent",
        "a2c",
        "sac",
        "sac_decoupled",
        "sac_ae",
        "droq",
        "dreamer_v1",
        "dreamer_v2",
        "dreamer_v3",
        "p2e_dv1_exploration",
        "p2e_dv2_exploration",
        "p2e_dv3_exploration",
        "anakin_ppo",
        "anakin_sac",
        "anakin_ppo_pop",
        "anakin_sac_pop",
        # The serve tier's AOT act programs (sheeprl_tpu/serve/precompile.py):
        # the inference server dispatches ONLY precompiled ladder buckets, so the
        # served act fns must stay under audit exactly like training dispatches.
        "serve_ppo",
        "serve_sac",
        # Precision tier (howto/precision.md): the algo.precision=bf16 Anakin
        # dispatches (IR002 proves bf16 on the dots with mesh pinned to fp32)
        # and the serve.precision=int8 act programs (dequant-in-jit kernels).
        "anakin_ppo_bf16",
        "anakin_sac_bf16",
        "serve_ppo_int8",
        "serve_sac_int8",
    }
)


def registry_names() -> List[str]:
    return sorted(REGISTRY)


def build_entries(select: Optional[Sequence[str]] = None) -> Iterator[AuditEntry]:
    """Build (lazily, one registry unit at a time) the audit entries; ``select``
    filters by registry key.  Unknown keys raise ``ValueError`` eagerly."""
    if select:
        unknown = set(select) - set(REGISTRY)
        if unknown:
            raise ValueError(f"unknown audit unit(s): {sorted(unknown)}; known: {registry_names()}")

    def _iter() -> Iterator[AuditEntry]:
        for name in registry_names():
            if select and name not in select:
                continue
            mod_name, _, fn_name = REGISTRY[name].rpartition(":")
            hook = getattr(importlib.import_module(mod_name), fn_name)
            for entry in hook():
                yield entry

    return _iter()


def coverage_findings(entries: Sequence[AuditEntry], full_run: bool) -> List[Finding]:
    """IR000: the audit's own coverage floor (only meaningful on unfiltered runs)."""
    if not full_run:
        return []
    covered = set()
    for e in entries:
        covered.update(e.covers)
    missing = EXPECTED_COVERAGE - covered
    if not missing:
        return []
    return [
        Finding(
            rule="IR000",
            path="<coverage>",
            line=0,
            col=0,
            message=(
                f"audit coverage dropped below the floor: {sorted(missing)} no longer "
                "covered by any lower_for_audit hook"
            ),
            detail=f"missing:{','.join(sorted(missing))}",
        )
    ]
