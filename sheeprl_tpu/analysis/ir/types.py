"""Audit-entry contract between the IR tier and the train-step builders.

Each builder module (the 6 shared ``make_train_step``/train-fn builders, the
Dreamer-family ``make_train_step`` modules and ``engine/anakin.py``) exposes a
``lower_for_audit()`` hook returning a list of :class:`AuditEntry` — the jitted
update program built with TINY synthetic shapes, exactly as the entry point's
training loop builds it (same builder, same config plumbing), so what the audit
lowers is what production compiles.

This module is deliberately dependency-light (no jax import at module scope) so
the hooks can import it without cost.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple


@dataclass
class AuditEntry:
    """One lowerable program: ``fn`` must be a ``jax.jit``-wrapped callable (it
    exposes ``.lower``/``.trace``); ``args``/``kwargs`` are the synthetic example
    arguments.

    ``covers`` names the CLI entry points this program is the jitted update of
    (e.g. the shared ``PPOTrainFns.train_fn`` covers both ``ppo`` and
    ``ppo_decoupled``) — the audit's coverage report is the union over entries.

    ``precision`` is the config's declared compute precision for this build
    (``mesh.precision``); IR002 checks dtype promotion against it.

    ``callbacks_gated`` declares that host callbacks inside scan/while bodies are
    EXPECTED because the build enabled the obs/health/strict flags that emit them;
    the default audit build keeps those flags off, so any callback found is a
    violation (IR003).
    """

    name: str
    fn: Any
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any] = field(default_factory=dict)
    covers: Tuple[str, ...] = ()
    precision: str = "fp32"
    callbacks_gated: bool = False
    single_mesh: bool = True
    notes: str = ""
