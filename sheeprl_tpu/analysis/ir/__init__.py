"""jaxlint-IR: the jaxpr/HLO audit tier (``python -m sheeprl_tpu.analysis.ir``).

The AST tier (``sheeprl_tpu.analysis``) catches source-level hazards; this tier
audits what XLA actually compiles.  Every entry point's jitted update (and both
Anakin dispatches) is AOT-lowered through its REAL builder at tiny synthetic
shapes, then the closed jaxpr and compiled HLO are checked for:

* IR001 donation-not-applied (silent 2x device memory on the donated state),
* IR002 dtype promotion against the declared precision,
* IR003 ungated host callbacks inside scan/while bodies,
* IR004 cross-device collectives / host transfers in single-mesh graphs,
* IR005 oversize constants folded into the executable,
* IR006 compile-memory budget drift vs the checked-in ``irbudgets.json``.

See ``howto/static_analysis.md`` ("IR audit") for the workflow.
"""

from __future__ import annotations

from sheeprl_tpu.analysis.ir.types import AuditEntry  # noqa: F401
from sheeprl_tpu.analysis.ir.rules import (  # noqa: F401
    LoweredArtifacts,
    lower_entry,
    measured_budget,
    run_ir_rules,
)
from sheeprl_tpu.analysis.ir.budgets import check_budgets, load_budgets, write_budgets  # noqa: F401
from sheeprl_tpu.analysis.ir.entrypoints import (  # noqa: F401
    EXPECTED_COVERAGE,
    REGISTRY,
    build_entries,
    coverage_findings,
    registry_names,
)
