"""``python -m sheeprl_tpu.analysis.ir`` / ``jaxlint-ir`` — the IR audit CLI.

Exit status: 0 when no findings survive the baseline, 1 otherwise, 2 on usage
errors.

    jaxlint-ir                         # audit everything vs irbudgets.json
    jaxlint-ir --entry sac --entry droq  # one or two registry units only
    jaxlint-ir --write-budgets         # accept current compile-memory budgets
    jaxlint-ir --json report.json      # full machine-readable report (CI artifact)
    jaxlint-ir --list                  # registry units + covered entry points

The audit forces the CPU backend (platform-independent IR properties are what
the rules check) and must stay importable before jax initialises a backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

DEFAULT_BASELINE = "jaxlint-ir.baseline"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="jaxlint-ir",
        description="jaxlint-IR: jaxpr/HLO audit of every entry point's jitted update (rules IR000-IR006).",
    )
    parser.add_argument(
        "--entry",
        action="append",
        default=None,
        metavar="NAME",
        help="registry unit(s) to audit (default: all); repeatable",
    )
    parser.add_argument("--budgets", default=None, help="irbudgets.json path (default: ./irbudgets.json)")
    parser.add_argument(
        "--write-budgets",
        action="store_true",
        help="write the measured compile-memory budgets to the budgets file and exit 0",
    )
    parser.add_argument(
        "--tolerance", type=float, default=None, help="override the baseline's relative budget tolerance"
    )
    parser.add_argument(
        "--max-const-kb", type=int, default=128, help="IR005 threshold for baked-in constants (KiB)"
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="fingerprint baseline for intentional IR violations (optional file)",
    )
    parser.add_argument("--no-baseline", action="store_true", help="ignore the fingerprint baseline")
    parser.add_argument("--json", default=None, metavar="PATH", help="write the full JSON report here")
    parser.add_argument("--list", action="store_true", help="list registry units and covered entry points")
    parser.add_argument("-q", "--quiet", action="store_true", help="suppress progress/summary lines")
    args = parser.parse_args(argv)

    # Force CPU BEFORE jax initialises a backend: the audit runs on dev boxes and
    # CI runners; the IR properties it checks are backend-independent.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from sheeprl_tpu.analysis.core import filter_baseline, load_baseline
    from sheeprl_tpu.analysis.ir import (
        build_entries,
        check_budgets,
        coverage_findings,
        load_budgets,
        lower_entry,
        measured_budget,
        run_ir_rules,
        write_budgets,
    )
    from sheeprl_tpu.analysis.ir.budgets import DEFAULT_BUDGETS_FILE

    budgets_path = args.budgets or DEFAULT_BUDGETS_FILE
    full_run = not args.entry
    t0 = time.perf_counter()

    try:
        entry_iter = build_entries(args.entry)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    findings = []
    measurements: Dict[str, Dict[str, int]] = {}
    entries = []
    report_entries = []
    for entry in entry_iter:
        if args.list:
            entries.append(entry)
            continue
        t_entry = time.perf_counter()
        art = lower_entry(entry)
        entry_findings = run_ir_rules(art, max_const_bytes=args.max_const_kb * 1024)
        budget = measured_budget(art)
        measurements[entry.name] = budget
        findings.extend(entry_findings)
        entries.append(entry)
        elapsed = time.perf_counter() - t_entry
        if not args.quiet:
            status = "ok" if not entry_findings else f"{len(entry_findings)} finding(s)"
            print(
                f"jaxlint-ir: {entry.name}: {status} "
                f"(donated {art.donated_count} arg(s), {budget['total_bytes']} B, {elapsed:.1f}s)",
                file=sys.stderr,
            )
        report_entries.append(
            {
                "name": entry.name,
                "covers": list(entry.covers),
                "precision": entry.precision,
                "donated_args": art.donated_count,
                "budget": budget,
                "findings": [f.render() for f in entry_findings],
                "seconds": round(elapsed, 2),
            }
        )

    if args.list:
        for e in entries:
            print(f"{e.name}  covers: {', '.join(e.covers) or '-'}")
        return 0

    if args.write_budgets:
        if not full_run:
            print(
                "error: --write-budgets needs a full (unfiltered) audit so the "
                "baseline stays complete",
                file=sys.stderr,
            )
            return 2
        write_budgets(measurements, budgets_path)
        if not args.quiet:
            print(f"jaxlint-ir: wrote {len(measurements)} budget(s) to {budgets_path}")
        return 0

    findings.extend(coverage_findings(entries, full_run))
    baseline_doc = load_budgets(budgets_path)
    budget_findings = check_budgets(measurements, baseline_doc, tolerance=args.tolerance)
    if not full_run:
        # A filtered run audits a subset: entries absent from the run are not
        # stale, and coverage cannot be judged.
        budget_findings = [f for f in budget_findings if f.detail != "stale-budget-row"]
    findings.extend(budget_findings)

    baseline = None if args.no_baseline else load_baseline(args.baseline)
    if baseline:
        findings = filter_baseline(findings, baseline)

    for f in findings:
        print(f.render())
    if args.json:
        report = {
            "elapsed_seconds": round(time.perf_counter() - t0, 2),
            "entries": report_entries,
            "budgets_file": budgets_path,
            "findings": [
                {"rule": f.rule, "entry": f.path, "message": f.message, "detail": f.detail}
                for f in findings
            ],
        }
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=1)
    if not args.quiet:
        status = "ok" if not findings else f"{len(findings)} finding(s)"
        print(
            f"jaxlint-ir: {status} over {len(entries)} audit entr{'y' if len(entries) == 1 else 'ies'} "
            f"({time.perf_counter() - t0:.1f}s)",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
