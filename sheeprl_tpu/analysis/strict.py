"""Runtime strict mode (``analysis.strict=True``).

The static rules catch what is visible in the source; this module catches the rest at
run time, while the run is still cheap to kill:

* :func:`strict_guard` wraps a jitted entry point with a shape/dtype/structure guard:
  the first call records the argument signature, any later drift (the thing that
  silently recompiles) raises :class:`SignatureDriftError` instead;
* :func:`nan_scan` is called *inside* a jitted function and emits a
  ``jax.debug.callback`` that records non-finite outputs; :func:`assert_finite` /
  :func:`raise_pending` turn those records into :class:`NonFiniteError` at the update
  boundary, plus run a direct host-side scan over whatever tree they are given;
* ``TrainingMonitor`` (``sheeprl_tpu/obs``) reads the same flag and upgrades the
  recompile watchdog from a loud warning to a hard :class:`RecompileError`.

Everything is a no-op (identity wrapper, early return) when strict mode is off, so
the hot path pays nothing in normal runs.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Tuple

_pending_lock = threading.Lock()
_pending_nonfinite: List[str] = []

#: name -> guarded callable, for introspection/tests
_registered_guards: Dict[str, Callable] = {}


class StrictModeError(RuntimeError):
    """Base class for every hard failure strict mode introduces."""


class SignatureDriftError(StrictModeError):
    """A guarded jit entry point was called with a different signature than its
    first call: the exact condition that triggers a silent recompile."""


class NonFiniteError(StrictModeError):
    """A NaN/Inf crossed the update boundary."""


def strict_enabled(cfg: Any) -> bool:
    """True iff ``cfg.analysis.strict`` is set (tolerates dicts/DotDicts/None)."""
    if cfg is None:
        return False
    try:
        analysis = cfg.get("analysis") if hasattr(cfg, "get") else getattr(cfg, "analysis", None)
    except Exception:
        return False
    if not analysis:
        return False
    try:
        return bool(analysis.get("strict", False) if hasattr(analysis, "get") else getattr(analysis, "strict", False))
    except Exception:
        return False


# ----------------------------------------------------------------- signature guard
def _leaf_signature(leaf: Any) -> Tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None and dtype is None:
        return (type(leaf).__name__,)
    return (tuple(shape) if shape is not None else None, str(dtype))


def _signature(args: tuple, kwargs: dict) -> Tuple:
    import jax

    leaves, treedef = jax.tree.flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_signature(leaf) for leaf in leaves))


def strict_guard(cfg: Any, name: str, fn: Callable) -> Callable:
    """Wrap a jitted entry point with a first-call signature guard.

    Identity when strict mode is off.  The guard exists because a drifting argument
    signature is invisible until the recompile hits the profile; with strict mode on
    it fails at the call site with the offending leaf spelled out.
    """
    if not strict_enabled(cfg):
        return fn

    recorded: Dict[str, Tuple] = {}

    def guarded(*args, **kwargs):
        sig = _signature(args, kwargs)
        first = recorded.get("sig")
        if first is None:
            recorded["sig"] = sig
        elif sig != first:
            diff = _describe_drift(first, sig)
            from sheeprl_tpu.obs import flight_recorder

            flight_recorder.record_event("signature_drift", entry_point=name, diff=diff)
            raise SignatureDriftError(
                f"analysis.strict: jit entry point '{name}' called with a drifting signature "
                f"({diff}); this would silently recompile every time it changes. Pad/bucket the "
                f"inputs to a fixed shape, or exempt this entry point from the guard."
            )
        return fn(*args, **kwargs)

    guarded.__name__ = f"strict_guard[{name}]"
    guarded.__wrapped__ = fn
    _registered_guards[name] = guarded
    return guarded


def _describe_drift(first: Tuple, now: Tuple) -> str:
    if first[0] != now[0]:
        return f"tree structure changed: {first[0]} -> {now[0]}"
    for i, (a, b) in enumerate(zip(first[1], now[1])):
        if a != b:
            return f"leaf {i}: {a} -> {b}"
    return "argument count changed"


def registered_guards() -> Dict[str, Callable]:
    return dict(_registered_guards)


# --------------------------------------------------------------- fault injection
def inject_nonfinite_enabled(cfg: Any) -> bool:
    """True iff ``cfg.analysis.inject_nan`` is set — the flight-recorder e2e /
    chaos-drill knob (tolerates dicts/DotDicts/None)."""
    if cfg is None:
        return False
    try:
        analysis = cfg.get("analysis") if hasattr(cfg, "get") else getattr(cfg, "analysis", None)
    except Exception:
        return False
    if not analysis:
        return False
    try:
        return bool(
            analysis.get("inject_nan", False)
            if hasattr(analysis, "get")
            else getattr(analysis, "inject_nan", False)
        )
    except Exception:
        return False


def maybe_inject_nonfinite(cfg: Any, metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Poison one metric leaf with NaN when ``analysis.inject_nan`` is on.

    Called inside jitted updates (via ``obs.health.health_metrics``); the gate is a
    trace-time constant, so production runs compile no trace of it.  The injected
    leaf crosses the update boundary like any real NaN: strict mode trips
    ``assert_finite``/``nan_scan``, the flight recorder dumps, and — because the
    dumped config carries the flag — ``replay_blackbox`` reproduces it.
    """
    if not inject_nonfinite_enabled(cfg):
        return metrics
    import jax.numpy as jnp

    metrics = dict(metrics)
    metrics["Health/inject_nan"] = jnp.float32(jnp.nan)
    return metrics


# --------------------------------------------------------------------- NaN/Inf scan
def nan_scan(tree: Any, label: str) -> None:
    """Emit a non-finite check for every floating leaf of ``tree``.

    Call *inside* a jitted function (guarded by a trace-time ``if strict:``); the
    check runs as a ``jax.debug.callback``, so it costs one tiny host callback per
    update and never blocks the device.  Pending hits are raised by
    :func:`raise_pending` / :func:`assert_finite` at the next update boundary.
    """
    import jax
    import jax.numpy as jnp

    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    paths, flags = [], []
    for path, leaf in leaves_with_paths:
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        paths.append(jax.tree_util.keystr(path))
        flags.append(jnp.logical_not(jnp.all(jnp.isfinite(leaf))))
    if not flags:
        return

    def _record(*flag_values):
        hits = [p for p, f in zip(paths, flag_values) if bool(f)]
        if hits:
            with _pending_lock:
                _pending_nonfinite.extend(f"{label}{p}" for p in hits)

    jax.debug.callback(_record, *flags)


def raise_pending() -> None:
    """Raise :class:`NonFiniteError` if any ``nan_scan`` callback recorded a hit."""
    import jax

    try:
        jax.effects_barrier()  # flush outstanding debug callbacks
    except Exception:
        pass
    with _pending_lock:
        hits, _pending_nonfinite[:] = list(_pending_nonfinite), []
    if hits:
        from sheeprl_tpu.obs import flight_recorder

        flight_recorder.record_event("nonfinite", labels=sorted(set(hits)))
        raise NonFiniteError(
            f"analysis.strict: non-finite values crossed the update boundary: {sorted(set(hits))}"
        )


def clear_pending() -> None:
    with _pending_lock:
        _pending_nonfinite.clear()


def assert_finite(cfg: Any, tree: Any, label: str) -> None:
    """Update-boundary NaN/Inf scan: drains pending ``nan_scan`` hits, then checks
    every floating leaf of ``tree`` host-side.  No-op unless strict mode is on."""
    if not strict_enabled(cfg):
        return
    import numpy as np

    raise_pending()
    import jax

    bad: List[str] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if not np.isfinite(arr).all():
            bad.append(f"{label}{jax.tree_util.keystr(path)}")
    if bad:
        from sheeprl_tpu.obs import flight_recorder

        flight_recorder.record_event("nonfinite", labels=bad)
        raise NonFiniteError(f"analysis.strict: non-finite values at the update boundary: {bad}")
