"""Shared static-analysis plumbing: findings, fingerprints and baselines.

Both analysis tiers build on this module:

* the AST tier (``sheeprl_tpu.analysis.engine`` + ``rules/``, the ``jaxlint`` CLI)
  walks source files;
* the IR tier (``sheeprl_tpu.analysis.ir``, the ``jaxlint-ir`` CLI) AOT-lowers the
  jitted updates of every entry point and walks the closed jaxpr / compiled HLO.

A :class:`Finding` is one diagnostic with a stable ``fingerprint`` (rule + path +
rule-chosen detail token, deliberately *without* the line number so baselines
survive unrelated edits — for IR findings ``path`` is the audit-entry name and the
line is 0).  A baseline is a checked-in text file of fingerprints for intentional
violations, so CI starts green and fails only on new findings.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Sequence, Set


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``detail`` is a rule-chosen stable token (a config key, a
    ``function:variable`` pair, an IR artifact name, ...) used for baseline
    fingerprints instead of the line number, which churns with every unrelated
    edit."""

    rule: str  # "JL001" / "IR001"
    path: str  # repo-relative source path (AST) or audit-entry name (IR)
    line: int  # 1-based; 0 for IR findings (no source line)
    col: int
    message: str
    detail: str

    @property
    def fingerprint(self) -> str:
        return f"{self.rule} {self.path} {self.detail}"

    def render(self) -> str:
        if self.line <= 0:
            return f"{self.path}: {self.rule} {self.message}"
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


BASELINE_HEADER = "# jaxlint baseline v1 — one fingerprint per line: RULE path detail"


def load_baseline(path: os.PathLike) -> Set[str]:
    p = Path(path)
    if not p.is_file():
        return set()
    out: Set[str] = set()
    for raw in p.read_text().splitlines():
        line = raw.strip()
        if line and not line.startswith("#"):
            out.add(line)
    return out


def write_baseline(findings: Iterable[Finding], path: os.PathLike) -> None:
    lines = sorted({f.fingerprint for f in findings})
    Path(path).write_text(BASELINE_HEADER + "\n" + "\n".join(lines) + "\n")


def filter_baseline(findings: Sequence[Finding], baseline: Set[str]) -> List[Finding]:
    return [f for f in findings if f.fingerprint not in baseline]
