"""jaxlint: JAX-aware static analysis + runtime strict mode for the training stack.

Static half (``python -m sheeprl_tpu.analysis [paths]``): AST rules JL001–JL006 over
the codebase, with ``# jaxlint: disable=RULE`` suppressions and a checked-in
``jaxlint.baseline`` of intentional exceptions so CI fails only on *new* violations.

Runtime half (``analysis.strict=True`` in the config tree): shape/dtype guards on
registered jit entry points, a NaN/Inf scan at the update boundary, and the ``obs``
recompile watchdog upgraded from warning to hard error.  See
``howto/static_analysis.md``.
"""

from sheeprl_tpu.analysis.engine import (
    Finding,
    Rule,
    filter_baseline,
    load_baseline,
    parse_suppressions,
    run_lint,
    write_baseline,
)
from sheeprl_tpu.analysis.strict import (
    NonFiniteError,
    SignatureDriftError,
    StrictModeError,
    assert_finite,
    nan_scan,
    raise_pending,
    strict_enabled,
    strict_guard,
)

__all__ = [
    "Finding",
    "Rule",
    "run_lint",
    "load_baseline",
    "write_baseline",
    "filter_baseline",
    "parse_suppressions",
    "StrictModeError",
    "SignatureDriftError",
    "NonFiniteError",
    "strict_enabled",
    "strict_guard",
    "assert_finite",
    "nan_scan",
    "raise_pending",
]
