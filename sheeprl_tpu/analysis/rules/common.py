"""Shared AST machinery for the jaxlint rules.

Everything here is *heuristic but sound in practice*: we resolve import aliases to
canonical dotted paths (``jrandom.split`` -> ``jax.random.split``), walk function
scopes without descending into nested function bodies (each nested function is its own
scope), and propagate "this name is a jitted callable" facts through the simple
assignment patterns the codebase actually uses (decorated defs, ``f = jax.jit(g)``,
``self.f = f``, ``f = obj.f`` and tuple versions thereof).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: transforms whose function argument is traced (python control flow on its
#: arguments is a concretization error)
TRACING_TRANSFORMS = {
    "jax.jit",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
}

JIT_WRAPPERS = {"jax.jit", "jax.pmap"}

#: attribute accesses on a traced value that yield *static* (trace-time) information
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "weak_type", "sharding", "itemsize"}

#: calls that return static information regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "type", "jax.numpy.shape", "jax.numpy.ndim", "numpy.shape", "numpy.ndim"}


# ------------------------------------------------------------------ import aliases
def collect_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> canonical dotted path, from every import in the module."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def qualname(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted path of a Name/Attribute chain, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = aliases.get(node.id, node.id)
    parts.append(root)
    return ".".join(reversed(parts))


def call_qualname(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    return qualname(call.func, aliases)


# ------------------------------------------------------------------------- scopes
@dataclass
class Scope:
    """One function (or the module) and its immediate body, nested scopes excluded."""

    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda / Module
    parent: Optional["Scope"]

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>" if isinstance(self.node, ast.Lambda) else "<module>")

    def body(self) -> List[ast.stmt]:
        if isinstance(self.node, ast.Lambda):
            return [ast.Expr(self.node.body)]
        return list(self.node.body)

    def params(self) -> List[str]:
        if not isinstance(self.node, FunctionNode):
            return []
        a = self.node.args
        names = [x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


def iter_scopes(tree: ast.AST) -> Iterator[Scope]:
    """Yield the module scope and every function scope, with parent links."""

    def rec(node: ast.AST, parent: Optional[Scope]) -> Iterator[Scope]:
        scope = Scope(node, parent)
        yield scope
        for child in walk_scope(node):
            if isinstance(child, FunctionNode):
                yield from rec(child, scope)

    yield from rec(tree, None)


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk limited to the current scope: does not descend into nested functions
    (their *bodies*; decorators and defaults belong to the enclosing scope)."""
    stack: List[ast.AST] = list(node) if isinstance(node, list) else list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, FunctionNode):
            for dec in getattr(n, "decorator_list", []):
                stack.append(dec)
            continue  # nested scope: skip the body
        stack.extend(ast.iter_child_nodes(n))


def walk_stmts_scope(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    for stmt in stmts:
        yield stmt
        if isinstance(stmt, FunctionNode):
            continue  # nested scope: its body belongs to its own Scope
        yield from walk_scope(stmt)


def target_names(target: ast.AST) -> Iterator[str]:
    """Plain names bound by an assignment target (tuples/lists/starred unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from target_names(el)
    elif isinstance(target, ast.Starred):
        yield from target_names(target.value)


def stmt_assigned_names(node: ast.AST) -> Set[str]:
    """Every plain name (re)bound anywhere inside ``node`` (current scope only)."""
    out: Set[str] = set()
    for n in walk_scope(node) if not isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)) else [node]:
        if isinstance(n, ast.Assign):
            for t in n.targets:
                out.update(target_names(t))
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            out.update(target_names(n.target))
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            out.update(target_names(n.target))
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            out.update(target_names(n.optional_vars))
        elif isinstance(n, ast.NamedExpr):
            out.update(target_names(n.target))
        elif isinstance(n, FunctionNode) and hasattr(n, "name"):
            out.add(n.name)  # a def rebinds its name
    return out


# --------------------------------------------------------------- jit-ness tracking
def _jit_call_info(call: ast.Call, aliases: Dict[str, str]) -> Optional[Dict[str, tuple]]:
    """If ``call`` is ``jax.jit(...)`` (or ``partial(jax.jit, ...)``), return its
    static/donate argument spec; else None."""
    qn = call_qualname(call, aliases)
    if qn in ("functools.partial", "partial") and call.args:
        inner = call.args[0]
        if qualname(inner, aliases) in JIT_WRAPPERS:
            return _extract_jit_kwargs(call)
        return None
    if qn in JIT_WRAPPERS:
        return _extract_jit_kwargs(call)
    return None


def _literal_tuple(node: ast.AST) -> tuple:
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(v, (int, str)):
        return (v,)
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return ()


def _extract_jit_kwargs(call: ast.Call) -> Dict[str, tuple]:
    spec = {"static_argnums": (), "static_argnames": (), "donate_argnums": (), "donate_argnames": ()}
    for kw in call.keywords:
        if kw.arg in spec:
            spec[kw.arg] = _literal_tuple(kw.value)
    return spec


@dataclass
class JitIndex:
    """Which names/attributes in a module are jitted callables, plus their
    static/donate specs.  Built with a small fixpoint over simple assignments."""

    names: Set[str] = field(default_factory=set)
    attrs: Set[str] = field(default_factory=set)
    specs: Dict[str, Dict[str, tuple]] = field(default_factory=dict)  # name -> jit kwargs

    def is_jitted_callee(self, func: ast.AST) -> Optional[str]:
        """Return a display name if ``func`` (a Call.func node) is a known jitted
        callable: a known Name, or any attribute access with a known jitted attr."""
        if isinstance(func, ast.Name) and func.id in self.names:
            return func.id
        if isinstance(func, ast.Attribute) and func.attr in self.attrs:
            return func.attr
        return None


def build_jit_index(tree: ast.AST, aliases: Dict[str, str]) -> JitIndex:
    idx = JitIndex()
    # Decorated defs (any nesting).
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    spec = _jit_call_info(dec, aliases)
                    if spec is not None:
                        idx.names.add(node.name)
                        idx.specs[node.name] = spec
                elif qualname(dec, aliases) in JIT_WRAPPERS:
                    idx.names.add(node.name)
    # Fixpoint over assignments: f = jax.jit(g); self.f = f; f = obj.f; tuples.
    for _ in range(3):
        before = (len(idx.names), len(idx.attrs))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                pairs: List[Tuple[ast.AST, ast.AST]] = []
                if isinstance(target, (ast.Tuple, ast.List)) and isinstance(node.value, (ast.Tuple, ast.List)):
                    if len(target.elts) == len(node.value.elts):
                        pairs = list(zip(target.elts, node.value.elts))
                else:
                    pairs = [(target, node.value)]
                for tgt, val in pairs:
                    jitted = False
                    spec = None
                    if isinstance(val, ast.Call):
                        spec = _jit_call_info(val, aliases)
                        jitted = spec is not None
                    elif isinstance(val, ast.Name) and val.id in idx.names:
                        jitted, spec = True, idx.specs.get(val.id)
                    elif isinstance(val, ast.Attribute) and val.attr in idx.attrs:
                        jitted, spec = True, idx.specs.get(val.attr)
                    if not jitted:
                        continue
                    if isinstance(tgt, ast.Name):
                        idx.names.add(tgt.id)
                        if spec:
                            idx.specs[tgt.id] = spec
                    elif isinstance(tgt, ast.Attribute):
                        idx.attrs.add(tgt.attr)
                        if spec:
                            idx.specs[tgt.attr] = spec
        if (len(idx.names), len(idx.attrs)) == before:
            break
    return idx


# ------------------------------------------------------------------ taint helpers
def expr_tainted(node: ast.AST, tainted: Set[str], aliases: Dict[str, str]) -> bool:
    """Does evaluating ``node`` depend on the *value* (not just static metadata) of a
    tainted name?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return expr_tainted(node.value, tainted, aliases)
    if isinstance(node, ast.Call):
        qn = call_qualname(node, aliases)
        if qn in STATIC_CALLS:
            return False
        args: Iterable[ast.AST] = [*node.args, *[kw.value for kw in node.keywords]]
        return any(expr_tainted(a, tainted, aliases) for a in args)
    if isinstance(node, FunctionNode):
        return False
    return any(expr_tainted(child, tainted, aliases) for child in ast.iter_child_nodes(node))


def enclosing_loops(scope_body: Sequence[ast.stmt]) -> List[Tuple[ast.AST, List[ast.AST]]]:
    """Every for/while loop in a scope with the list of nodes inside it (scope-local)."""
    out = []
    for node in walk_stmts_scope(scope_body):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            inner = list(walk_stmts_scope(node.body + node.orelse))
            out.append((node, inner))
    return out
