"""JL004 — recompile hazards.

Three statically detectable ways to turn a 30µs jit cache hit into a multi-second
XLA compile every step:

* **jit-in-loop** — applying ``jax.jit`` (directly, via ``partial``, or as a decorator
  on a def) inside a ``for``/``while`` body creates a fresh cache each iteration;
* **unhashable static arg** — a list/dict/set literal passed for a
  ``static_argnums``/``static_argnames`` parameter (TypeError at best, recompile via
  ``str()`` fallback in older JAX at worst);
* **varying static arg** — a loop-varying name passed for a static parameter
  recompiles on every new value;
* **mutable closure** — a jitted nested function closing over a name the enclosing
  scope reassigns *after* the definition: the trace bakes in the first value and the
  update never reaches the compiled code (or, with explicit re-wrapping, recompiles).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import (
    FunctionNode,
    Scope,
    _jit_call_info,
    build_jit_index,
    collect_aliases,
    enclosing_loops,
    iter_scopes,
    qualname,
    stmt_assigned_names,
    target_names,
    walk_scope,
)

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


class RecompileHazard(Rule):
    id = "JL004"
    name = "recompile-hazard"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        jit_index = build_jit_index(module.tree, aliases)
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            findings.extend(self._jit_in_loop(module, scope, aliases))
            findings.extend(self._static_arg_hazards(module, scope, aliases, jit_index))
        findings.extend(self._mutable_closures(module, aliases))
        return findings

    # ------------------------------------------------------------- jit-in-loop
    def _jit_in_loop(self, module: Module, scope: Scope, aliases) -> List[Finding]:
        findings: List[Finding] = []
        for loop, inner in enclosing_loops(scope.body()):
            for n in inner:
                is_jit = isinstance(n, ast.Call) and _jit_call_info(n, aliases) is not None
                if not is_jit and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    is_jit = any(
                        (isinstance(d, ast.Call) and _jit_call_info(d, aliases) is not None)
                        or qualname(d, aliases) in ("jax.jit", "jax.pmap")
                        for d in n.decorator_list
                    )
                if is_jit:
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=module.path,
                            line=n.lineno,
                            col=n.col_offset,
                            message="jax.jit applied inside a loop: every iteration builds a fresh "
                            "jit cache and recompiles; hoist the jit out of the loop",
                            detail=f"{scope.name}:jit-in-loop",
                        )
                    )
        return findings

    # ------------------------------------------------------ static-arg hazards
    def _static_arg_hazards(self, module: Module, scope: Scope, aliases, jit_index) -> List[Finding]:
        findings: List[Finding] = []
        loops = enclosing_loops(scope.body())
        loop_varying: Dict[int, Set[str]] = {}
        loop_members: List[Tuple[ast.AST, Set[int]]] = []
        for loop, inner in loops:
            names: Set[str] = set()
            if isinstance(loop, (ast.For, ast.AsyncFor)):
                names.update(target_names(loop.target))
            for n in inner:
                if isinstance(n, ast.stmt):
                    names |= stmt_assigned_names(n)
            loop_varying[id(loop)] = names
            loop_members.append((loop, {id(x) for x in inner}))

        for node in walk_scope(scope.node):
            if not isinstance(node, ast.Call):
                continue
            callee = jit_index.is_jitted_callee(node.func)
            if callee is None:
                continue
            spec = jit_index.specs.get(callee)
            if not spec:
                continue
            static_nums = {n for n in spec.get("static_argnums", ()) if isinstance(n, int)}
            static_names = set(spec.get("static_argnames", ()))
            if not static_nums and not static_names:
                continue
            in_loops = [loop for loop, members in loop_members if id(node) in members]
            static_args = [(i, a) for i, a in enumerate(node.args) if i in static_nums]
            static_args += [(kw.arg, kw.value) for kw in node.keywords if kw.arg in static_names]
            for pos, arg in static_args:
                if isinstance(arg, _UNHASHABLE):
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=module.path,
                            line=arg.lineno,
                            col=arg.col_offset,
                            message=f"unhashable literal passed as static argument {pos!r} of jitted "
                            f"'{callee}'; static args must be hashable (use a tuple)",
                            detail=f"{scope.name}:{callee}:static-unhashable",
                        )
                    )
                elif isinstance(arg, ast.Name) and any(
                    arg.id in loop_varying.get(id(loop), ()) for loop in in_loops
                ):
                    findings.append(
                        Finding(
                            rule=self.id,
                            path=module.path,
                            line=arg.lineno,
                            col=arg.col_offset,
                            message=f"loop-varying value '{arg.id}' passed as static argument {pos!r} of "
                            f"jitted '{callee}': every new value recompiles; pass it traced or hoist it",
                            detail=f"{scope.name}:{callee}:static-varying",
                        )
                    )
        return findings

    # --------------------------------------------------------- mutable closure
    def _mutable_closures(self, module: Module, aliases) -> List[Finding]:
        findings: List[Finding] = []
        reported: Set[str] = set()
        for scope in iter_scopes(module.tree):
            if not isinstance(scope.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            enclosing = scope.parent
            if enclosing is None or not isinstance(enclosing.node, FunctionNode):
                continue
            jitted = any(
                (isinstance(d, ast.Call) and _jit_call_info(d, aliases) is not None)
                or qualname(d, aliases) in ("jax.jit", "jax.pmap")
                for d in scope.node.decorator_list
            )
            if not jitted:
                continue
            # free variables: names read in the nested fn, not bound locally
            local = set(scope.params())
            for stmt in scope.body():
                local |= stmt_assigned_names(stmt)
            reads: Set[str] = set()
            for n in walk_scope(scope.node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and n.id not in local:
                    reads.add(n.id)
            # enclosing-scope rebinds after the def line (or inside a loop)
            def_line = scope.node.lineno
            for stmt in enclosing.body():
                for n in [stmt, *walk_scope(stmt)]:
                    if not isinstance(n, ast.stmt):
                        continue
                    assigned = stmt_assigned_names(n) & reads
                    if not assigned:
                        continue
                    in_loop = any(
                        id(n) in {id(x) for x in inner} for _, inner in enclosing_loops(enclosing.body())
                    )
                    if n.lineno > def_line or in_loop:
                        for name in sorted(assigned):
                            fp = f"{enclosing.name}:{scope.name}:closure:{name}"
                            if fp in reported:
                                continue
                            reported.add(fp)
                            findings.append(
                                Finding(
                                    rule=self.id,
                                    path=module.path,
                                    line=scope.node.lineno,
                                    col=scope.node.col_offset,
                                    message=f"jitted '{scope.name}' closes over '{name}', which "
                                    f"'{enclosing.name}' reassigns at line {n.lineno}: the trace bakes "
                                    "in the first value — pass it as an argument instead",
                                    detail=f"{enclosing.name}:{scope.name}:closure:{name}",
                                )
                            )
                        break
        return findings
