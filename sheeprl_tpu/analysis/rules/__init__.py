"""jaxlint rule catalogue.

| ID    | name                   | catches                                             |
|-------|------------------------|-----------------------------------------------------|
| JL001 | prng-key-reuse         | same PRNG key consumed twice without a split        |
| JL002 | traced-control-flow    | python if/while/bool() on a traced value            |
| JL003 | host-sync-in-hot-loop  | .item()/float()/np.asarray on device arrays in loops|
| JL004 | recompile-hazard       | jit-in-loop, varying/unhashable static args,        |
|       |                        | jitted closures over mutable state                  |
| JL005 | use-after-donation     | reads of a buffer after donate_argnums donated it   |
| JL006 | config-drift           | cfg keys accessed-but-undefined / defined-but-dead  |
| JL007 | donated-binding-reuse  | a caller reuses a binding it passed into a function |
|       |                        | that forwards it to a donated argument              |
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from sheeprl_tpu.analysis.engine import Rule
from sheeprl_tpu.analysis.rules.jl001_prng import PRNGKeyReuse
from sheeprl_tpu.analysis.rules.jl002_traced_control_flow import TracedControlFlow
from sheeprl_tpu.analysis.rules.jl003_host_sync import HostSyncInHotLoop
from sheeprl_tpu.analysis.rules.jl004_recompile import RecompileHazard
from sheeprl_tpu.analysis.rules.jl005_donation import UseAfterDonation
from sheeprl_tpu.analysis.rules.jl006_config_drift import ConfigDrift
from sheeprl_tpu.analysis.rules.jl007_donated_binding import DonatedBindingReuse

_RULE_CLASSES = [
    PRNGKeyReuse,
    TracedControlFlow,
    HostSyncInHotLoop,
    RecompileHazard,
    UseAfterDonation,
    ConfigDrift,
    DonatedBindingReuse,
]


def default_rules(select: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the rule set, optionally restricted to the given rule ids."""
    rules = [cls() for cls in _RULE_CLASSES]
    if select:
        wanted = {s.strip().upper() for s in select}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {sorted(unknown)}; known: {[r.id for r in rules]}")
        rules = [r for r in rules if r.id in wanted]
    return rules


__all__ = [
    "default_rules",
    "PRNGKeyReuse",
    "TracedControlFlow",
    "HostSyncInHotLoop",
    "RecompileHazard",
    "UseAfterDonation",
    "ConfigDrift",
    "DonatedBindingReuse",
]
