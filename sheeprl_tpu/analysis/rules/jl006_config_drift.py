"""JL006 — Hydra config drift.

Cross-checks every ``cfg.x.y`` / ``cfg.get("x")`` access in the linted Python files
against the union of the YAML config tree (:mod:`sheeprl_tpu.analysis.config_index`):

* **accessed-but-undefined** — the code reads a key no YAML file defines.  With a
  ``.get(..., default)`` this fails *silently*: the hard-coded default shadows
  whatever the YAML author believes the value is (or a typo'd key always returns the
  default).  Reported at the access site.
* **defined-but-never-accessed** — dead config: a YAML key no code path and no
  ``${...}`` interpolation ever reads.  Reported at the YAML definition site.

Accesses are resolved through attribute chains, literal ``.get``/``.pop``/``[...]``
lookups, ``(cfg.get("x") or {})`` guards, and one level of call-site propagation:
when ``f(cfg.a.b)`` passes a sub-config to a function whose parameter accesses
``.lr`` / ``.get("eps")``, those count as accesses of ``a.b.lr`` / ``a.b.eps``.
A dynamic access (non-literal key, iteration, ``**splat``) marks the whole subtree
used.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.config_index import ConfigIndex, PathTuple, build_config_index
from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import FunctionNode

_DICT_METHODS = {"get", "pop", "keys", "values", "items", "update", "setdefault", "copy", "clear", "to_dict"}
_CFG_ROOTS = {"cfg"}

#: root keys the CLI/runtime injects programmatically rather than via YAML
_RUNTIME_KEYS = {("rank",), ("world_size",), ("checkpoint", "resume_from")}


def _resolve(node: ast.AST, roots: Dict[str, PathTuple]) -> Optional[PathTuple]:
    """Dotted config path of an expression rooted at one of ``roots`` (a map of
    local name -> path prefix; the root config itself has prefix ()), or None."""
    if isinstance(node, ast.Name):
        return roots.get(node.id)
    if isinstance(node, ast.Attribute):
        base = _resolve(node.value, roots)
        if base is None or node.attr in _DICT_METHODS:
            return None
        return base + (node.attr,)
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in ("get", "pop"):
            base = _resolve(func.value, roots)
            if base is not None and node.args and isinstance(node.args[0], ast.Constant):
                key = node.args[0].value
                if isinstance(key, str):
                    return base + (key,)
        if isinstance(func, ast.Name) and func.id == "dict" and len(node.args) == 1:
            return _resolve(node.args[0], roots)  # dict(cfg.x) keeps the path
        return None
    if isinstance(node, ast.Subscript):
        base = _resolve(node.value, roots)
        if base is not None and isinstance(node.slice, ast.Constant) and isinstance(node.slice.value, str):
            return base + (node.slice.value,)
        return None
    if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) and node.values:
        return _resolve(node.values[0], roots)
    return None


class _AccessCollector(ast.NodeVisitor):
    """Records every maximal config-path access in a module (or function body).

    Local aliases of sub-configs (``wm_cfg = cfg.algo.world_model``) become new
    roots, so accesses through them resolve to full dotted paths."""

    def __init__(self, roots: Dict[str, PathTuple]):
        self.roots = dict(roots)
        self.accessed: List[Tuple[PathTuple, int, int]] = []  # (path, line, col)
        self.assigned: Set[PathTuple] = set()  # cfg.x = ... programmatic definitions

    def visit_Assign(self, node: ast.Assign) -> None:
        alias = _resolve(node.value, self.roots)
        if alias and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            self.roots[node.targets[0].id] = alias
        self.generic_visit(node)

    def _try(self, node: ast.AST) -> bool:
        path = _resolve(node, self.roots)
        if path:
            self.accessed.append((path, node.lineno, node.col_offset))
            # keep walking non-path children (e.g. the default of .get(k, <expr>))
            if isinstance(node, ast.Call):
                for a in node.args[1:]:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
            return True
        return False

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Store):
            path = _resolve(node, self.roots)
            if path:
                self.assigned.add(path)
                return
        if not self._try(node):
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if not self._try(node):
            self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if not self._try(node):
            self.generic_visit(node)


def _param_accesses(tree: ast.AST) -> Dict[str, Dict[object, List[PathTuple]]]:
    """function name -> {param position and name -> relative paths accessed on it}."""
    out: Dict[str, Dict[object, List[PathTuple]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in (*node.args.posonlyargs, *node.args.args)]
        per_param: Dict[object, List[PathTuple]] = {}
        for i, p in enumerate(params):
            if p in ("self", "cls") or p in _CFG_ROOTS:
                continue
            collector = _AccessCollector({p: ()})
            for stmt in node.body:
                collector.visit(stmt)
            rels = [path for path, _, _ in collector.accessed if path]
            if rels:
                per_param[i] = rels
                per_param[p] = rels
        if per_param:
            out.setdefault(node.name, {}).update(per_param)
    return out


class ConfigDrift(Rule):
    id = "JL006"
    name = "config-drift"
    scope = "project"

    def __init__(self, report_unused: bool = True):
        self.report_unused = report_unused

    def check_project(self, modules: Sequence[Module], config_dir: Optional[Path]) -> List[Finding]:
        if config_dir is None:
            config_dir = Path(__file__).resolve().parents[2] / "config" / "configs"
        if not Path(config_dir).is_dir():
            return []
        repo_root = Path(config_dir).resolve()
        for parent in repo_root.parents:
            if (parent / "pyproject.toml").is_file() or (parent / ".git").exists():
                repo_root = parent
                break
        else:
            repo_root = Path.cwd()
        index = build_config_index(Path(config_dir), root=repo_root)

        accessed: Set[PathTuple] = set(index.interp_accessed)
        assigned: Set[PathTuple] = set(_RUNTIME_KEYS)
        sites: List[Tuple[Module, PathTuple, int, int]] = []

        # pass 1: direct accesses + per-function param-relative accesses
        param_maps: Dict[str, Dict[object, List[PathTuple]]] = {}
        for module in modules:
            for name, pmap in _param_accesses(module.tree).items():
                param_maps.setdefault(name, {}).update(pmap)
        for module in modules:
            collector = _AccessCollector({r: () for r in _CFG_ROOTS})
            collector.visit(module.tree)
            assigned |= collector.assigned
            for path, line, col in collector.accessed:
                accessed.add(path)
                sites.append((module, path, line, col))
            # pass 2: call-site propagation through one level
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                pmap = param_maps.get(fname)
                if not pmap:
                    continue
                bindings: List[Tuple[object, ast.AST]] = list(enumerate(node.args))
                bindings += [(kw.arg, kw.value) for kw in node.keywords if kw.arg]
                for key, arg in bindings:
                    rels = pmap.get(key)
                    if not rels:
                        continue
                    base = _resolve(arg, {r: () for r in _CFG_ROOTS})
                    if not base:
                        continue
                    for rel in rels:
                        accessed.add(base + rel)
                        sites.append((module, base + rel, node.lineno, node.col_offset))

        findings: List[Finding] = []
        # ---------------------------------------------- accessed-but-undefined
        seen_undefined: Set[Tuple[str, PathTuple]] = set()
        for module, path, line, col in sites:
            if path in index.defined or path in assigned:
                continue
            if any(path[: i + 1] in assigned for i in range(len(path))):
                continue
            prefix = index.longest_defined_prefix(path)
            missing = path[: len(prefix) + 1]
            key = (module.path, missing)
            if key in seen_undefined:
                continue
            seen_undefined.add(key)
            dotted = ".".join(path)
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=line,
                    col=col,
                    message=f"config key '{dotted}' is accessed here but defined nowhere in the YAML "
                    "tree: a .get default silently shadows the config (or the key is a typo); "
                    "define it in YAML or drop the access",
                    detail=f"undefined:{dotted}",
                )
            )

        # ---------------------------------------------- defined-but-never-accessed
        if self.report_unused:
            used: Set[PathTuple] = set()
            all_accessed = accessed | assigned
            for d in index.defined:
                for p in all_accessed:
                    if p[: len(d)] == d or d[: len(p)] == p:
                        used.add(d)
                        break
            for d, (yaml_rel, yaml_line) in sorted(index.defined.items()):
                if d in used:
                    continue
                parent = d[:-1]
                if parent and parent in index.defined and parent not in used:
                    continue  # the subtree root is already reported; skip its children
                dotted = ".".join(d)
                findings.append(
                    Finding(
                        rule=self.id,
                        path=yaml_rel,
                        line=yaml_line,
                        col=0,
                        message=f"config key '{dotted}' is defined here but never accessed by any "
                        "code path or ${...} interpolation: dead config (delete it, or wire it up)",
                        detail=f"unused:{dotted}",
                    )
                )
        return findings
