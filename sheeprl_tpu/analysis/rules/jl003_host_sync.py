"""JL003 — implicit host syncs in hot loops.

``x.item()``, ``float(x)``, ``int(x)``, ``bool(x)``, ``np.asarray(x)`` / ``np.array(x)``
on a device array block the host until the device catches up; inside a per-step
training loop that stalls the dispatch pipeline every iteration.  A value is
"device-tainted" when it flows from a call to a known-jitted callable (see
``common.build_jit_index``), from ``jax.device_put``, or from a ``jax.numpy`` op;
``jax.device_get`` / ``jax.block_until_ready`` are *explicit* syncs and clear the
taint (one deliberate sync beats many hidden ones).
"""

from __future__ import annotations

import ast
from typing import List, Set

from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import (
    FunctionNode,
    Scope,
    build_jit_index,
    collect_aliases,
    call_qualname,
    iter_scopes,
    target_names,
    walk_scope,
)

_EXPLICIT_SYNCS = {"jax.device_get", "jax.block_until_ready", "numpy.asarray", "numpy.array"}
_SYNC_BUILTINS = {"float", "int", "bool"}
_NP_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.float32", "numpy.float64", "numpy.int32", "numpy.int64"}


class HostSyncInHotLoop(Rule):
    id = "JL003"
    name = "host-sync-in-hot-loop"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        jit_index = build_jit_index(module.tree, aliases)
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            findings.extend(self._check_scope(module, scope, aliases, jit_index))
        return findings

    def _check_scope(self, module: Module, scope: Scope, aliases, jit_index) -> List[Finding]:
        findings: List[Finding] = []
        device: Set[str] = set()
        seen: Set[tuple] = set()

        def device_producing(node: ast.AST) -> bool:
            """Does this expression yield a device value?"""
            if isinstance(node, ast.Name):
                return node.id in device
            if isinstance(node, ast.Call):
                qn = call_qualname(node, aliases)
                if qn in _EXPLICIT_SYNCS:
                    return False
                if qn is not None and (qn.startswith("jax.numpy.") or qn == "jax.device_put"):
                    return True
                if jit_index.is_jitted_callee(node.func):
                    return True
                if isinstance(node.func, ast.Attribute):
                    # method call: taint follows the receiver (env.step(device_action)
                    # returns host values; device_array.sum() stays on device)
                    return device_producing(node.func.value)
                return any(device_producing(a) for a in [*node.args, *[kw.value for kw in node.keywords]])
            if isinstance(node, FunctionNode):
                return False
            return any(device_producing(c) for c in ast.iter_child_nodes(node))

        def flag(node: ast.AST, call_desc: str) -> None:
            key = (node.lineno, call_desc)
            if key in seen:
                return
            seen.add(key)
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"implicit host sync '{call_desc}' on a device array inside a hot loop; "
                    "batch the transfer with one jax.device_get outside the step, or keep the "
                    "value on device",
                    detail=f"{scope.name}:{call_desc}",
                )
            )

        def check_sync_calls(node: ast.AST, in_loop: bool) -> None:
            for n in [node, *walk_scope(node)]:
                if not isinstance(n, ast.Call) or not in_loop:
                    continue
                qn = call_qualname(n, aliases)
                arg0 = n.args[0] if n.args else None
                if isinstance(n.func, ast.Attribute) and n.func.attr == "item" and device_producing(n.func.value):
                    flag(n, ".item()")
                elif (
                    isinstance(n.func, ast.Name)
                    and n.func.id in _SYNC_BUILTINS
                    and arg0 is not None
                    and device_producing(arg0)
                ):
                    flag(n, f"{n.func.id}()")
                elif qn in _NP_SYNC_CALLS and arg0 is not None and device_producing(arg0):
                    flag(n, qn.replace("numpy.", "np."))

        def handle_stmt(stmt: ast.stmt, in_loop: bool) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return
            if isinstance(stmt, ast.Assign):
                check_sync_calls(stmt.value, in_loop)
                produces = device_producing(stmt.value)
                for t in stmt.targets:
                    for name in target_names(t):
                        (device.add if produces else device.discard)(name)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                check_sync_calls(stmt.iter, in_loop)
                if device_producing(stmt.iter):
                    device.update(target_names(stmt.target))
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s, True)
                return
            if isinstance(stmt, ast.While):
                check_sync_calls(stmt.test, in_loop)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s, True)
                return
            if isinstance(stmt, (ast.If,)):
                check_sync_calls(stmt.test, in_loop)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s, in_loop)
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    check_sync_calls(item.context_expr, in_loop)
                for s in stmt.body:
                    handle_stmt(s, in_loop)
                return
            for child in ast.iter_child_nodes(stmt):
                if not isinstance(child, FunctionNode):
                    check_sync_calls(child, in_loop)

        for stmt in scope.body():
            handle_stmt(stmt, False)
        return findings
