"""JL002 — Python control flow on traced values.

Inside a function that JAX traces (``@jax.jit``, an argument to ``jax.lax.scan`` /
``cond`` / ``while_loop`` / ``fori_loop``, ``vmap``, ``grad``, ...), a Python ``if`` /
``while`` / ternary / short-circuit ``and``/``or`` / ``bool()`` on a traced value
raises ``TracerBoolConversionError`` at best and silently bakes in a constant at
worst.  Taint starts at the traced function's parameters and propagates through
assignments; static metadata (``x.shape``, ``x.dtype``, ``len(x)``...) is exempt.
"""

from __future__ import annotations

import ast
from typing import List, Set

from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import (
    FunctionNode,
    Scope,
    TRACING_TRANSFORMS,
    collect_aliases,
    call_qualname,
    expr_tainted,
    iter_scopes,
    qualname,
    target_names,
    walk_scope,
)


def _traced_function_nodes(tree: ast.AST, aliases) -> Set[ast.AST]:
    """Function nodes whose bodies run under a JAX trace."""
    traced: Set[ast.AST] = set()
    defs_by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                qn = qualname(target, aliases)
                if qn in TRACING_TRANSFORMS:
                    traced.add(node)
                elif qn in ("functools.partial", "partial") and isinstance(dec, ast.Call) and dec.args:
                    if qualname(dec.args[0], aliases) in TRACING_TRANSFORMS:
                        traced.add(node)
        elif isinstance(node, ast.Call):
            qn = call_qualname(node, aliases)
            if qn not in TRACING_TRANSFORMS:
                continue
            for arg in [*node.args, *[kw.value for kw in node.keywords]]:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    traced.add(defs_by_name[arg.id])
    return traced


class TracedControlFlow(Rule):
    id = "JL002"
    name = "traced-control-flow"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        traced = _traced_function_nodes(module.tree, aliases)
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            if scope.node in traced:
                findings.extend(self._check_traced_scope(module, scope, aliases))
        return findings

    def _check_traced_scope(self, module: Module, scope: Scope, aliases) -> List[Finding]:
        findings: List[Finding] = []
        tainted: Set[str] = set(scope.params())
        seen_lines: Set[tuple] = set()

        def flag(node: ast.AST, construct: str) -> None:
            key = (node.lineno, construct)
            if key in seen_lines:
                return
            seen_lines.add(key)
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"Python {construct} on a traced value inside traced function "
                    f"'{scope.name}'; use jax.lax.cond/select/while_loop or jnp.where instead",
                    detail=f"{scope.name}:{construct}",
                )
            )

        def check_expr(node: ast.AST) -> None:
            for n in [node, *walk_scope(node)]:
                if isinstance(n, ast.BoolOp) and expr_tainted(n, tainted, aliases):
                    flag(n, "and/or" if isinstance(n.op, ast.And) or isinstance(n.op, ast.Or) else "boolop")
                elif isinstance(n, ast.IfExp) and expr_tainted(n.test, tainted, aliases):
                    flag(n, "ternary")
                elif (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "bool"
                    and n.args
                    and expr_tainted(n.args[0], tainted, aliases)
                ):
                    flag(n, "bool()")

        def handle_stmt(stmt: ast.stmt) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return  # nested scope: traced nested functions are checked on their own
            if isinstance(stmt, ast.If):
                if expr_tainted(stmt.test, tainted, aliases):
                    flag(stmt, "if")
                check_expr(stmt.test)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s)
                return
            if isinstance(stmt, ast.While):
                if expr_tainted(stmt.test, tainted, aliases):
                    flag(stmt, "while")
                check_expr(stmt.test)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s)
                return
            if isinstance(stmt, ast.Assign):
                check_expr(stmt.value)
                if expr_tainted(stmt.value, tainted, aliases):
                    for t in stmt.targets:
                        tainted.update(target_names(t))
                else:
                    for t in stmt.targets:
                        for name in target_names(t):
                            tainted.discard(name)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                check_expr(stmt.iter)
                if expr_tainted(stmt.iter, tainted, aliases):
                    tainted.update(target_names(stmt.target))
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s)
                return
            for child in ast.iter_child_nodes(stmt):
                if not isinstance(child, FunctionNode):
                    check_expr(child)

        for stmt in scope.body():
            handle_stmt(stmt)
        return findings
