"""JL001 — PRNG key reuse.

A JAX PRNG key is single-use: consuming the same key in two ``jax.random.*`` calls
(samplers *or* ``split``) without re-deriving it in between silently correlates the
two draws.  We flag, per function scope:

* a key name consumed twice in statement order with no intervening rebind;
* a key consumed inside a loop body whose name is never rebound in that loop
  (every iteration re-consumes the same key).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import (
    Scope,
    collect_aliases,
    call_qualname,
    enclosing_loops,
    iter_scopes,
    stmt_assigned_names,
    target_names,
    walk_scope,
)

_NON_CONSUMING = {"PRNGKey", "key", "key_data", "wrap_key_data", "key_impl"}


def _terminates(stmts) -> bool:
    """Does this branch always leave the enclosing block (return/raise/break/continue)?"""
    return bool(stmts) and isinstance(stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _consumed_key_name(call: ast.Call, aliases) -> str | None:
    """Name of the key variable this jax.random call consumes, if statically known."""
    qn = call_qualname(call, aliases)
    if not qn or not qn.startswith("jax.random."):
        return None
    fn = qn.rsplit(".", 1)[-1]
    if fn in _NON_CONSUMING:
        return None
    key_arg = call.args[0] if call.args else None
    if key_arg is None:
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
    return key_arg.id if isinstance(key_arg, ast.Name) else None


class PRNGKeyReuse(Rule):
    id = "JL001"
    name = "prng-key-reuse"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            findings.extend(self._check_scope(module, scope, aliases))
        return findings

    # ------------------------------------------------------------- linear scan
    def _check_scope(self, module: Module, scope: Scope, aliases) -> List[Finding]:
        findings: List[Finding] = []
        consumed: Dict[str, int] = {}  # key name -> line of first consumption
        flagged: set = set()

        def flag(name: str, node: ast.AST, why: str) -> None:
            key = (name, node.lineno)
            if key in flagged:
                return
            flagged.add(key)
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"PRNG key '{name}' {why}; split it (e.g. "
                    f"'{name}, subkey = jax.random.split({name})') before reuse",
                    detail=f"{scope.name}:{name}",
                )
            )

        def handle_expr(node: ast.AST) -> None:
            for n in walk_scope(node) if not isinstance(node, ast.Call) else [node, *walk_scope(node)]:
                if isinstance(n, ast.Call):
                    name = _consumed_key_name(n, aliases)
                    if name is None:
                        continue
                    if name in consumed:
                        flag(name, n, f"already consumed at line {consumed[name]} with no intervening split/rebind")
                    else:
                        consumed[name] = n.lineno

        def handle_stmt(stmt: ast.stmt) -> None:
            if isinstance(stmt, ast.Assign):
                handle_expr(stmt.value)
                for t in stmt.targets:
                    for name in target_names(t):
                        consumed.pop(name, None)
                return
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    handle_expr(stmt.value)
                for name in target_names(stmt.target):
                    consumed.pop(name, None)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                handle_expr(stmt.iter)
                for name in target_names(stmt.target):
                    consumed.pop(name, None)
                saved = dict(consumed)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s)
                # conservative join: a rebind inside the loop may or may not run
                for k, v in saved.items():
                    consumed.setdefault(k, v)
                return
            if isinstance(stmt, (ast.If, ast.While)):
                # Branches are exclusive: process each from the same base state, then
                # join (union of consumptions from branches that can fall through).
                handle_expr(stmt.test)
                base = dict(consumed)
                for s in stmt.body:
                    handle_stmt(s)
                body_out = dict(consumed)
                consumed.clear()
                consumed.update(base)
                for s in stmt.orelse:
                    handle_stmt(s)
                orelse_out = dict(consumed)
                consumed.clear()
                consumed.update(base)
                if not _terminates(stmt.body):
                    for k, v in body_out.items():
                        consumed.setdefault(k, v)
                if not _terminates(stmt.orelse):
                    for k, v in orelse_out.items():
                        consumed.setdefault(k, v)
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    handle_expr(item.context_expr)
                for s in stmt.body:
                    handle_stmt(s)
                return
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return  # nested scopes are checked separately
            for child in ast.iter_child_nodes(stmt):
                handle_expr(child)

        for stmt in scope.body():
            handle_stmt(stmt)

        # ------------------------------------------------- loop-carried reuse
        for loop, inner in enclosing_loops(scope.body()):
            rebound = set()
            for n in inner:
                rebound |= stmt_assigned_names(n) if isinstance(n, ast.stmt) else set()
            for n in inner:
                if isinstance(n, ast.Call):
                    name = _consumed_key_name(n, aliases)
                    if name is not None and name not in rebound:
                        flag(name, n, "is consumed every loop iteration but never rebound in the loop")
        return findings
