"""JL005 — donated-buffer use-after-donation.

``jax.jit(f, donate_argnums=(0,))`` hands the argument's device buffer to XLA; any
later read of the donated array raises ``RuntimeError: invalid buffer`` — but only on
backends that actually donate (TPU/GPU), so CPU tests pass and the TPU run dies.  We
track calls through known donating wrappers and flag reads of a donated name before
it is rebound — including the implicit next-iteration read when the donating call
sits in a loop that never rebinds the name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from sheeprl_tpu.analysis.engine import Finding, Module, Rule
from sheeprl_tpu.analysis.rules.common import (
    Scope,
    build_jit_index,
    collect_aliases,
    enclosing_loops,
    iter_scopes,
    stmt_assigned_names,
    target_names,
    walk_scope,
)


def _donated_names(call: ast.Call, spec: Dict[str, tuple]) -> List[str]:
    nums = {n for n in spec.get("donate_argnums", ()) if isinstance(n, int)}
    names = set(spec.get("donate_argnames", ()))
    out = []
    for i, a in enumerate(call.args):
        if i in nums and isinstance(a, ast.Name):
            out.append(a.id)
    for kw in call.keywords:
        if kw.arg in names and isinstance(kw.value, ast.Name):
            out.append(kw.value.id)
    return out


class UseAfterDonation(Rule):
    id = "JL005"
    name = "use-after-donation"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        jit_index = build_jit_index(module.tree, aliases)
        if not any(
            any(spec.get("donate_argnums") or spec.get("donate_argnames") for spec in (jit_index.specs.get(n),) if spec)
            for n in [*jit_index.names, *jit_index.attrs]
        ):
            return []
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            findings.extend(self._check_scope(module, scope, aliases, jit_index))
        return findings

    def _donating_call(self, node: ast.AST, jit_index) -> List[str]:
        if not isinstance(node, ast.Call):
            return []
        callee = jit_index.is_jitted_callee(node.func)
        if callee is None:
            return []
        spec = jit_index.specs.get(callee)
        if not spec or not (spec.get("donate_argnums") or spec.get("donate_argnames")):
            return []
        return _donated_names(node, spec)

    def _check_scope(self, module: Module, scope: Scope, aliases, jit_index) -> List[Finding]:
        findings: List[Finding] = []
        donated: Dict[str, int] = {}  # name -> line of donation
        seen: Set[tuple] = set()

        def flag(name: str, node: ast.AST, why: str) -> None:
            key = (name, node.lineno)
            if key in seen:
                return
            seen.add(key)
            findings.append(
                Finding(
                    rule=self.id,
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"'{name}' {why}: its device buffer is invalid after donation "
                    "(fails on TPU/GPU even though CPU runs pass); rebind the result "
                    f"(e.g. '{name} = f({name})') or drop the donation",
                    detail=f"{scope.name}:{name}",
                )
            )

        def handle_expr(node: ast.AST) -> None:
            for n in [node, *walk_scope(node)]:
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and n.id in donated:
                    flag(n.id, n, f"is read after being donated at line {donated[n.id]}")
            for n in [node, *walk_scope(node)]:
                for name in self._donating_call(n, jit_index):
                    donated[name] = n.lineno

        def handle_stmt(stmt: ast.stmt) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return
            if isinstance(stmt, ast.Assign):
                handle_expr(stmt.value)
                for t in stmt.targets:
                    for name in target_names(t):
                        donated.pop(name, None)
                return
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    handle_expr(stmt.value)
                for name in target_names(stmt.target):
                    donated.pop(name, None)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While, ast.If)):
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    handle_expr(stmt.iter)
                    for name in target_names(stmt.target):
                        donated.pop(name, None)
                else:
                    handle_expr(stmt.test)
                for s in stmt.body + stmt.orelse:
                    handle_stmt(s)
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    handle_expr(item.context_expr)
                for s in stmt.body:
                    handle_stmt(s)
                return
            for child in ast.iter_child_nodes(stmt):
                handle_expr(child)

        for stmt in scope.body():
            handle_stmt(stmt)

        # loop-carried: donating call in a loop that never rebinds the donated name
        for loop, inner in enclosing_loops(scope.body()):
            rebound: Set[str] = set()
            for n in inner:
                if isinstance(n, ast.stmt):
                    rebound |= stmt_assigned_names(n)
            for n in inner:
                for name in self._donating_call(n, jit_index):
                    if name not in rebound:
                        flag(name, n, "is donated every loop iteration but never rebound in the loop")
        return findings
