"""JL007 — donated binding reused by a CALLER of a donating wrapper.

JL005 flags reads after a direct ``jax.jit(..., donate_argnums=...)`` call; the
bug class that actually bit this repo hides one call deeper: a plain python
function (or method) *forwards one of its parameters into a donated argument
position* — ``FusedRingDispatcher.dispatch`` and the Anakin engine's dispatch
programs all have this shape — so every caller's binding is donated too, and a
caller that keeps using its pre-call reference crashes on TPU/GPU only (the
flight recorder's post-dispatch re-staging exists precisely to dance around
this).  This rule derives the set of *donating wrappers* (a fixpoint: wrappers
calling wrappers propagate) and runs the JL005 use-after-donation scope check
against them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.engine import Finding, Module
from sheeprl_tpu.analysis.rules.common import (
    JitIndex,
    build_jit_index,
    collect_aliases,
    iter_scopes,
    target_names,
)
from sheeprl_tpu.analysis.rules.jl005_donation import UseAfterDonation


def _donated_positions(call: ast.Call, spec: Dict[str, tuple], params: List[str]) -> Set[str]:
    """Parameter names of the ENCLOSING function that this call donates."""
    nums = {n for n in spec.get("donate_argnums", ()) if isinstance(n, int)}
    names = set(spec.get("donate_argnames", ()))
    out: Set[str] = set()
    for i, a in enumerate(call.args):
        if i in nums and isinstance(a, ast.Name) and a.id in params:
            out.add(a.id)
    for kw in call.keywords:
        if kw.arg in names and isinstance(kw.value, ast.Name) and kw.value.id in params:
            out.add(kw.value.id)
    return out


def _methods_of_classes(tree: ast.AST) -> Set[str]:
    """Names of functions defined directly inside a class body (callers reach
    them through an attribute with the instance bound, shifting positions by 1)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.add(stmt.name)
    return out


def derive_wrapper_index(tree: ast.AST, aliases, base: JitIndex) -> JitIndex:
    """A :class:`JitIndex` of plain functions/methods that FORWARD a parameter
    into a donated argument of a known donating callable — from the caller's
    perspective these functions donate that argument position themselves."""
    derived = JitIndex()
    methods = _methods_of_classes(tree)

    def donating_spec(name: str) -> Optional[Dict[str, tuple]]:
        for idx in (base, derived):
            if name in idx.names or name in idx.attrs:
                spec = idx.specs.get(name)
                if spec and (spec.get("donate_argnums") or spec.get("donate_argnames")):
                    return spec
        return None

    def scan_function(scope) -> Optional[Tuple[tuple, tuple]]:
        params = scope.params()
        donated: Set[str] = set()
        rebound: Set[str] = set()

        def handle(node: ast.AST) -> None:
            # statement-ordered walk: a param rebound before the donating call no
            # longer aliases the caller's buffer.
            if isinstance(node, ast.Call):
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    callee = node.func.attr
                spec = donating_spec(callee) if callee else None
                if spec is not None:
                    live = [p for p in params if p not in rebound]
                    donated.update(_donated_positions(node, spec, live))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                handle(child)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    rebound.update(target_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                rebound.update(target_names(node.target))

        for stmt in scope.body():
            handle(stmt)
        if not donated:
            return None
        is_method = scope.name in methods and params and params[0] in ("self", "cls")
        caller_params = params[1:] if is_method else params
        nums = tuple(i for i, p in enumerate(caller_params) if p in donated)
        names = tuple(p for p in caller_params if p in donated)
        return nums, names

    # fixpoint: wrappers that call wrappers donate transitively
    for _ in range(3):
        before = (len(derived.names), len(derived.attrs))
        for scope in iter_scopes(tree):
            if not isinstance(scope.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = scope.name
            if name in base.names or name in base.attrs:
                continue  # directly jitted: JL005's territory
            got = scan_function(scope)
            if got is None:
                continue
            nums, names = got
            spec = {"donate_argnums": nums, "donate_argnames": names}
            if name in _methods_of_classes(tree):
                derived.attrs.add(name)
            else:
                derived.names.add(name)
            derived.specs[name] = spec
        if (len(derived.names), len(derived.attrs)) == before:
            break
    return derived


class DonatedBindingReuse(UseAfterDonation):
    id = "JL007"
    name = "donated-binding-reuse"

    def check_module(self, module: Module) -> List[Finding]:
        aliases = collect_aliases(module.tree)
        base = build_jit_index(module.tree, aliases)
        if not any(
            spec.get("donate_argnums") or spec.get("donate_argnames") for spec in base.specs.values()
        ):
            return []
        derived = derive_wrapper_index(module.tree, aliases, base)
        if not derived.names and not derived.attrs:
            return []
        findings: List[Finding] = []
        for scope in iter_scopes(module.tree):
            findings.extend(self._check_scope(module, scope, aliases, derived))
        return findings
