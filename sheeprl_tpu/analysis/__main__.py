"""``python -m sheeprl_tpu.analysis [paths...]`` — the jaxlint CLI.

Exit status: 0 when no findings survive the baseline, 1 otherwise, 2 on usage errors.

    python -m sheeprl_tpu.analysis sheeprl_tpu/               # lint against jaxlint.baseline
    python -m sheeprl_tpu.analysis --no-baseline sheeprl_tpu/ # everything, baseline ignored
    python -m sheeprl_tpu.analysis --write-baseline sheeprl_tpu/  # accept current findings
    python -m sheeprl_tpu.analysis --select JL006 sheeprl_tpu/    # one rule only
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from sheeprl_tpu.analysis.engine import load_baseline, run_lint, write_baseline
from sheeprl_tpu.analysis.rules import default_rules

DEFAULT_BASELINE = "jaxlint.baseline"


def _default_config_dir() -> Optional[Path]:
    p = Path(__file__).resolve().parents[1] / "config" / "configs"
    return p if p.is_dir() else None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sheeprl_tpu.analysis",
        description="jaxlint: JAX-aware static analysis (rules JL001-JL007) for sheeprl-tpu.",
    )
    parser.add_argument("paths", nargs="*", default=["sheeprl_tpu"], help="files or directories to lint")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE, help="baseline file of accepted fingerprints")
    parser.add_argument("--no-baseline", action="store_true", help="ignore the baseline entirely")
    parser.add_argument(
        "--write-baseline", action="store_true", help="write all current findings to the baseline and exit 0"
    )
    parser.add_argument("--select", default=None, help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--config-dir", default=None, help="YAML config tree for JL006 (default: the package's config/configs)"
    )
    parser.add_argument("--root", default=".", help="directory paths are reported relative to")
    parser.add_argument("-q", "--quiet", action="store_true", help="suppress the summary line")
    args = parser.parse_args(argv)

    try:
        rules = default_rules(args.select.split(",")) if args.select else default_rules()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    config_dir = Path(args.config_dir) if args.config_dir else _default_config_dir()
    baseline = None if (args.no_baseline or args.write_baseline) else load_baseline(args.baseline)

    findings = run_lint(args.paths, rules=rules, config_dir=config_dir, baseline=baseline, root=args.root)

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        if not args.quiet:
            print(f"jaxlint: wrote {len(findings)} fingerprint(s) to {args.baseline}")
        return 0

    for f in findings:
        print(f.render())
    if not args.quiet:
        n_base = len(baseline) if baseline else 0
        status = "ok" if not findings else f"{len(findings)} finding(s)"
        print(f"jaxlint: {status} ({n_base} baselined)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
