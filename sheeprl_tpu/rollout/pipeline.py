"""``PipelinedPlayer``: overlap policy inference, the host↔device tunnel and env
stepping (Podracer/Sebulba decoupling, PAPERS.md arXiv:2104.06272).

The round-5 profile split the acting floor into ~150 ms/iter of env stepping and
~125 ms/iter of player dispatch + action ``device_get`` RTT, serialized.  The
player removes the serialization:

* ``pipeline_depth=0`` — synchronous: dispatch the policy, fetch, step.  This is
  bit-for-bit today's acting path (the parity tests assert it) and the default.
* ``pipeline_depth=k>=1`` — *policy-lag* mode: each ``act`` call dispatches the
  policy jit on the newest observation and returns the action of the dispatch
  made ``k`` calls ago, whose device→host copy was started at dispatch time
  (``copy_to_host_async``) and completed while the workers were stepping.  The
  device therefore computes action *t+1* while the env pool executes step *t*,
  and the host never blocks on the tunnel.  The action applied at step *t* was
  computed from obs *t−k*: an explicit, opt-in policy lag (off-policy algos
  tolerate it; on-policy losses see slightly stale log-probs — see
  ``howto/async_rollout.md``).  While the pipeline fills, the first ``k`` steps
  replay the initial action.

The policy contract keeps all algorithm state in the caller's closure:
``policy(*args) -> device_tree`` (called at dispatch time — recurrent state
threads through device futures without blocking), and
``postprocess(host_tree) -> (env_actions, payload)`` converts the fetched tree
on the host (argmax, clipping, ...).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Optional, Tuple

import jax

from sheeprl_tpu.obs.tracer import span


def _default_postprocess(fetched: Any) -> Tuple[Any, Any]:
    return fetched, None


def _start_host_copy(tree: Any) -> None:
    """Begin the device→host copy early so the later ``device_get`` is a wait,
    not a round trip (no-op for committed/numpy arrays)."""
    for leaf in jax.tree.leaves(tree):
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # non-addressable shards etc. — device_get still works
                pass


class PipelinedPlayer:
    def __init__(
        self,
        envs: Any,
        policy: Callable[..., Any],
        postprocess: Optional[Callable[[Any], Tuple[Any, Any]]] = None,
        depth: int = 0,
    ):
        if depth < 0:
            raise ValueError(f"pipeline_depth must be >= 0, got {depth}")
        self.envs = envs
        self.depth = int(depth)
        self._policy = policy
        self._post = postprocess or _default_postprocess
        self._queue: deque = deque()

    # ------------------------------------------------------------------ acting
    def act(self, *args: Any, **kwargs: Any) -> Tuple[Any, Any]:
        """Dispatch the policy; return ``(env_actions, payload)`` — the current
        dispatch's result at depth 0, a ``depth``-lagged one otherwise."""
        with span("Rollout/policy_dispatch"):
            fut = self._policy(*args, **kwargs)
        if self.depth == 0:
            with span("Rollout/action_fetch"):
                return self._post(jax.device_get(fut))
        _start_host_copy(fut)
        self._queue.append(fut)
        if len(self._queue) > self.depth:
            fut = self._queue.popleft()
        else:
            # Pipeline still filling: replay the oldest dispatch's action (it
            # stays queued, so the lag ramps up to ``depth`` over the first calls).
            fut = self._queue[0]
        with span("Rollout/action_fetch"):
            return self._post(jax.device_get(fut))

    def env_step(self, actions: Any):
        """Step the vector env.  With ``depth>=1`` the device is computing the
        next action concurrently — the overlap needs no extra bookkeeping here."""
        with span("Rollout/env_step"):
            return self.envs.step(actions)

    def step(self, *args: Any, **kwargs: Any):
        """Combined ``act`` + ``env_step`` for loops without work between them."""
        env_actions, payload = self.act(*args, **kwargs)
        transition = self.env_step(env_actions)
        return env_actions, payload, transition

    def reset_pipeline(self) -> None:
        """Drop queued dispatches (e.g. when the caller rebuilds its env state)."""
        self._queue.clear()
