"""Shard the EnvPool worker budget across Sebulba actor processes.

A thread-decoupled run owns the whole host, so ``env.pool.num_workers`` (or its
cpu-count default) is a per-*host* budget.  Under Sebulba that same config is
executed by ``num_actors`` separate processes on one host; if each actor took the
full budget the host would oversubscribe by ``num_actors``x and the pool's
heartbeat watchdog starts reaping workers that are merely starved.  Each actor
therefore takes a disjoint ``1/num_actors`` slice of the budget, remainder going
to the lowest actor ids so the total is preserved.

Stdlib-only: imported by actor processes before JAX is configured.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def shard_worker_count(num_workers: Optional[int], num_actors: int, actor_id: int) -> Optional[int]:
    """Return this actor's slice of a host-wide worker budget (>=1), or ``None``
    to keep the pool's own default when no explicit budget was configured."""
    if num_actors <= 1:
        return num_workers
    if not (0 <= actor_id < num_actors):
        raise ValueError(f"actor_id {actor_id} out of range for num_actors {num_actors}")
    if num_workers is None:
        # Pool default is min(num_envs, cpu_count); shard the cpu budget instead
        # so co-located actors do not each claim every core.
        num_workers = max(os.cpu_count() or 1, 1)
    base, extra = divmod(int(num_workers), num_actors)
    return max(1, base + (1 if actor_id < extra else 0))


def shard_pool_cfg(cfg: Any, num_actors: int, actor_id: int) -> None:
    """Rewrite ``cfg.env.pool.num_workers`` in place to this actor's shard.
    No-op when the pool is disabled or the run is single-actor."""
    pool_cfg = cfg.env.get("pool") or {}
    if not pool_cfg.get("enabled", False) or num_actors <= 1:
        return
    cfg.env.pool.num_workers = shard_worker_count(pool_cfg.get("num_workers"), num_actors, actor_id)
