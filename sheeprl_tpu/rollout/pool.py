"""``EnvPool``: a shared-memory, multi-process, fault-tolerant vector env.

The gap it closes (PROFILE_r05 §1): ``gym.vector.SyncVectorEnv`` steps envs
serially on the host thread and ``AsyncVectorEnv`` pays a pickle round-trip per
step; at DreamerV3 walker shapes that is ~150 ms/iter of single-core MuJoCo+GL
while the device sits idle.  ``EnvPool`` runs one worker process per env
*group*, all groups stepping concurrently, with obs/reward/done slabs in shared
memory (``shared.py``) so the per-step host cost is a pipe ack and a memcpy.

Semantics are a drop-in for the existing
``SyncVectorEnv(..., autoreset_mode=SAME_STEP)`` path (``utils/env.py``):
identical batched obs layout, float64 rewards, ``final_obs``/``final_info``
payloads merged through the same ``VectorEnv._add_info`` aggregation, and
identical seeding (``reset(seed=s)`` seeds env ``i`` with ``s + i``) — the
tier-1 parity tests assert bit-equality against ``SyncVectorEnv``.

Robustness layer:

* **step timeout** — a worker that does not ack within ``step_timeout_s`` is
  declared hung, killed and restarted;
* **heartbeat watchdog** — each worker stamps a shared timestamp from a daemon
  thread; a stale stamp (dead process) is detected even between commands;
* **automatic restart** — a replacement worker is forked, its envs rebuilt and
  reseeded deterministically (base seed + a generation offset), and the
  affected envs surface the boundary as ``truncated=True`` with
  ``info["rollout_restart"]`` (the ``RestartOnException`` convention, so every
  training loop's ordinary done path marks the episode boundary);
* **restart budget** — more than ``max_restarts`` restarts over the pool's
  lifetime raises ``RolloutAbortError`` after a clean teardown.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence

import gymnasium as gym
import numpy as np
from gymnasium.vector import AutoresetMode, VectorEnv
from gymnasium.vector.utils import batch_space

from sheeprl_tpu.obs import flight_recorder
from sheeprl_tpu.obs.tracer import span
from sheeprl_tpu.rollout.shared import RolloutSlabs
from sheeprl_tpu.rollout.worker import worker_entry


class RolloutAbortError(RuntimeError):
    """Raised when the worker-restart budget is exhausted: the env fleet is
    persistently failing and continuing would silently corrupt training data."""


class _WorkerTimeout(Exception):
    pass


class _WorkerCrashed(Exception):
    pass


class _Worker:
    """Parent-side handle: process + pipe + env-index range + restart generation."""

    __slots__ = ("idx", "first", "env_fns", "proc", "conn", "generation", "failed", "restarts", "timeouts", "crashes")

    def __init__(self, idx: int, first: int, env_fns: Sequence[Callable]):
        self.idx = idx
        self.first = first
        self.env_fns = list(env_fns)
        self.proc: Optional[mp.Process] = None
        self.conn = None
        self.generation = 0
        self.failed = False
        # Per-worker fault ledger, quoted in the RolloutAbortError post-mortem.
        self.restarts = 0
        self.timeouts = 0
        self.crashes = 0

    @property
    def num_envs(self) -> int:
        return len(self.env_fns)

    @property
    def env_indices(self) -> range:
        return range(self.first, self.first + len(self.env_fns))


# Deterministic reseed offset per restart generation (prime, so overlapping
# worker seed ranges don't re-collide after a restart).
_RESEED_STRIDE = 7919


class EnvPool(VectorEnv):
    def __init__(
        self,
        env_fns: Sequence[Callable[[], gym.Env]],
        num_workers: Optional[int] = None,
        step_timeout_s: float = 60.0,
        heartbeat_interval_s: float = 2.0,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.5,
        start_method: Optional[str] = None,
        autoreset_mode: AutoresetMode = AutoresetMode.SAME_STEP,
        observation_space: Optional[gym.Space] = None,
        action_space: Optional[gym.Space] = None,
    ):
        super().__init__()
        if autoreset_mode != AutoresetMode.SAME_STEP:
            raise ValueError(f"EnvPool implements SAME_STEP autoreset only, got {autoreset_mode}")
        if not env_fns:
            raise ValueError("EnvPool needs at least one env_fn")
        self.env_fns = list(env_fns)
        self.num_envs = len(self.env_fns)
        self.autoreset_mode = autoreset_mode
        self.step_timeout_s = float(step_timeout_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)

        start_method = start_method or "fork"
        if start_method != "fork":
            # Thunks are closures (make_env) — only fork can ship them to workers.
            raise ValueError(
                f"EnvPool requires the 'fork' start method (env thunks are closures); got {start_method!r}"
            )
        self._ctx = mp.get_context(start_method)

        if observation_space is None or action_space is None:
            # Probe one env for the spaces/metadata, AsyncVectorEnv-style.
            probe = self.env_fns[0]()
            observation_space = observation_space or probe.observation_space
            action_space = action_space or probe.action_space
            self.metadata = dict(getattr(probe, "metadata", {}) or {})
            self.spec = getattr(probe, "spec", None)
            probe.close()
        self.single_observation_space = observation_space
        self.single_action_space = action_space
        self.observation_space = batch_space(observation_space, self.num_envs)
        self.action_space = batch_space(action_space, self.num_envs)
        self.metadata = {**getattr(self, "metadata", {}), "autoreset_mode": autoreset_mode}

        cpus = os.cpu_count() or 1
        if num_workers is None:
            num_workers = min(self.num_envs, max(cpus, 1))
        num_workers = max(1, min(int(num_workers), self.num_envs))
        self.num_workers = num_workers

        # Contiguous groups, sizes differing by at most one.
        base, extra = divmod(self.num_envs, num_workers)
        self._workers: List[_Worker] = []
        first = 0
        for w in range(num_workers):
            n = base + (1 if w < extra else 0)
            self._workers.append(_Worker(w, first, self.env_fns[first : first + n]))
            first += n

        self._slabs = RolloutSlabs(self.single_observation_space, self.single_action_space, self.num_envs, num_workers)
        self._views = self._slabs.views()
        self._env_seeds: List[Optional[int]] = [None] * self.num_envs
        self._reset_options: Optional[dict] = None
        self._step_pending = False
        self.closed = False

        # Rollout/* counters, surfaced by ``rollout_metrics``.
        self._total_restarts = 0
        self._timeout_restarts = 0
        self._crash_restarts = 0
        self._step_count = 0

        for w in self._workers:
            self._spawn(w)
        for w in self._workers:
            try:
                self._collect(w, self.step_timeout_s, expect="ready")
            except (_WorkerTimeout, _WorkerCrashed) as e:
                self.close(terminate=True)
                raise RuntimeError(f"EnvPool worker {w.idx} failed to start: {e}") from e

    # ------------------------------------------------------------------ process mgmt
    def _spawn(self, w: _Worker) -> None:
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        w.conn = parent_conn
        w.failed = False
        w.proc = self._ctx.Process(
            target=worker_entry,
            args=(w.idx, w.first, w.env_fns, self._slabs, child_conn, self.heartbeat_interval_s, w.generation),
            name=f"envpool-worker-{w.idx}-gen{w.generation}",
            daemon=True,
        )
        w.proc.start()
        child_conn.close()

    def _kill(self, w: _Worker) -> None:
        if w.proc is not None and w.proc.is_alive():
            w.proc.terminate()
            w.proc.join(timeout=2.0)
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(timeout=2.0)
        if w.conn is not None:
            try:
                w.conn.close()
            except Exception:
                pass
        w.conn = None
        w.proc = None

    def _send(self, w: _Worker, msg: tuple) -> None:
        try:
            w.conn.send(msg)
        except (BrokenPipeError, OSError) as e:
            raise _WorkerCrashed(f"worker {w.idx} pipe broken on send: {e}")

    def _collect(self, w: _Worker, timeout_s: float, expect: str = "ok"):
        """Wait for a worker ack, policing the timeout and process liveness."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise _WorkerTimeout(f"worker {w.idx} exceeded {timeout_s:.1f}s step timeout")
            if w.conn.poll(min(remaining, 0.2)):
                try:
                    msg = w.conn.recv()
                except (EOFError, OSError) as e:
                    raise _WorkerCrashed(f"worker {w.idx} pipe closed: {e}")
                if msg[0] == "error":
                    raise _WorkerCrashed(f"worker {w.idx} raised:\n{msg[1]}")
                if msg[0] != expect:
                    raise _WorkerCrashed(f"worker {w.idx} protocol violation: got {msg[0]!r}, wanted {expect!r}")
                return msg[1]
            if w.proc is None or not w.proc.is_alive():
                # Drain a final message that may have been sent before death.
                if w.conn.poll(0):
                    continue
                code = None if w.proc is None else w.proc.exitcode
                raise _WorkerCrashed(f"worker {w.idx} died (exitcode={code})")

    def _abort_post_mortem(self) -> str:
        """Per-worker fault ledger for the RolloutAbortError message: WHY the budget
        ran out, without the operator having to dig through metrics or event logs."""
        ages = self.heartbeat_ages()
        rows = []
        for w in self._workers:
            age = ages[w.idx] if w.idx < len(ages) else float("inf")
            age_s = f"{age:.1f}s" if np.isfinite(age) else "never"
            rows.append(
                f"worker {w.idx}: restarts={w.restarts} timeouts={w.timeouts} "
                f"crashes={w.crashes} last_heartbeat={age_s} ago"
            )
        return (
            f"totals: restarts={self._total_restarts} timeouts={self._timeout_restarts} "
            f"crashes={self._crash_restarts} over {self._step_count} steps; " + "; ".join(rows)
        )

    def heartbeat_ages(self) -> np.ndarray:
        """Seconds since each worker's last heartbeat stamp (inf before first beat)."""
        stamps = np.array(self._views.heartbeats, dtype=np.float64)
        now = time.time()
        ages = np.where(stamps > 0, now - stamps, np.inf)
        return ages

    # ------------------------------------------------------------------ restart
    def _worker_seeds(self, w: _Worker) -> List[Optional[int]]:
        offset = w.generation * _RESEED_STRIDE
        return [None if s is None else s + offset for s in (self._env_seeds[i] for i in w.env_indices)]

    def _restart(self, w: _Worker, reason: str) -> None:
        """Kill + replace a failed worker; its envs come back freshly reset with
        generation-offset seeds.  Raises ``RolloutAbortError`` past the budget."""
        with span("Rollout/restart"):
            while True:
                self._total_restarts += 1
                flight_recorder.record_event(
                    "rollout_restart",
                    worker=w.idx,
                    reason=reason,
                    restart=self._total_restarts,
                    budget=self.max_restarts,
                )
                if self._total_restarts > self.max_restarts:
                    post_mortem = self._abort_post_mortem()
                    self.close(terminate=True)
                    flight_recorder.record_event(
                        "rollout_abort", worker=w.idx, reason=reason, restarts=self._total_restarts
                    )
                    raise RolloutAbortError(
                        f"EnvPool exceeded max_restarts={self.max_restarts} "
                        f"(last failure: worker {w.idx}: {reason}); {post_mortem}"
                    )
                w.restarts += 1
                warnings.warn(f"EnvPool restarting worker {w.idx} ({reason}); restart {self._total_restarts}/{self.max_restarts}")
                self._kill(w)
                if self.restart_backoff_s > 0:
                    time.sleep(self.restart_backoff_s)
                w.generation += 1
                self._spawn(w)
                try:
                    self._collect(w, self.step_timeout_s, expect="ready")
                    self._send(w, ("reset", self._worker_seeds(w), self._reset_options))
                    self._collect(w, self.step_timeout_s)
                    w.failed = False
                    return
                except (_WorkerTimeout, _WorkerCrashed) as e:
                    reason = f"replacement failed: {e}"

    # ------------------------------------------------------------------ VectorEnv API
    def reset(self, *, seed=None, options=None):
        if seed is None:
            seeds: List[Optional[int]] = [None] * self.num_envs
        elif isinstance(seed, int):
            seeds = [seed + i for i in range(self.num_envs)]
        else:
            seeds = list(seed)
            if len(seeds) != self.num_envs:
                raise ValueError(f"got {len(seeds)} seeds for {self.num_envs} envs")
        self._env_seeds = seeds
        self._reset_options = dict(options) if options else None
        self._step_pending = False

        with span("Rollout/reset"):
            for w in self._workers:
                try:
                    self._send(w, ("reset", self._worker_seeds(w), self._reset_options))
                except _WorkerCrashed as e:
                    w.failed = True
                    self._restart(w, str(e))  # restart includes the reset
            payloads = self._gather(command="reset")
        infos = self._merge_infos(payloads)
        return self._views.read_obs_batch(), infos

    def step_async(self, actions) -> None:
        if self._step_pending:
            raise RuntimeError("step_async called with a step already pending")
        self._views.write_actions(actions)
        self._step_pending = True
        for w in self._workers:
            try:
                self._send(w, ("step",))
            except _WorkerCrashed:
                w.failed = True  # handled in step_wait

    def step_wait(self):
        if not self._step_pending:
            raise RuntimeError("step_wait called without step_async")
        with span("Rollout/step_wait"):
            payloads = self._gather(command="step")
        self._step_pending = False
        self._step_count += 1
        infos = self._merge_infos(payloads)
        return (
            self._views.read_obs_batch(),
            np.array(self._views.rewards, dtype=np.float64),
            np.array(self._views.terminated, dtype=np.bool_),
            np.array(self._views.truncated, dtype=np.bool_),
            infos,
        )

    def step(self, actions):
        with span("Rollout/step"):
            self.step_async(actions)
            return self.step_wait()

    def _gather(self, command: str) -> Dict[int, List[dict]]:
        """Collect all worker acks; on a hung/crashed worker, restart it and
        fabricate a truncated boundary for its envs."""
        per_env: Dict[int, List[dict]] = {}
        # Shared wall-clock start: workers run concurrently, so each gets the
        # full step budget measured from dispatch, not from its turn in the loop.
        deadline = time.monotonic() + self.step_timeout_s
        for w in self._workers:
            failure: Optional[str] = None
            if w.failed:
                failure = "pipe broken at dispatch"
            else:
                try:
                    payloads = self._collect(w, max(deadline - time.monotonic(), 0.01))
                    for gi, entries in payloads:
                        per_env[gi] = entries
                    continue
                except _WorkerTimeout as e:
                    self._timeout_restarts += 1
                    w.timeouts += 1
                    failure = str(e)
                    flight_recorder.record_event("rollout_timeout", worker=w.idx, error=failure)
                except _WorkerCrashed as e:
                    self._crash_restarts += 1
                    w.crashes += 1
                    failure = str(e)
                    flight_recorder.record_event("rollout_crash", worker=w.idx, error=failure)
            self._restart(w, failure)
            # The replacement reset its envs and wrote fresh obs to the slab;
            # surface the break as a truncation (RestartOnException convention).
            for gi in w.env_indices:
                self._views.rewards[gi] = 0.0
                self._views.terminated[gi] = False
                self._views.truncated[gi] = command == "step"
                per_env[gi] = [{"rollout_restart": True}]
        return per_env

    def _merge_infos(self, per_env: Dict[int, List[dict]]) -> dict:
        infos: dict = {}
        for gi in range(self.num_envs):
            for entry in per_env.get(gi, ()):
                infos = self._add_info(infos, entry, gi)
        return infos

    # ------------------------------------------------------------------ teardown
    def close_extras(self, terminate: bool = False, **kwargs) -> None:
        for w in self._workers:
            if w.proc is None:
                continue
            if terminate or w.failed or not w.proc.is_alive():
                self._kill(w)
                continue
            try:
                # A pending step's ack may still be in flight; drain it first.
                if self._step_pending and w.conn.poll(self.step_timeout_s):
                    w.conn.recv()
                self._send(w, ("close",))
                self._collect(w, timeout_s=5.0)
            except (_WorkerTimeout, _WorkerCrashed):
                pass
            finally:
                self._kill(w)
        self._step_pending = False

    def close(self, **kwargs) -> None:
        if getattr(self, "closed", True):
            return
        self.closed = True
        self.close_extras(**kwargs)

    def __del__(self):
        try:
            self.close(terminate=True)
        except Exception:
            pass

    # ------------------------------------------------------------------ telemetry
    @property
    def total_restarts(self) -> int:
        return self._total_restarts

    def rollout_metrics(self) -> Dict[str, float]:
        ages = self.heartbeat_ages()
        finite = ages[np.isfinite(ages)]
        return {
            "Rollout/worker_restarts": float(self._total_restarts),
            "Rollout/worker_timeouts": float(self._timeout_restarts),
            "Rollout/worker_crashes": float(self._crash_restarts),
            "Rollout/env_steps": float(self._step_count),
            "Rollout/num_workers": float(self.num_workers),
            "Rollout/heartbeat_age_max": float(finite.max()) if finite.size else 0.0,
        }


def rollout_metrics(envs: Any) -> Dict[str, float]:
    """``Rollout/*`` counters from a vector env, ``{}`` when it is not an EnvPool —
    lets every algo loop merge pool telemetry with one unconditional line."""
    fn = getattr(envs, "rollout_metrics", None)
    return fn() if callable(fn) else {}
