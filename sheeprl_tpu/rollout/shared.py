"""Preallocated shared-memory slabs for the rollout engine.

One flat ``multiprocessing.RawArray`` per batched leaf (obs key / rewards /
terminated / truncated / actions / heartbeats), allocated once by the parent and
inherited by the worker processes at fork time.  Per step the workers write their
env-group slice in place and the parent reads it back — no per-step pickling of
observations or rewards crosses the pipe (only episode-boundary payloads do, and
those are rare by construction).

Layouts mirror gymnasium's vector conventions exactly
(``create_empty_array(single_space, n)``), so a slab view is bit-compatible with
what ``SyncVectorEnv`` would have concatenated: ``Dict`` spaces become a dict of
``[num_envs, *leaf_shape]`` arrays, flat spaces a single batched array, rewards
are float64 and the done flags are bools.
"""

from __future__ import annotations

import multiprocessing as mp
from typing import Any, Dict, Tuple, Union

import gymnasium as gym
import numpy as np
from gymnasium.vector.utils import create_empty_array

SlabView = Union[np.ndarray, Dict[str, np.ndarray]]


def _alloc_raw(shape: Tuple[int, ...], dtype: np.dtype):
    """RawArray (no lock: each worker owns a disjoint slice) sized for shape/dtype."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return mp.RawArray("b", max(nbytes, 1))


def _view(raw, shape: Tuple[int, ...], dtype: np.dtype) -> np.ndarray:
    return np.frombuffer(raw, dtype=dtype, count=int(np.prod(shape, dtype=np.int64))).reshape(shape)


class SharedSlab:
    """A single space's batched shared buffer: raw storage + numpy view factory."""

    def __init__(self, space: gym.Space, num_envs: int):
        template = create_empty_array(space, n=num_envs, fn=np.zeros)
        if isinstance(template, dict):
            self._spec = {k: (v.shape, v.dtype) for k, v in template.items()}
            self._raw = {k: _alloc_raw(v.shape, v.dtype) for k, v in template.items()}
        elif isinstance(template, np.ndarray):
            self._spec = (template.shape, template.dtype)
            self._raw = _alloc_raw(template.shape, template.dtype)
        else:
            raise TypeError(
                f"EnvPool supports Dict[str, Box]/Box/Discrete/MultiDiscrete/MultiBinary "
                f"spaces; got a batched template of type {type(template).__name__} for {space}"
            )

    def view(self) -> SlabView:
        """Rebuild the numpy view; call from each process (views don't cross fork)."""
        if isinstance(self._spec, dict):
            return {k: _view(self._raw[k], *self._spec[k]) for k in self._spec}
        return _view(self._raw, *self._spec)


class RolloutSlabs:
    """The full per-pool slab set.  Constructed in the parent before workers fork;
    each process calls ``views()`` to get its own numpy windows over the same pages."""

    def __init__(self, observation_space: gym.Space, action_space: gym.Space, num_envs: int, num_workers: int):
        self.obs = SharedSlab(observation_space, num_envs)
        self.actions = SharedSlab(action_space, num_envs)
        self._rewards = _alloc_raw((num_envs,), np.float64)
        self._terminated = _alloc_raw((num_envs,), np.bool_)
        self._truncated = _alloc_raw((num_envs,), np.bool_)
        self._heartbeats = _alloc_raw((num_workers,), np.float64)
        self._num_envs = num_envs
        self._num_workers = num_workers

    def views(self) -> "SlabViews":
        return SlabViews(
            obs=self.obs.view(),
            actions=self.actions.view(),
            rewards=_view(self._rewards, (self._num_envs,), np.float64),
            terminated=_view(self._terminated, (self._num_envs,), np.bool_),
            truncated=_view(self._truncated, (self._num_envs,), np.bool_),
            heartbeats=_view(self._heartbeats, (self._num_workers,), np.float64),
        )


class SlabViews:
    __slots__ = ("obs", "actions", "rewards", "terminated", "truncated", "heartbeats")

    def __init__(self, obs, actions, rewards, terminated, truncated, heartbeats):
        self.obs = obs
        self.actions = actions
        self.rewards = rewards
        self.terminated = terminated
        self.truncated = truncated
        self.heartbeats = heartbeats

    # ------------------------------------------------------------------ helpers
    def write_obs(self, env_idx: int, obs: Any) -> None:
        if isinstance(self.obs, dict):
            for k, slab in self.obs.items():
                slab[env_idx] = np.asarray(obs[k])
        else:
            self.obs[env_idx] = np.asarray(obs)

    def read_obs_batch(self) -> SlabView:
        """A snapshot copy of the batched observation (callers keep obs across steps)."""
        if isinstance(self.obs, dict):
            return {k: v.copy() for k, v in self.obs.items()}
        return self.obs.copy()

    def read_action(self, env_idx: int) -> Any:
        act = self.actions[env_idx] if not isinstance(self.actions, dict) else {
            k: v[env_idx] for k, v in self.actions.items()
        }
        # Row views alias the slab; hand the env its own copy.
        if isinstance(act, np.ndarray):
            return np.array(act)
        if isinstance(act, dict):
            return {k: np.array(v) for k, v in act.items()}
        return act

    def write_actions(self, actions: Any) -> None:
        if isinstance(self.actions, dict):
            for k, slab in self.actions.items():
                slab[:] = np.asarray(actions[k])
        else:
            self.actions[:] = np.asarray(actions).reshape(self.actions.shape)
