"""Worker-process loop for ``EnvPool``.

Each worker owns one contiguous group of environments.  The protocol over its
duplex pipe is command/ack:

    ("reset", seeds, options) -> ("ok", [(env_idx, [info, ...]), ...])
    ("step",)                 -> ("ok", [(env_idx, [info, ...]), ...])
    ("close",)                -> ("ok", None)

Observations, rewards and done flags never ride the pipe: the worker writes them
into its slice of the shared slabs (``shared.py``) and the ack only carries the
*info* payloads — empty for an ordinary step, the ``{"final_obs", "final_info"}``
pair plus the reset info on an episode boundary, exactly the dicts
``SyncVectorEnv`` would feed ``_add_info`` in ``SAME_STEP`` autoreset mode, in
the same per-env order.

This module must stay importable without JAX: it runs in forked children that
never touch a device.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, List, Optional, Sequence, Tuple

InfoPayload = List[Tuple[int, List[dict]]]


def _start_heartbeat(heartbeats, worker_idx: int, interval_s: float) -> None:
    """Daemon thread stamping wall-clock time: a stale stamp means the *process*
    died (crash/OOM/kill) — a hung env keeps beating and is caught by the parent's
    step timeout instead."""

    def beat() -> None:
        while True:
            heartbeats[worker_idx] = time.time()
            time.sleep(interval_s)

    threading.Thread(target=beat, name=f"envpool-heartbeat-{worker_idx}", daemon=True).start()


def worker_entry(
    worker_idx: int,
    first_env_idx: int,
    env_fns: Sequence[Callable[[], Any]],
    slabs,
    conn,
    heartbeat_interval_s: float,
    generation: int = 0,
) -> None:
    # Chaos harness hook (stdlib-only module): the worker-fault spec set in the
    # parent BEFORE the fork rides into this process; the poll below is a no-op
    # (one global load) unless a fault is scheduled for this worker+generation.
    from sheeprl_tpu.fault import chaos as _chaos

    envs: List[Any] = []
    step_count = 0
    try:
        views = slabs.views()
        _start_heartbeat(views.heartbeats, worker_idx, max(heartbeat_interval_s, 0.05))
        envs = [fn() for fn in env_fns]
        conn.send(("ready", None))
        while True:
            msg = conn.recv()
            cmd = msg[0]
            if cmd == "reset":
                _, seeds, options = msg
                payloads: InfoPayload = []
                for j, env in enumerate(envs):
                    gi = first_env_idx + j
                    obs, info = env.reset(seed=seeds[j], options=options)
                    views.write_obs(gi, obs)
                    views.rewards[gi] = 0.0
                    views.terminated[gi] = False
                    views.truncated[gi] = False
                    payloads.append((gi, [info] if info else []))
                conn.send(("ok", payloads))
            elif cmd == "step":
                step_count += 1
                _chaos.maybe_worker_fault(worker_idx, generation, step_count)
                payloads = []
                for j, env in enumerate(envs):
                    gi = first_env_idx + j
                    action = views.read_action(gi)
                    obs, reward, terminated, truncated, info = env.step(action)
                    entries: List[dict] = []
                    if terminated or truncated:
                        # SAME_STEP autoreset: surface the pre-reset obs/info, then
                        # reset immediately (gymnasium SyncVectorEnv.step parity).
                        entries.append({"final_obs": obs, "final_info": info})
                        obs, info = env.reset()
                    if info:
                        entries.append(info)
                    views.write_obs(gi, obs)
                    views.rewards[gi] = reward
                    views.terminated[gi] = bool(terminated)
                    views.truncated[gi] = bool(truncated)
                    payloads.append((gi, entries))
                conn.send(("ok", payloads))
            elif cmd == "close":
                for env in envs:
                    env.close()
                envs = []
                conn.send(("ok", None))
                return
            else:  # pragma: no cover - protocol bug guard
                conn.send(("error", f"unknown command {cmd!r}"))
                return
    except (EOFError, KeyboardInterrupt):  # parent went away: die quietly
        return
    except Exception:
        try:
            conn.send(("error", traceback.format_exc()))
        except Exception:
            pass
    finally:
        for env in envs:
            try:
                env.close()
            except Exception:
                pass
        try:
            conn.close()
        except Exception:
            pass
