"""Asynchronous, fault-tolerant environment execution engine.

* ``EnvPool`` — shared-memory multi-process vector env, drop-in for the
  ``SyncVectorEnv(SAME_STEP)`` path behind ``cfg.env.pool.enabled``;
* ``PipelinedPlayer`` — overlaps policy dispatch, the action ``device_get`` and
  env stepping (``cfg.rollout.pipeline_depth``);
* ``rollout_metrics`` — ``Rollout/*`` counters for the metric flush;
* ``RolloutAbortError`` — raised when the worker-restart budget is exhausted.

``EnvPool`` itself never imports JAX (its workers must stay device-free);
``PipelinedPlayer`` does, so it is re-exported lazily via ``__getattr__``.
"""

from sheeprl_tpu.rollout.pool import EnvPool, RolloutAbortError, rollout_metrics

__all__ = ["EnvPool", "PipelinedPlayer", "RolloutAbortError", "rollout_metrics"]


def __getattr__(name: str):
    if name == "PipelinedPlayer":
        from sheeprl_tpu.rollout.pipeline import PipelinedPlayer

        return PipelinedPlayer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
