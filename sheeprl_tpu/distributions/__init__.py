"""Distributions as lightweight JAX containers.

The reference uses ``torch.distributions`` subclasses
(``/root/reference/sheeprl/utils/distribution.py``); on TPU these become plain classes
holding logits/params, with ``log_prob`` / ``entropy`` / ``sample`` / ``mode`` as pure
jnp functions — created and consumed entirely inside a jitted trace, so there is nothing
to register as a pytree.

Provided (reference line cites):

* ``Normal``, ``Independent`` — standard building blocks.
* ``TanhNormal`` — tanh-squashed Gaussian with log-det correction (SAC actor,
  reference ``algos/sac/agent.py:57-…``).
* ``TruncatedNormal`` — ``distribution.py:116``.
* ``Categorical`` / ``OneHotCategorical`` / ``OneHotCategoricalStraightThrough`` —
  ``distribution.py:281,387``; straight-through gradients via ``sample + p - sg(p)``.
* ``TwoHotEncodingDistribution`` — symlog-space 255-bin two-hot, ``distribution.py:253-276``.
* ``SymlogDistribution`` — ``distribution.py:152``; ``MSEDistribution`` — ``:196``.
* ``BernoulliSafeMode`` — ``:409``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.utils import symexp, symlog, two_hot_decoder, two_hot_encoder

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


class Distribution:
    def log_prob(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        raise NotImplementedError

    @property
    def mode(self) -> jax.Array:
        raise NotImplementedError

    @property
    def mean(self) -> jax.Array:
        raise NotImplementedError

    def entropy(self) -> jax.Array:
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.loc = loc
        self.scale = scale

    def log_prob(self, x: jax.Array) -> jax.Array:
        var = self.scale**2
        return -((x - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + self.loc.shape
        return self.loc + self.scale * jax.random.normal(key, shape, dtype=self.loc.dtype)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.sample(key)

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def mean(self) -> jax.Array:
        return self.loc

    def entropy(self) -> jax.Array:
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)

    def kl_divergence(self, other: "Normal") -> jax.Array:
        # KL(self || other)
        return (
            jnp.log(other.scale / self.scale)
            + (self.scale**2 + (self.loc - other.loc) ** 2) / (2 * other.scale**2)
            - 0.5
        )


class Independent(Distribution):
    """Sum log-probs over the trailing ``reinterpreted_batch_ndims`` event dims."""

    def __init__(self, base: Distribution, reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    def _reduce(self, x: jax.Array) -> jax.Array:
        if self.ndims == 0:
            return x
        return x.sum(axis=tuple(range(-self.ndims, 0)))

    def log_prob(self, x: jax.Array) -> jax.Array:
        return self._reduce(self.base.log_prob(x))

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return self.base.sample(key, sample_shape)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.base.rsample(key)

    @property
    def mode(self) -> jax.Array:
        return self.base.mode

    @property
    def mean(self) -> jax.Array:
        return self.base.mean

    def entropy(self) -> jax.Array:
        return self._reduce(self.base.entropy())


class TanhNormal(Distribution):
    """tanh-squashed Gaussian with change-of-variables log-prob (SAC actor)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, eps: float = 1e-6):
        self.base = Normal(loc, scale)
        self.eps = eps

    def sample_and_log_prob(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        pre = self.base.sample(key)
        act = jnp.tanh(pre)
        # log det of tanh: log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x)) (stable form)
        log_det = 2.0 * (math.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))
        logp = self.base.log_prob(pre) - log_det
        return act, logp

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return jnp.tanh(self.base.sample(key, sample_shape))

    def rsample(self, key: jax.Array) -> jax.Array:
        # Reparameterised: tanh of the Normal's pathwise sample.
        return jnp.tanh(self.base.rsample(key))

    def log_prob(self, a: jax.Array) -> jax.Array:
        a = jnp.clip(a, -1 + self.eps, 1 - self.eps)
        pre = jnp.arctanh(a)
        log_det = 2.0 * (math.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))
        return self.base.log_prob(pre) - log_det

    @property
    def mode(self) -> jax.Array:
        return jnp.tanh(self.base.loc)

    @property
    def mean(self) -> jax.Array:
        return jnp.tanh(self.base.loc)

    def entropy(self) -> jax.Array:
        # H[tanh(X)] = H[X] + E[log|dtanh/dx|]; the expectation of the log-det has no
        # closed form, so approximate it at the mean (delta method) — the reference
        # falls back to a sampled estimate (torch TransformedDistribution has none).
        loc = self.base.loc
        log_det = 2.0 * (math.log(2.0) - loc - jax.nn.softplus(-2.0 * loc))
        return self.base.entropy() + log_det


class TruncatedNormal(Distribution):
    """Normal truncated to ``[low, high]`` (reference ``distribution.py:116``); sampling
    via clipped reparameterisation (the reference's ``sample_mean + clip`` behavior)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, low: float = -1.0, high: float = 1.0, eps: float = 1e-6):
        self.loc = loc
        self.scale = scale
        self.low = low
        self.high = high
        self.eps = eps

    def _clamp(self, x: jax.Array) -> jax.Array:
        clamped = jnp.clip(x, self.low + self.eps, self.high - self.eps)
        return x + jax.lax.stop_gradient(clamped - x)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + self.loc.shape
        # inverse-CDF truncated sampling
        a = (self.low - self.loc) / self.scale
        b = (self.high - self.loc) / self.scale
        cdf_a = jax.scipy.stats.norm.cdf(a)
        cdf_b = jax.scipy.stats.norm.cdf(b)
        u = jax.random.uniform(key, shape, dtype=self.loc.dtype, minval=1e-5, maxval=1 - 1e-5)
        p = cdf_a + u * (cdf_b - cdf_a)
        x = self.loc + self.scale * jax.scipy.stats.norm.ppf(p)
        return self._clamp(x)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.sample(key)

    def log_prob(self, x: jax.Array) -> jax.Array:
        a = (self.low - self.loc) / self.scale
        b = (self.high - self.loc) / self.scale
        z = jax.scipy.stats.norm.cdf(b) - jax.scipy.stats.norm.cdf(a)
        logp = Normal(self.loc, self.scale).log_prob(x) - jnp.log(z + 1e-8)
        return logp

    @property
    def mode(self) -> jax.Array:
        return jnp.clip(self.loc, self.low, self.high)

    @property
    def mean(self) -> jax.Array:
        return self.mode

    def entropy(self) -> jax.Array:
        # Exact truncated-normal entropy (reference distribution.py:64-132):
        # H = log(sqrt(2*pi*e)*scale*Z) + (a*pdf(a) - b*pdf(b)) / (2Z)
        a = (self.low - self.loc) / self.scale
        b = (self.high - self.loc) / self.scale
        phi_a = jax.scipy.stats.norm.pdf(a)
        phi_b = jax.scipy.stats.norm.pdf(b)
        z = jax.scipy.stats.norm.cdf(b) - jax.scipy.stats.norm.cdf(a)
        z = jnp.maximum(z, 1e-8)
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale) + jnp.log(z) + (a * phi_a - b * phi_b) / (2 * z)


class Categorical(Distribution):
    def __init__(self, logits: jax.Array):
        self.logits = jax.nn.log_softmax(logits, axis=-1)

    @property
    def probs(self) -> jax.Array:
        return jnp.exp(self.logits)

    def log_prob(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, x[..., None], axis=-1)[..., 0]

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1, shape=sample_shape + self.logits.shape[:-1])

    @property
    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def entropy(self) -> jax.Array:
        return -(self.probs * self.logits).sum(-1)


class OneHotCategorical(Categorical):
    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        idx = super().sample(key, sample_shape)
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.logits.dtype)

    def log_prob(self, x: jax.Array) -> jax.Array:
        return (self.logits * x).sum(-1)

    @property
    def mode(self) -> jax.Array:
        return jax.nn.one_hot(jnp.argmax(self.logits, axis=-1), self.logits.shape[-1], dtype=self.logits.dtype)


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """Sample is one-hot forward, ``probs`` gradient backward (reference
    ``distribution.py:387-401``) — the stop-gradient placement IS the algorithm."""

    def rsample(self, key: jax.Array) -> jax.Array:
        hard = self.sample(key)
        probs = self.probs
        return hard + probs - jax.lax.stop_gradient(probs)


def unimix_logits(logits: jax.Array, unimix: float = 0.01) -> jax.Array:
    """Mix 1% uniform into the categorical (DreamerV3; reference
    ``algos/dreamer_v3/agent.py:437-449``)."""
    if unimix <= 0:
        return logits
    probs = jax.nn.softmax(logits, axis=-1)
    uniform = jnp.ones_like(probs) / probs.shape[-1]
    probs = (1 - unimix) * probs + unimix * uniform
    return jnp.log(probs)


class TwoHotEncodingDistribution(Distribution):
    """Symlog-space two-hot distribution over a fixed support (reference
    ``distribution.py:222-276``).  ``logits``: ``[..., bins]``; values decode via
    symexp of the support expectation."""

    def __init__(self, logits: jax.Array, dims: int = 0, low: float = -20.0, high: float = 20.0):
        self.logits = jax.nn.log_softmax(logits, axis=-1)
        self.dims = dims
        self.low = low
        self.high = high
        self.bins = logits.shape[-1]

    @property
    def mean(self) -> jax.Array:
        probs = jnp.exp(self.logits)
        support = jnp.linspace(self.low, self.high, self.bins, dtype=self.logits.dtype)
        return symexp((probs * support).sum(-1, keepdims=True))

    @property
    def mode(self) -> jax.Array:
        return self.mean

    def log_prob(self, x: jax.Array) -> jax.Array:
        # x: [..., 1] raw-space scalar.
        target = two_hot_encoder(symlog(x), support_range=int(self.high), num_buckets=self.bins)
        lp = (target * self.logits).sum(-1, keepdims=True)
        if self.dims:
            lp = lp.sum(axis=tuple(range(-self.dims, 0)))
        return lp


class SymlogDistribution(Distribution):
    """-MSE in symlog space as a log-prob (reference ``distribution.py:152-193``)."""

    def __init__(self, loc: jax.Array, dims: int = 1, agg: str = "sum"):
        self.loc = loc
        self.dims = dims
        self.agg = agg

    @property
    def mode(self) -> jax.Array:
        return symexp(self.loc)

    @property
    def mean(self) -> jax.Array:
        return symexp(self.loc)

    def log_prob(self, x: jax.Array) -> jax.Array:
        dist = -((self.loc - symlog(x)) ** 2)
        if self.dims == 0:
            return dist
        axes = tuple(range(-self.dims, 0))
        return dist.sum(axes) if self.agg == "sum" else dist.mean(axes)


class MSEDistribution(Distribution):
    def __init__(self, loc: jax.Array, dims: int = 1, agg: str = "sum"):
        self.loc = loc
        self.dims = dims
        self.agg = agg

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def mean(self) -> jax.Array:
        return self.loc

    def log_prob(self, x: jax.Array) -> jax.Array:
        dist = -((self.loc - x) ** 2)
        if self.dims == 0:
            return dist
        axes = tuple(range(-self.dims, 0))
        return dist.sum(axes) if self.agg == "sum" else dist.mean(axes)


class Bernoulli(Distribution):
    def __init__(self, logits: jax.Array):
        self.logits = logits

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    def log_prob(self, x: jax.Array) -> jax.Array:
        return -jnp.maximum(self.logits, 0) + self.logits * x - jnp.log1p(jnp.exp(-jnp.abs(self.logits)))

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        shape = sample_shape + self.logits.shape
        return (jax.random.uniform(key, shape) < self.probs).astype(self.logits.dtype)

    def entropy(self) -> jax.Array:
        p = self.probs
        return -(p * jnp.log(p + 1e-8) + (1 - p) * jnp.log(1 - p + 1e-8))


class BernoulliSafeMode(Bernoulli):
    """Bernoulli whose mode never NaNs at p=0.5 (reference ``distribution.py:409``)."""

    @property
    def mode(self) -> jax.Array:
        return (self.probs > 0.5).astype(self.logits.dtype)


class MultiCategorical(Distribution):
    """Tuple of independent categoricals over split logits (MultiDiscrete actions)."""

    def __init__(self, logits: jax.Array, nvec: Sequence[int]):
        self.nvec = tuple(int(n) for n in nvec)
        splits = []
        offset = 0
        for n in self.nvec:
            splits.append(Categorical(logits[..., offset : offset + n]))
            offset += n
        self.dists = splits

    def log_prob(self, x: jax.Array) -> jax.Array:
        # x: [..., len(nvec)] integer actions
        return sum(d.log_prob(x[..., i]) for i, d in enumerate(self.dists))

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        keys = jax.random.split(key, len(self.dists))
        return jnp.stack([d.sample(k, sample_shape) for d, k in zip(self.dists, keys)], axis=-1)

    @property
    def mode(self) -> jax.Array:
        return jnp.stack([d.mode for d in self.dists], axis=-1)

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)
