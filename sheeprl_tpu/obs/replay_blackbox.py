"""Replay a flight-recorder black box: re-execute the failing update step on CPU.

    python -m sheeprl_tpu.obs.replay_blackbox <log_dir>/blackbox [--platform cpu]

The dump (see ``obs/flight_recorder.py``) carries the run's config, the staged batch
and train state of the last dispatched update, and a *replay target* —
``"module:function"`` registered by the algorithm via ``FlightRecorder.arm_replay``.
The target function rebuilds the algorithm's jitted update from the config + dumped
statics (spaces, action dims), restores the state through
``CheckpointManager.load`` with freshly initialised templates, re-executes the
single failing update, and returns its host-fetched outputs.  This module then
scans every floating leaf for non-finite values and reports them — deterministic
repro of a NaN blow-up without rerunning the multi-hour job.

Platform selection happens BEFORE JAX initialises a backend (the whole point is
replaying a TPU crash on a CPU dev box), so keep this module free of top-level jax
imports.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple


def _force_platform(platform: str) -> None:
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)


def load_meta(blackbox_dir: os.PathLike) -> Dict[str, Any]:
    with open(Path(blackbox_dir) / "meta.json") as f:
        return json.load(f)


def load_config(blackbox_dir: os.PathLike):
    from sheeprl_tpu.config.core import DotDict, load_config as _load

    return DotDict.wrap(_load(Path(blackbox_dir) / "config.yaml"))


def state_dir(blackbox_dir: os.PathLike) -> Path:
    return Path(blackbox_dir) / "state" / "ckpt_0"


def load_state(blackbox_dir: os.PathLike, templates: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Load the dumped step state; ``templates`` restores typed pytrees (optimizer
    NamedTuples) exactly — entries without a template come back as raw nested
    dicts/arrays, which is what batches and flax param dicts need."""
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    return CheckpointManager.load(state_dir(blackbox_dir), templates=templates)


def as_step_list(raw: Any) -> List[Any]:
    """msgpack round-trips python lists as ``{"0": ..., "1": ...}`` dicts; restore
    the per-step batch list the block dispatcher was fed."""
    if isinstance(raw, (list, tuple)):
        return list(raw)
    if isinstance(raw, dict) and raw and all(str(k).isdigit() for k in raw):
        return [raw[k] for k in sorted(raw, key=int)]
    return [raw]


def scan_nonfinite(tree: Any, label: str = "") -> List[str]:
    """Paths of every non-finite floating leaf in a host pytree."""
    import jax
    import numpy as np

    bad: List[str] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(f"{label}{jax.tree_util.keystr(path)}")
    return bad


def replay(
    blackbox_dir: os.PathLike, platform: str = "cpu", member: Optional[int] = None
) -> Tuple[Dict[str, Any], List[str]]:
    """Re-execute the dumped update step; returns ``(outputs, nonfinite_paths)``.

    ``outputs`` is whatever the replay target returns (host pytree — typically the
    update's metrics plus summary norms of the new state).  ``member`` selects a
    single population member to replay (``--member``; only replay targets that
    understand a population axis accept it — currently the Anakin engine's
    ``engine.anakin:replay_update``).
    """
    _force_platform(platform)
    meta = load_meta(blackbox_dir)
    target = meta.get("replay_target")
    if not target:
        raise SystemExit(
            f"blackbox at {blackbox_dir} has no replay target (algo={meta.get('algo')!r}): "
            "the state was dumped for forensics but this algorithm did not register a "
            "replay builder."
        )
    if not meta.get("staged_state"):
        raise SystemExit(
            f"blackbox at {blackbox_dir} has no staged step state — the crash happened "
            "before the first update was dispatched."
        )
    cfg = load_config(blackbox_dir)
    # The dump's mesh config may describe the crashed run's accelerator topology;
    # replay runs on whatever this host has.
    mesh = dict(cfg.get("mesh") or {})
    mesh.update({"devices": None, "data": -1, "model": 1})
    mesh.pop("distributed", None)
    cfg["mesh"] = mesh

    import importlib

    mod_name, _, fn_name = target.rpartition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    if member is not None:
        import inspect

        if "member" not in inspect.signature(fn).parameters:
            raise SystemExit(
                f"--member is not supported by this dump's replay target ({target}): "
                "single-member replay exists for population Anakin dumps only."
            )
        outputs = fn(cfg, Path(blackbox_dir), member=int(member))
    else:
        outputs = fn(cfg, Path(blackbox_dir))
    return outputs, scan_nonfinite(outputs)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    parser.add_argument("blackbox_dir", help="<log_dir>/blackbox directory of a crashed run")
    parser.add_argument("--platform", default="cpu", help="JAX platform to replay on (default: cpu)")
    parser.add_argument("--json", action="store_true", help="emit a JSON report instead of text")
    parser.add_argument(
        "--member",
        type=int,
        default=None,
        help="population Anakin dumps: replay only this member's slice of the staged "
        "carry through the plain single-member program (howto/population.md)",
    )
    args = parser.parse_args(argv)

    meta = load_meta(args.blackbox_dir)
    outputs, nonfinite = replay(args.blackbox_dir, platform=args.platform, member=args.member)

    if args.json:
        import numpy as np

        flat = {}
        import jax

        for path, leaf in jax.tree_util.tree_flatten_with_path(outputs)[0]:
            arr = np.asarray(leaf)
            flat[jax.tree_util.keystr(path)] = float(arr.reshape(-1)[0]) if arr.size == 1 else arr.shape
        print(json.dumps({"algo": meta.get("algo"), "nonfinite": nonfinite, "outputs": {k: str(v) for k, v in flat.items()}}))
    else:
        print(f"replayed {meta.get('algo')!r} update from {args.blackbox_dir}")
        exc = meta.get("exception") or {}
        if exc:
            print(f"original failure: {exc.get('type')}: {exc.get('message')}")
        if nonfinite:
            print(f"NON-FINITE REPRODUCED in {len(nonfinite)} output leaf/leaves:")
            for path in nonfinite:
                print(f"  {path}")
        else:
            print("update output is finite — the failure did not reproduce from the dumped state")
    return 0


if __name__ == "__main__":
    sys.exit(main())
