"""Unified training observability: span tracer, XProf integration, device telemetry.

Layers (bottom-up):

* ``tracer``    — hierarchical span tracer (context manager + decorator), Chrome-trace/
                  Perfetto JSON export, per-span latency histograms;
* ``telemetry`` — ``Memory/*`` gauges from ``Device.memory_stats()`` with a host-RSS
                  fallback on CPU backends;
* ``watchdog``  — ``Compile/*`` counters + loud warnings on post-warmup recompiles;
* ``monitor``   — ``TrainingMonitor``, the per-algorithm facade tying it together and
                  driving ``jax.profiler`` step annotations / capture windows.

Import note: ``utils.timer`` imports ``obs.tracer`` at module load so every existing
``with timer(...)`` block doubles as a span — nothing in this package may import
``utils.timer``, and JAX is only imported lazily inside methods.
"""

from sheeprl_tpu.obs.monitor import TrainingMonitor
from sheeprl_tpu.obs.telemetry import DeviceTelemetry
from sheeprl_tpu.obs.tracer import SpanTracer, get_active, set_active, span, trace_span
from sheeprl_tpu.obs.watchdog import RecompileWarning, RecompileWatchdog

__all__ = [
    "TrainingMonitor",
    "DeviceTelemetry",
    "SpanTracer",
    "RecompileWarning",
    "RecompileWatchdog",
    "get_active",
    "set_active",
    "span",
    "trace_span",
]
