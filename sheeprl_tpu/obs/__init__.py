"""Unified training observability: span tracer, XProf integration, device telemetry,
in-jit health diagnostics, flight recorder.

Layers (bottom-up):

* ``tracer``          — hierarchical span tracer (context manager + decorator),
                        Chrome-trace/Perfetto JSON export, per-span latency histograms;
* ``telemetry``       — ``Memory/*`` gauges from ``Device.memory_stats()`` with a
                        host-RSS fallback on CPU backends;
* ``watchdog``        — ``Compile/*`` counters + loud warnings on post-warmup
                        recompiles;
* ``health``          — ``Health/*`` training-health diagnostics computed INSIDE the
                        jitted updates (grad/param/update norms, finite fraction,
                        entropy/critic stats, replay staleness);
* ``flight_recorder`` — bounded ring of structured events + staged batch/train-state,
                        dumped to ``<log_dir>/blackbox/`` on crash;
* ``replay_blackbox`` — ``python -m sheeprl_tpu.obs.replay_blackbox``: re-execute a
                        dumped update step on CPU for deterministic repro;
* ``monitor``         — ``TrainingMonitor``, the per-algorithm facade tying it
                        together and driving ``jax.profiler`` step annotations /
                        capture windows;
* ``fleet``           — cross-process telemetry plane: per-process ``FleetExporter``
                        pushing tagged metric rows over the Sebulba transport to a
                        launcher-hosted ``FleetAggregator`` (merged timeline JSONL,
                        live snapshot, merged Perfetto trace, fleet blackbox);
* ``top``             — ``python -m sheeprl_tpu.obs.top``: live per-process fleet
                        status table rendered from the aggregator snapshot.

Import note: ``utils.timer`` imports ``obs.tracer`` at module load so every existing
``with timer(...)`` block doubles as a span — nothing in this package may import
``utils.timer`` at module load, and JAX is only imported lazily inside methods
(``flight_recorder`` is stdlib-only until a dump actually happens).
"""

from sheeprl_tpu.obs import fleet, flight_recorder
from sheeprl_tpu.obs.fleet import FleetAggregator, FleetExporter, maybe_exporter
from sheeprl_tpu.obs.flight_recorder import FlightRecorder
from sheeprl_tpu.obs.health import health_metrics, replay_age_metrics
from sheeprl_tpu.obs.monitor import TrainingMonitor
from sheeprl_tpu.obs.telemetry import DeviceTelemetry
from sheeprl_tpu.obs.tracer import SpanTracer, get_active, set_active, span, trace_span
from sheeprl_tpu.obs.watchdog import RecompileWarning, RecompileWatchdog

__all__ = [
    "TrainingMonitor",
    "DeviceTelemetry",
    "FleetAggregator",
    "FleetExporter",
    "FlightRecorder",
    "SpanTracer",
    "RecompileWarning",
    "RecompileWatchdog",
    "fleet",
    "flight_recorder",
    "maybe_exporter",
    "get_active",
    "health_metrics",
    "replay_age_metrics",
    "set_active",
    "span",
    "trace_span",
]
