"""Flight recorder: a bounded in-memory ring of structured training events that dumps
a post-mortem "black box" on crash.

A long training run that dies after hours leaves (by default) nothing but a
traceback.  The recorder keeps the last few thousand structured events — span
closures, metric flushes, health snapshots, rollout worker restarts/timeouts,
recompile events, strict-mode trips — in a lock-protected ring (O(1) append, fixed
memory), and every update the training loop *stages* references to the current
batch + train state (device arrays: staging is pointer bookkeeping, no host sync).
When the run crashes (any exception escaping the algorithm entry point — see
``cli.run_algorithm`` — including strict-mode ``NonFiniteError`` /
``SignatureDriftError`` / ``RecompileError``), :func:`dump_active` writes
``<log_dir>/blackbox/``:

* ``events.jsonl``       — the last-K events, one JSON object per line;
* ``meta.json``          — exception, algo, git SHA, jax/jaxlib versions, config
  fingerprint, replay target;
* ``config.yaml``        — the run's composed config;
* ``state/ckpt_0/``      — the staged batch + train state + replay statics, written
  through ``checkpoint.manager.CheckpointManager`` (barriers disabled: a crash dump
  must never wait on peer processes).

``python -m sheeprl_tpu.obs.replay_blackbox <blackbox_dir>`` reloads the dump and
re-executes the failing update step on CPU (see ``replay_blackbox.py``) — the
record-then-inspect loop of Podracer (arXiv:2104.06272) applied to crash forensics.

Import constraints: stdlib-only at module load (``utils.timer`` → ``obs.tracer`` →
this module feeds spans; JAX and the checkpoint manager are imported lazily at dump
time only).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import traceback
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

_ACTIVE: Optional["FlightRecorder"] = None


def get_active() -> Optional["FlightRecorder"]:
    return _ACTIVE


def install(recorder: Optional["FlightRecorder"]) -> Optional["FlightRecorder"]:
    """Install ``recorder`` as the process-global flight recorder; returns the
    previous one.  ``install(None)`` clears it (``cli.run_algorithm`` does this
    after every run so recorders never leak across runs in one process)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = recorder
    return prev


def record_event(kind: str, **payload: Any) -> None:
    """Record on the active recorder; no-op (one global load) when none is armed."""
    if _ACTIVE is not None:
        _ACTIVE.record(kind, **payload)


def record_span(name: str, dur_ms: float, depth: int) -> None:
    """Span-closure hook for ``obs.tracer`` (kept separate from :func:`record_event`
    so the tracer's hot path pays exactly one global load when no recorder is on)."""
    if _ACTIVE is not None:
        _ACTIVE.record("span", name=name, dur_ms=round(float(dur_ms), 3), depth=depth)


def dump_active(reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
    """Dump the active recorder's black box; returns the dump dir or None."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.dump(reason, exc)


def _jsonable(value: Any) -> Any:
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, float):
        return value if value == value and value not in (float("inf"), float("-inf")) else repr(value)
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    item = getattr(value, "item", None)
    if callable(item) and getattr(value, "size", None) == 1:
        try:
            return _jsonable(item())
        except Exception:
            pass
    return repr(value)


def _git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def _config_fingerprint(cfg: Any) -> Optional[str]:
    import hashlib

    try:
        blob = json.dumps(_jsonable(dict(cfg)), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()
    except Exception:
        return None


class FlightRecorder:
    """Bounded, thread-safe event ring + staged-step storage + blackbox dump.

    ``capacity`` bounds ring memory; ``keep_events`` bounds the dump (the tail of
    the ring).  Thread safety matters: spans and metric flushes arrive from player/
    trainer threads in the decoupled loops, worker restarts from the EnvPool's
    watchdog path.
    """

    def __init__(
        self,
        log_dir: str,
        capacity: int = 4096,
        keep_events: int = 512,
        algo: Optional[str] = None,
        cfg: Any = None,
    ):
        self.log_dir = str(log_dir)
        self.capacity = max(int(capacity), 1)
        self.keep_events = max(int(keep_events), 1)
        self.algo = algo
        self.cfg = cfg
        self.total_recorded = 0
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._staged: Dict[str, Any] = {}
        self._statics: Dict[str, Any] = {}
        self._replay_target: Optional[str] = None
        self._staged_updates = 0
        self._dumped: Optional[str] = None

    # ------------------------------------------------------------------ events
    def record(self, kind: str, **payload: Any) -> None:
        event = {"ts": round(time.time(), 6), "kind": str(kind)}
        for k, v in payload.items():
            event[k] = _jsonable(v)
        with self._lock:
            self._events.append(event)
            self.total_recorded += 1

    def events(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
        return out if last is None else out[-last:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    # ------------------------------------------------------------------ staging
    def arm_replay(self, target: Optional[str], **statics: Any) -> None:
        """Register the dump's replay entry point (``"module:function"``) plus the
        picklable host objects it needs to rebuild the update (spaces, action dims,
        block cadence).  Call once per run; later calls merge ``statics``."""
        if target is not None:
            self._replay_target = str(target)
        self._statics.update(statics)

    def stage_step(self, **entries: Any) -> None:
        """Stage the current update's inputs (device-array references + host
        scalars).  No host sync, no copy: the arrays are fetched only if the run
        crashes.  Replaces the previous stage, so at most one extra reference to
        the previous params/batch is ever kept alive."""
        self._staged = dict(entries)
        self._staged_updates += 1

    @property
    def staged_updates(self) -> int:
        return self._staged_updates

    # ------------------------------------------------------------------ dump
    def dump(self, reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
        """Write the black box.  First dump wins (a crash can unwind through several
        layers that each try); every failure inside the dump path degrades to a
        warning — the dump must never mask the original exception."""
        if self._dumped is not None:
            return self._dumped
        out_dir = os.path.join(self.log_dir, "blackbox")
        try:
            os.makedirs(out_dir, exist_ok=True)
        except OSError as e:
            warnings.warn(f"flight recorder: cannot create {out_dir}: {e}")
            return None
        self._dumped = out_dir

        rank = 0
        try:
            import jax

            rank = jax.process_index()
        except Exception:
            pass

        events_name = "events.jsonl" if rank == 0 else f"events_rank{rank}.jsonl"
        try:
            with open(os.path.join(out_dir, events_name), "w") as f:
                for event in self.events(last=self.keep_events):
                    f.write(json.dumps(event) + "\n")
        except Exception as e:
            warnings.warn(f"flight recorder: could not write events: {e}")

        staged_written = False
        if rank == 0 and (self._staged or self._statics):
            try:
                staged_written = self._dump_state(out_dir)
            except Exception as e:
                warnings.warn(f"flight recorder: could not dump staged step state: {e}")

        if rank == 0:
            try:
                self._dump_meta(out_dir, reason, exc, staged_written)
            except Exception as e:
                warnings.warn(f"flight recorder: could not write meta.json: {e}")
            try:
                if self.cfg is not None:
                    from sheeprl_tpu.config.core import save_config

                    save_config(self.cfg, os.path.join(out_dir, "config.yaml"))
            except Exception as e:
                warnings.warn(f"flight recorder: could not save config: {e}")
        return out_dir

    def _dump_state(self, out_dir: str) -> bool:
        from sheeprl_tpu.checkpoint.manager import CheckpointManager

        state: Dict[str, Any] = dict(self._staged)
        if self._statics:
            state["statics"] = dict(self._statics)
        manager = CheckpointManager(os.path.join(out_dir, "state"), keep_last=None)
        manager.save(0, state, sync=False)
        return True

    def _dump_meta(self, out_dir: str, reason: str, exc: Optional[BaseException], staged: bool) -> None:
        meta: Dict[str, Any] = {
            "reason": reason,
            "algo": self.algo,
            "time": time.time(),
            "git_sha": _git_sha(),
            "replay_target": self._replay_target,
            "staged_state": staged,
            "staged_updates": self._staged_updates,
            "events_recorded": self.total_recorded,
            "events_dumped": min(self.total_recorded, self.keep_events, self.capacity),
            "config_fingerprint": _config_fingerprint(self.cfg) if self.cfg is not None else None,
        }
        try:
            # The supervisor's classification context: how many lives this run has
            # already burned, whether a preemption signal was in flight at death.
            from sheeprl_tpu.fault.counters import fault_metrics

            meta["fault_counters"] = fault_metrics()
        except Exception:
            pass
        try:
            import jax
            import jaxlib

            meta["jax_version"] = jax.__version__
            meta["jaxlib_version"] = jaxlib.__version__
        except Exception:
            pass
        if exc is not None:
            meta["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(
                    traceback.format_exception(type(exc), exc, exc.__traceback__)
                )[-8000:],
            }
        with open(os.path.join(out_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
