"""In-jit training-health diagnostics (``Health/*`` metrics).

The failure modes that kill long runs — NaN blow-ups, silent divergence, a learning
rate that quietly stopped biting — are visible in quantities the update step already
has in registers: gradient/parameter/update norms, the update-to-parameter ratio,
the fraction of finite gradient elements, policy entropy, critic value statistics.
:func:`health_metrics` computes them *inside* the existing jitted update as one extra
scalar pytree merged into the step's metrics, so they ride the deferred-metrics path
every loop already has (``WindowedFutures``/``BlockDispatcher`` drains, or the one
``device_get`` per update in the on-policy loops) — **zero additional host syncs per
step** and a few extra reductions fused into the update program.

Per-module grouping: the top level of the grads/params/updates trees (``world_model``
/ ``actor`` / ``critic`` for the Dreamer family, ``actor`` / ``critic`` / ``alpha``
for SAC, encoder/actor/critic flax modules for PPO) becomes the metric suffix, e.g.
``Health/grad_norm/actor``.  Single-key wrappers (flax's ``{"params": ...}``) are
unwrapped first.

Gated by ``obs.health`` (default on) at **trace time**: with the flag off the jitted
program is bit-identical to the pre-health one.

Host-side replay staleness (:func:`replay_age_metrics`) reads the sample-age stats
the buffers in ``data/buffers.py`` record at sampling time — how many buffer-add
steps old the rows of the most recent batch were — surfacing stale-replay bugs
(e.g. a stuck rollout worker feeding an ever-older ring).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

_EPS = 1e-12


def health_enabled(cfg: Any) -> bool:
    """True unless ``obs.health`` is explicitly disabled (tolerates dicts/None)."""
    if cfg is None:
        return False
    try:
        obs_cfg = cfg.get("obs") if hasattr(cfg, "get") else getattr(cfg, "obs", None)
    except Exception:
        return False
    if not obs_cfg:
        return False
    try:
        return bool(obs_cfg.get("health", True) if hasattr(obs_cfg, "get") else getattr(obs_cfg, "health", True))
    except Exception:
        return False


def _top_modules(tree: Any) -> Dict[str, Any]:
    """Split a pytree into named top-level module subtrees.

    Unwraps single-key mappings (``{"params": {...}}``) so flax param dicts group by
    their real module names; non-mapping trees land under ``"all"``.
    """
    while isinstance(tree, Mapping) and len(tree) == 1:
        tree = next(iter(tree.values()))
    if isinstance(tree, Mapping) and tree:
        return {str(k): v for k, v in tree.items()}
    return {"all": tree}


def diagnostics(
    grads: Any = None,
    params: Any = None,
    updates: Any = None,
    aux: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Pure-JAX training-health scalars; call inside a jitted update.

    * ``Health/grad_norm/<module>`` / ``Health/param_norm/<module>`` /
      ``Health/update_norm/<module>`` — per-top-level-module global norms;
    * ``Health/update_ratio/<module>`` — update norm over param norm (the "effective
      step size"; collapsing toward 0 = training stalled, exploding = divergence),
      for modules present in both trees;
    * ``Health/grad_finite_frac`` — fraction of finite gradient elements (1.0 in a
      healthy run; the first number to look at in a blackbox dump);
    * ``Health/<name>`` — the mean of every entry of ``aux`` (algorithm-specific
      extras: policy entropy, Q-value/critic statistics).
    """
    import jax
    import jax.numpy as jnp
    import optax

    out: Dict[str, Any] = {}
    grad_mods = _top_modules(grads) if grads is not None else {}
    param_mods = _top_modules(params) if params is not None else {}
    update_mods = _top_modules(updates) if updates is not None else {}

    for name, g in grad_mods.items():
        out[f"Health/grad_norm/{name}"] = optax.global_norm(g)
    for name, p in param_mods.items():
        out[f"Health/param_norm/{name}"] = optax.global_norm(p)
    for name, u in update_mods.items():
        u_norm = optax.global_norm(u)
        out[f"Health/update_norm/{name}"] = u_norm
        if name in param_mods:
            out[f"Health/update_ratio/{name}"] = u_norm / (
                out.get(f"Health/param_norm/{name}", optax.global_norm(param_mods[name])) + _EPS
            )

    if grads is not None:
        leaves = [x for x in jax.tree.leaves(grads) if hasattr(x, "dtype")]
        float_leaves = [x for x in leaves if jnp.issubdtype(x.dtype, jnp.floating)]
        if float_leaves:
            total = sum(x.size for x in float_leaves)  # static
            finite = sum(jnp.isfinite(x).sum() for x in float_leaves)
            out["Health/grad_finite_frac"] = finite.astype(jnp.float32) / float(total)

    for name, value in (aux or {}).items():
        if value is None:
            continue
        v = jnp.asarray(value)
        out[f"Health/{name}"] = v if v.ndim == 0 else v.mean()
    return out


def health_metrics(
    cfg: Any,
    metrics: Dict[str, Any],
    *,
    grads: Any = None,
    params: Any = None,
    updates: Any = None,
    aux: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Merge :func:`diagnostics` into a jitted update's metrics dict.

    The ``obs.health`` gate is read at trace time, so a disabled run compiles the
    exact pre-health program.  Also applies the ``analysis.inject_nan`` fault
    injection (the flight-recorder e2e path) so a single call site per algorithm
    covers both.
    """
    from sheeprl_tpu.analysis.strict import maybe_inject_nonfinite

    if health_enabled(cfg):
        metrics = {**metrics, **diagnostics(grads=grads, params=params, updates=updates, aux=aux)}
    return maybe_inject_nonfinite(cfg, metrics)


def replay_age_metrics(rb: Any) -> Dict[str, float]:
    """``Health/replay_age_*`` staleness gauges of ``rb``'s most recent sample.

    Duck-typed: any buffer exposing ``sample_age_metrics()`` (see
    ``data/buffers.py``) contributes; everything else returns ``{}`` so on-policy
    loops and exotic buffers need no special casing.
    """
    fn = getattr(rb, "sample_age_metrics", None)
    if fn is None:
        return {}
    try:
        return dict(fn())
    except Exception:
        return {}
