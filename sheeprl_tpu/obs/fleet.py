"""Fleet telemetry plane: cross-process metric export over the Sebulba transport.

PR-13/14 made the repo multi-process (placed actor/learner topologies, supervised
serve replicas) while observability stayed single-process: every process logged to
its own TensorBoard dir and the learner summary JSON was the only cross-process
artifact.  This module is the missing plane (Podracer, arXiv 2104.06272 §4;
MindSpeed RL, arXiv 2507.19017 both stress fleet-wide queue/staleness/throughput
visibility for actor-learner dataflow systems):

* :class:`FleetExporter` — one per process.  Roles push *counters* (cumulative,
  monotonic: grad steps, env steps, bytes) and *gauges* (instantaneous: queue
  depth, staleness) into a lock-protected dict — O(dict write), no JAX, no host
  sync — and a daemon thread flushes a tagged snapshot over the framed TCP
  channel every ``obs.fleet.interval_s`` seconds.  Tags:
  ``{role, actor_id, generation, host, pid, wall_clock, trace_id, seq}``.
* :class:`FleetAggregator` — hosted by the launcher (``distributed/launcher.py``)
  or the serving supervisor.  Merges every exporter's rows into ONE
  ``<fleet_dir>/timeline.jsonl``, derives per-counter rates
  (``<name>_per_s``), and keeps a live ``snapshot.json`` that
  ``python -m sheeprl_tpu.obs.top`` renders.
* **Correlated tracing** — every process under one launcher shares a run-level
  trace id (``SHEEPRL_TPU_TRACE_ID``); at close each exporter ships its
  ``SpanTracer`` events, and the aggregator rewrites their Chrome-trace ``pid``
  to the real OS pid (process names labeled by role) so N processes merge into
  ONE Perfetto timeline: ``<fleet_dir>/trace_fleet.json``.
* **Fleet blackbox** — :meth:`FleetAggregator.collect_blackboxes` broadcasts a
  dump request; each surviving exporter replies with its flight-recorder ring
  *inline* (events are already JSON), and any on-disk ``blackbox/`` dumps from
  dead peers are copied too — one correlated ``blackbox_fleet/`` crash bundle.

A process with no aggregator to reach but ``obs.fleet.dir`` set spins up a
private in-process aggregator and exports to it over localhost — standalone
serve replicas and tests ride the exact code path the placed topology uses.

Import cost is stdlib + numpy (via the transport): the launcher hosts the
aggregator before any child touches JAX.  Telemetry must never kill training:
every send is guarded, and a dead aggregator just stops the exporter.
"""

from __future__ import annotations

import json
import os
import shutil
import socket as _socket
import tempfile
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from sheeprl_tpu.distributed.transport import Channel, ChannelClosed, FramingError, Listener, connect
from sheeprl_tpu.obs import flight_recorder as _flight_recorder

#: ``host:port`` of the fleet aggregator; set by the launcher/supervisor on every
#: child so exporters find their host without config surgery.
FLEET_ENV_VAR = "SHEEPRL_TPU_FLEET"
#: Run-level trace id shared by every process under one launcher — the join key
#: for timeline rows, merged traces, and blackbox bundles.
TRACE_ID_ENV_VAR = "SHEEPRL_TPU_TRACE_ID"

HELLO_KIND = "fleet_hello"
METRICS_KIND = "fleet_metrics"
TRACE_KIND = "fleet_trace"
BYE_KIND = "fleet_bye"
DUMP_KIND = "fleet_dump"
DUMP_DONE_KIND = "fleet_dump_done"

#: Tag schema stamped on every timeline row (tests pin it; howto/observability.md).
ROW_TAG_KEYS = ("role", "actor_id", "generation", "host", "pid", "wall_clock", "trace_id", "seq")


def new_trace_id() -> str:
    """Run-level trace id: sortable wall-clock prefix + launcher pid + entropy."""
    return f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid():x}-{os.urandom(3).hex()}"


def _fleet_cfg(cfg: Any) -> Dict[str, Any]:
    try:
        obs = cfg.get("obs") if hasattr(cfg, "get") else getattr(cfg, "obs", None)
        section = (obs or {}).get("fleet")
    except Exception:
        section = None
    return dict(section) if section else {}


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=f".{os.path.basename(path)}.", suffix=".tmp", dir=os.path.dirname(path) or ".")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


class _RateTracker:
    """Derives ``<name>_per_s`` from consecutive cumulative-counter rows."""

    def __init__(self) -> None:
        self._prev: Optional[Tuple[float, Dict[str, float]]] = None

    def derive(self, wall_clock: float, counters: Dict[str, float]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if self._prev is not None:
            t0, prev = self._prev
            dt = wall_clock - t0
            if dt > 1e-6:
                for name, value in counters.items():
                    if name in prev:
                        out[f"{name}_per_s"] = max(float(value) - float(prev[name]), 0.0) / dt
        self._prev = (wall_clock, dict(counters))
        return out


def merge_chrome_traces(streams: List[Tuple[Dict[str, Any], List[Dict[str, Any]]]]) -> Dict[str, Any]:
    """Merge per-process Chrome-trace event lists into ONE Perfetto document.

    ``streams`` is ``[(tags, traceEvents), ...]``.  The per-process tracer uses
    its *rank* as ``pid`` (every process says rank 0 locally), so the merge
    rewrites every event's ``pid`` to the real OS pid from the tags and replaces
    the ``process_name`` metadata with a role-labeled one — distinct tracks per
    process, one timeline."""
    merged: List[Dict[str, Any]] = []
    for tags, events in streams:
        pid = int(tags.get("pid", 0))
        role = str(tags.get("role", "?"))
        actor_id = tags.get("actor_id", 0)
        label = f"{role}{actor_id}" if role == "actor" else role
        merged.append(
            {"name": "process_name", "ph": "M", "pid": pid, "args": {"name": f"{label} (pid {pid})"}}
        )
        for e in events:
            if not isinstance(e, dict) or e.get("name") == "process_name":
                continue
            e = dict(e)
            e["pid"] = pid
            merged.append(e)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------- host
class FleetAggregator:
    """The launcher/supervisor-side telemetry host: accept loop + one reader per
    exporter, merged timeline JSONL, live snapshot, trace merge, blackbox bundles.

    Processes are keyed by slot (``role`` + ``actor_id``) so a respawned actor
    (new generation, new pid) *replaces* its predecessor's live row — exactly the
    launcher's slot semantics — while the timeline keeps every generation's rows.
    A slot whose channel closed and whose last row is older than
    ``liveness_timeout_s`` is evicted from the snapshot (dead-exporter eviction);
    its log dir is remembered for blackbox collection regardless."""

    MAX_BUNDLES = 3  # crash-bundle cap: a respawn loop must not fill the disk

    def __init__(
        self,
        fleet_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        liveness_timeout_s: float = 10.0,
        trace_id: Optional[str] = None,
        max_timeline_mb: float = 64.0,
    ):
        self.fleet_dir = str(fleet_dir)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.trace_id = trace_id or os.environ.get(TRACE_ID_ENV_VAR) or new_trace_id()
        self.liveness_timeout_s = float(liveness_timeout_s)
        self.rows_written = 0
        self._lock = threading.Lock()
        self._procs: Dict[str, Dict[str, Any]] = {}
        self._log_dirs: Dict[str, str] = {}  # survives eviction: blackbox sources
        self._rates: Dict[str, _RateTracker] = {}
        self._respawns: Dict[int, int] = {}
        self._traces: List[Tuple[Dict[str, Any], List[Dict[str, Any]]]] = []
        self._dump_results: List[Tuple[Dict[str, Any], List[Dict[str, Any]]]] = []
        self._dump_pending = 0
        self._dump_done = threading.Condition(self._lock)
        self._bundles = 0
        self._closed = False
        # Per-slot {generation: [first_wall_clock, last_wall_clock]} — the gaps
        # between consecutive generations are restart/drain downtime in the
        # goodput.json rollup written at close.
        self._gen_spans: Dict[str, Dict[int, List[float]]] = {}
        # Size-capped timeline: the merged JSONL rotates once past the cap
        # (timeline.jsonl -> timeline.jsonl.1), bounding disk at ~2x the cap
        # while obs.top's tail rebuild reads across the boundary.
        self.max_timeline_bytes = max(int(float(max_timeline_mb) * 1024 * 1024), 1)
        self._timeline = open(self.timeline_path, "a")
        try:
            self._timeline_bytes = os.path.getsize(self.timeline_path)
        except OSError:
            self._timeline_bytes = 0
        self._listener = Listener(host, port)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return self._listener.address

    @property
    def timeline_path(self) -> str:
        return os.path.join(self.fleet_dir, "timeline.jsonl")

    @property
    def rotated_timeline_path(self) -> str:
        return os.path.join(self.fleet_dir, "timeline.jsonl.1")

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.fleet_dir, "snapshot.json")

    @property
    def goodput_path(self) -> str:
        return os.path.join(self.fleet_dir, "goodput.json")

    # ------------------------------------------------------------------ intake
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                ch = self._listener.accept(timeout=0.5)
            except TimeoutError:
                continue
            except OSError:
                return
            threading.Thread(target=self._reader, args=(ch,), daemon=True).start()

    @staticmethod
    def _slot_key(meta: Dict[str, Any]) -> str:
        return f"{meta.get('role', '?')}{int(meta.get('actor_id', 0))}"

    def _reader(self, ch: Channel) -> None:
        key: Optional[str] = None
        clean = False
        try:
            while True:
                kind, meta, payload = ch.recv()
                if kind == HELLO_KIND:
                    key = self._register(ch, meta)
                elif kind == METRICS_KIND:
                    key = self._ingest(ch, meta, payload)
                elif kind == TRACE_KIND:
                    events = (payload or {}).get("traceEvents") or []
                    with self._lock:
                        self._traces.append((dict(meta), list(events)))
                elif kind == DUMP_DONE_KIND:
                    with self._lock:
                        self._dump_results.append((dict(meta), list((payload or {}).get("events") or [])))
                        self._dump_pending = max(self._dump_pending - 1, 0)
                        self._dump_done.notify_all()
                elif kind == BYE_KIND:
                    clean = True
                    break
        except (ChannelClosed, FramingError, OSError):
            pass
        finally:
            ch.close()
            if key is not None:
                with self._lock:
                    proc = self._procs.get(key)
                    if proc is not None and proc.get("channel") is ch:
                        proc["alive"] = False
                        proc["done"] = clean
                    # an exporter that died mid-dump must not wedge the collector
                    if self._dump_pending:
                        self._dump_pending -= 1
                        self._dump_done.notify_all()
                self._write_snapshot()

    def _register(self, ch: Channel, meta: Dict[str, Any]) -> str:
        key = self._slot_key(meta)
        tags = {k: meta.get(k) for k in ("role", "actor_id", "generation", "host", "pid", "trace_id")}
        with self._lock:
            stale = self._procs.get(key, {}).get("channel")
            self._procs[key] = {
                "tags": tags,
                "channel": ch,
                "alive": True,
                "done": False,
                "wall_clock": time.time(),
                "metrics": {},
            }
            self._rates[key] = _RateTracker()
            if meta.get("log_dir"):
                self._log_dirs[f"{key}_g{tags.get('generation', 0)}_pid{tags.get('pid', 0)}"] = str(
                    meta["log_dir"]
                )
        if stale is not None and stale is not ch:
            stale.close()
        self._write_snapshot()
        return key

    def _ingest(self, ch: Channel, meta: Dict[str, Any], payload: Any) -> str:
        key = self._slot_key(meta)
        counters = dict((payload or {}).get("counters") or {})
        gauges = dict((payload or {}).get("gauges") or {})
        wall_clock = float(meta.get("wall_clock", time.time()))
        with self._lock:
            if key not in self._procs:  # metrics before hello (shouldn't happen): register bare
                self._procs[key] = {"tags": {}, "channel": ch, "alive": True, "done": False, "metrics": {}}
                self._rates[key] = _RateTracker()
            rates = self._rates[key].derive(wall_clock, counters)
            metrics = {**counters, **gauges, **rates}
            proc = self._procs[key]
            proc["tags"] = {
                k: meta.get(k) for k in ("role", "actor_id", "generation", "host", "pid", "trace_id")
            }
            proc["wall_clock"] = wall_clock
            proc["alive"] = True
            proc["metrics"] = metrics
            gen = int(meta.get("generation", 0) or 0)
            span = self._gen_spans.setdefault(key, {}).get(gen)
            if span is None:
                self._gen_spans[key][gen] = [wall_clock, wall_clock]
            else:
                span[1] = wall_clock
            row = {k: meta.get(k) for k in ROW_TAG_KEYS}
            row["metrics"] = metrics
            line = json.dumps(row) + "\n"
            self._timeline.write(line)
            self._timeline.flush()
            self.rows_written += 1
            self._timeline_bytes += len(line)
            if self._timeline_bytes >= self.max_timeline_bytes:
                self._rotate_timeline_locked()
                self._timeline_bytes = 0
        self._write_snapshot()
        return key

    def _rotate_timeline_locked(self) -> None:
        """Roll ``timeline.jsonl`` to ``timeline.jsonl.1`` (one rotated
        generation — disk stays bounded at ~2x the cap).  Caller holds _lock."""
        try:
            self._timeline.close()
            os.replace(self.timeline_path, self.rotated_timeline_path)
        except OSError as e:  # pragma: no cover - disk trouble must not kill intake
            warnings.warn(f"fleet: could not rotate timeline: {e}")
        self._timeline = open(self.timeline_path, "a")

    # --------------------------------------------------------------- snapshot
    def note_respawn(self, actor_id: int, count: int) -> None:
        """Launcher hook: respawn counts ride the snapshot, not the exporters
        (a respawned actor cannot know how many lives its slot already burned)."""
        with self._lock:
            self._respawns[int(actor_id)] = int(count)
        self._write_snapshot()

    def snapshot(self) -> Dict[str, Any]:
        """Live fleet view; evicts slots that are dead *and* silent past the
        liveness timeout (a closed channel alone is not eviction: a respawn may
        be seconds away and ``top`` should show the gap, not a vanished row)."""
        now = time.time()
        with self._lock:
            procs: Dict[str, Any] = {}
            for key, proc in list(self._procs.items()):
                age = now - float(proc.get("wall_clock", now))
                alive = bool(proc.get("alive")) and age <= self.liveness_timeout_s
                if not proc.get("alive") and not proc.get("done") and age > self.liveness_timeout_s:
                    del self._procs[key]  # dead-exporter eviction
                    continue
                tags = dict(proc.get("tags") or {})
                row = {
                    **tags,
                    "alive": alive,
                    "done": bool(proc.get("done")),
                    "age_s": round(age, 3),
                    "wall_clock": proc.get("wall_clock"),
                    "metrics": dict(proc.get("metrics") or {}),
                }
                if tags.get("role") == "actor":
                    row["respawns"] = self._respawns.get(int(tags.get("actor_id", 0)), 0)
                procs[key] = row
            return {
                "trace_id": self.trace_id,
                "written": now,
                "liveness_timeout_s": self.liveness_timeout_s,
                "fleet_dir": self.fleet_dir,
                "processes": procs,
            }

    def _write_snapshot(self) -> None:
        try:
            _atomic_write_json(self.snapshot_path, self.snapshot())
        except OSError as e:  # pragma: no cover - disk trouble must not kill intake
            warnings.warn(f"fleet: could not write snapshot: {e}")

    # --------------------------------------------------------------- blackbox
    def collect_blackboxes(self, reason: str, timeout_s: float = 5.0) -> Optional[str]:
        """One correlated crash bundle: broadcast a dump request, gather every
        surviving peer's flight-recorder ring (replied inline — events are
        already JSON), and copy any on-disk ``blackbox/`` dumps (the dead
        child's crash dump among them) into ``<parent>/blackbox_fleet/``."""
        with self._lock:
            if self._bundles >= self.MAX_BUNDLES:
                return None
            self._bundles += 1
            bundle_n = self._bundles
            self._dump_results = []
            live = [
                (key, proc["channel"])
                for key, proc in self._procs.items()
                if proc.get("alive") and proc.get("channel") is not None
            ]
        sent = 0
        for _, ch in live:
            try:
                ch.send(DUMP_KIND, None, reason=str(reason))
                sent += 1
            except (ChannelClosed, OSError):
                pass
        with self._lock:
            self._dump_pending = sent
            deadline = time.monotonic() + timeout_s
            while self._dump_pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._dump_done.wait(timeout=remaining)
            results = list(self._dump_results)
            log_dirs = dict(self._log_dirs)

        slug = "".join(c if c.isalnum() else "_" for c in str(reason))[:48] or "event"
        bundle = os.path.join(os.path.dirname(self.fleet_dir) or ".", "blackbox_fleet", f"{bundle_n:02d}_{slug}")
        os.makedirs(bundle, exist_ok=True)
        manifest: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "reason": str(reason),
            "wall_clock": time.time(),
            "peers": [],
            "copied": [],
        }
        for meta, events in results:
            key = f"{self._slot_key(meta)}_g{meta.get('generation', 0)}_pid{meta.get('pid', 0)}"
            peer_dir = os.path.join(bundle, key)
            os.makedirs(peer_dir, exist_ok=True)
            try:
                with open(os.path.join(peer_dir, "events.jsonl"), "w") as f:
                    for event in events:
                        f.write(json.dumps(event) + "\n")
            except (OSError, TypeError, ValueError) as e:
                warnings.warn(f"fleet: could not write peer ring for {key}: {e}")
            manifest["peers"].append({"slot": key, "events": len(events)})
        for key, log_dir in log_dirs.items():
            src = os.path.join(log_dir, "blackbox")
            if not os.path.isdir(src):
                continue
            try:
                shutil.copytree(src, os.path.join(bundle, f"{key}_disk"), dirs_exist_ok=True)
                manifest["copied"].append({"slot": key, "source": src})
            except OSError as e:
                warnings.warn(f"fleet: could not copy {src}: {e}")
        _atomic_write_json(os.path.join(bundle, "manifest.json"), manifest)
        return bundle

    # ---------------------------------------------------------------- goodput
    def goodput_report(self) -> Dict[str, Any]:
        """Fleet goodput rollup: per-slot attribution + the fleet's ceiling.

        Each slot carries its last ``Perf/goodput``/``Perf/mfu`` gauges (pushed
        by the per-process :class:`~sheeprl_tpu.obs.perf.PerfPlane`), restart
        downtime derived from the gaps between its generations' timeline spans,
        and the ``perf_anomalies`` count.  The fleet section names the slot with
        the lowest goodput — the straggler capping the whole run."""
        with self._lock:
            procs = {
                key: {
                    "tags": dict(proc.get("tags") or {}),
                    "metrics": dict(proc.get("metrics") or {}),
                }
                for key, proc in self._procs.items()
            }
            spans = {key: {g: list(v) for g, v in s.items()} for key, s in self._gen_spans.items()}
        slots: Dict[str, Any] = {}
        values: List[Tuple[str, float]] = []
        for key in sorted(set(procs) | set(spans)):
            proc = procs.get(key) or {"tags": {}, "metrics": {}}
            metrics = proc["metrics"]
            goodput = metrics.get("Perf/goodput")
            slot_spans = spans.get(key) or {}
            gens = sorted(slot_spans)
            downtime = sum(
                max(0.0, slot_spans[b][0] - slot_spans[a][1]) for a, b in zip(gens, gens[1:])
            )
            slots[key] = {
                "role": proc["tags"].get("role"),
                "actor_id": proc["tags"].get("actor_id"),
                "generation": proc["tags"].get("generation"),
                "generations": len(gens) or 1,
                "goodput": goodput,
                "mfu": metrics.get("Perf/mfu"),
                "anomalies": float(metrics.get("perf_anomalies", 0.0) or 0.0),
                "restart_downtime_s": downtime,
            }
            if goodput is not None:
                values.append((key, float(goodput)))
        fleet = {
            "min_goodput": min(v for _, v in values) if values else None,
            "mean_goodput": sum(v for _, v in values) / len(values) if values else None,
            "ceiling_slot": min(values, key=lambda kv: kv[1])[0] if values else None,
            "anomalies": sum(float(s["anomalies"]) for s in slots.values()),
        }
        return {
            "trace_id": self.trace_id,
            "written": time.time(),
            "slots": slots,
            "fleet": fleet,
        }

    # ------------------------------------------------------------------ close
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            _atomic_write_json(self.goodput_path, self.goodput_report())
        except OSError as e:  # pragma: no cover
            warnings.warn(f"fleet: could not write goodput rollup: {e}")
        # Merged Perfetto timeline from every trace stream shipped at exporter
        # close: one file, one track per real pid.
        with self._lock:
            streams = list(self._traces)
        if streams:
            try:
                with open(os.path.join(self.fleet_dir, "trace_fleet.json"), "w") as f:
                    json.dump(merge_chrome_traces(streams), f)
            except OSError as e:
                warnings.warn(f"fleet: could not write merged trace: {e}")
        self._write_snapshot()
        self._listener.close()
        with self._lock:
            channels = [p.get("channel") for p in self._procs.values() if p.get("channel")]
        for ch in channels:
            ch.close()
        try:
            self._timeline.close()
        except OSError:  # pragma: no cover
            pass


# --------------------------------------------------------------------- client
_ACTIVE: Optional["FleetExporter"] = None
_ACTIVE_LOCK = threading.Lock()


def get_active() -> Optional["FleetExporter"]:
    return _ACTIVE


def close_active(error: Optional[BaseException] = None) -> None:
    """Crash-boundary hook (``cli.run_algorithm``): flush + close whatever
    exporter this process has so the aggregator learns of a death from the
    dying process itself, not just from the launcher's poll loop."""
    with _ACTIVE_LOCK:
        exporter = _ACTIVE
    if exporter is None:
        return
    if error is not None:
        exporter.gauge("crashed", 1.0)
    exporter.close()


class FleetExporter:
    """Per-process telemetry pusher.  Hot-path API (:meth:`counter`,
    :meth:`gauge`) is a dict write under a lock — safe inside a training loop,
    no JAX, asserted sync-free under ``jax.transfer_guard("disallow")`` in the
    tests.  A daemon thread owns every send."""

    def __init__(
        self,
        tags: Dict[str, Any],
        channel: Optional[Channel] = None,
        interval_s: float = 2.0,
        log_dir: Optional[str] = None,
        own_aggregator: Optional[FleetAggregator] = None,
    ):
        self.tags = dict(tags)
        self.interval_s = max(float(interval_s), 0.05)
        self._ch = channel
        self._own_aggregator = own_aggregator
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._seq = 0
        self._closed = False
        self._stop = threading.Event()
        if self._ch is not None:
            try:
                self._ch.send(HELLO_KIND, None, **self.tags, log_dir=log_dir)
            except (ChannelClosed, OSError):
                self._ch = None
        self._thread = threading.Thread(target=self._loop, name="fleet-export", daemon=True)
        self._thread.start()
        global _ACTIVE
        with _ACTIVE_LOCK:
            _ACTIVE = self

    # ---------------------------------------------------------------- hot path
    def counter(self, name: str, cumulative: Any) -> None:
        """Record a cumulative monotonic counter; the aggregator derives
        ``<name>_per_s`` between consecutive rows."""
        with self._lock:
            self._counters[str(name)] = float(cumulative)

    def gauge(self, name: str, value: Any) -> None:
        """Record an instantaneous value (latest wins within a flush window)."""
        if value is None:
            return
        with self._lock:
            self._gauges[str(name)] = float(value)

    # ------------------------------------------------------------------ export
    #: Upper bound on one blocking wait in the export thread: bounds close()
    #: latency (the thread re-checks _stop at least this often) without the old
    #: 20 wake-ups/s busy poll.
    _POLL_CAP_S = 0.5

    def _loop(self) -> None:
        # Event-signalled, not polled: the thread sleeps in select() on the
        # channel socket until inbound traffic (a dump request) or the next
        # flush deadline.  Idle cost drops from 20 wake-ups/s to ~2/s worst
        # case (and one per interval_s when a flush deadline is the limiter).
        last_flush = time.monotonic()
        while not self._stop.is_set():
            delay = max(self.interval_s - (time.monotonic() - last_flush), 0.0)
            timeout = min(delay, self._POLL_CAP_S)
            with self._lock:
                ch = self._ch
            if ch is not None and ch.closed:
                # A locally-closed channel makes poll() return False WITHOUT
                # waiting — dropping it here keeps the loop on the blocking
                # _stop.wait branch instead of a full-speed spin.
                with self._lock:
                    if self._ch is ch:
                        self._ch = None
                ch = None
            if ch is not None:
                try:
                    if ch.poll(timeout):
                        self._poll_inbound()
                except (OSError, ValueError):
                    with self._lock:
                        self._ch = None
            else:
                self._stop.wait(timeout if timeout > 0 else self._POLL_CAP_S)
            if time.monotonic() - last_flush >= self.interval_s:
                last_flush = time.monotonic()
                self.flush()

    def _poll_inbound(self) -> None:
        with self._lock:
            ch = self._ch
        if ch is None:
            return
        try:
            while ch.poll(0):
                kind, meta, _ = ch.recv()
                if kind == DUMP_KIND:
                    self._reply_dump(str(meta.get("reason", "?")))
        except (ChannelClosed, FramingError, OSError, TimeoutError):
            with self._lock:
                self._ch = None

    def _reply_dump(self, reason: str) -> None:
        recorder = _flight_recorder.get_active()
        events = recorder.events() if recorder is not None else []
        _flight_recorder.record_event("fleet_dump", reason=reason)
        ch = self._ch
        if ch is None:
            return
        try:
            ch.send(DUMP_DONE_KIND, {"events": events}, **self.tags, reason=reason)
        except (ChannelClosed, OSError, TypeError):
            pass

    def flush(self) -> bool:
        """Send one tagged metrics row (also the liveness heartbeat — an idle
        process still flushes, so its snapshot row stays fresh); returns False
        once the channel is gone."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            self._seq += 1
            seq = self._seq
        ch = self._ch
        if ch is None:
            return False
        try:
            ch.send(
                METRICS_KIND,
                {"counters": counters, "gauges": gauges},
                **self.tags,
                wall_clock=time.time(),
                seq=seq,
            )
            return True
        except (ChannelClosed, OSError):
            with self._lock:
                self._ch = None
            return False

    def close(self) -> None:
        """Final flush + trace shipment + goodbye.  Idempotent; never raises."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.flush()
        ch = self._ch
        if ch is not None:
            try:
                from sheeprl_tpu.obs import tracer as _tracer

                active = _tracer.get_active()
                if active is not None and len(active):
                    ch.send(TRACE_KIND, {"traceEvents": active.chrome_trace()["traceEvents"]}, **self.tags)
                ch.send(BYE_KIND, None, **self.tags)
            except (ChannelClosed, OSError):
                pass
            ch.close()
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None
        if self._own_aggregator is not None:
            self._own_aggregator.close()
            self._own_aggregator = None


def maybe_exporter(
    cfg: Any,
    role: str,
    actor_id: int = 0,
    generation: int = 0,
    log_dir: Optional[str] = None,
) -> Optional[FleetExporter]:
    """Build this process's exporter, or ``None`` when no plane is configured.

    Resolution order: ``SHEEPRL_TPU_FLEET`` (set by the launcher/supervisor) →
    ``obs.fleet.dir`` (standalone: a private in-process aggregator writes the
    same timeline/snapshot files) → off.  Any failure degrades to ``None`` —
    telemetry must never take down the run it observes."""
    fleet_cfg = _fleet_cfg(cfg)
    if not bool(fleet_cfg.get("enabled", True)):
        return None
    interval_s = float(fleet_cfg.get("interval_s", 2.0))
    tags = {
        "role": str(role),
        "actor_id": int(actor_id),
        "generation": int(generation),
        "host": _socket.gethostname(),
        "pid": os.getpid(),
        "trace_id": os.environ.get(TRACE_ID_ENV_VAR) or "",
    }
    addr = os.environ.get(FLEET_ENV_VAR, "")
    own: Optional[FleetAggregator] = None
    if addr:
        host, _, port = addr.rpartition(":")
        try:
            ch = connect(host or "127.0.0.1", int(port), timeout_s=5.0)
        except (ConnectionError, OSError, ValueError) as e:
            warnings.warn(f"fleet: could not reach aggregator at {addr!r}: {e}")
            return None
    elif fleet_cfg.get("dir"):
        try:
            own = FleetAggregator(
                str(fleet_cfg["dir"]),
                liveness_timeout_s=float(fleet_cfg.get("liveness_timeout_s", 10.0)),
                max_timeline_mb=float(fleet_cfg.get("max_timeline_mb", 64.0)),
            )
            if not tags["trace_id"]:
                tags["trace_id"] = own.trace_id
            ch = connect(own._listener.host, own._listener.port, timeout_s=5.0)
        except (ConnectionError, OSError) as e:
            warnings.warn(f"fleet: could not start local aggregator: {e}")
            if own is not None:
                own.close()
            return None
    else:
        return None
    return FleetExporter(tags, channel=ch, interval_s=interval_s, log_dir=log_dir, own_aggregator=own)
