"""Hierarchical span tracer: Chrome-trace/Perfetto export + per-span latency histograms.

The tracer is the measurement core of ``sheeprl_tpu.obs`` (see Podracer,
arXiv:2104.06272 §4: per-phase dataflow telemetry is what makes actor/learner
pipelines tunable).  Spans nest through a per-thread stack, so a ``with``-block
inside another ``with``-block shows up as a child slice in Perfetto; every
completed span also feeds a ``HistogramMetric`` so p50/p95/p99 latencies flow
into the existing metric/logger pipeline.

Design constraints:

* stdlib + numpy only at import time — ``utils.timer`` hooks into this module, and the
  CLI imports the timer before JAX may touch a backend;
* a module-level *active* tracer with a ``None`` fast path, so instrumentation left in
  hot loops costs one global load + ``is None`` check when observability is off;
* thread-safe — decoupled algorithms run player/trainer phases from worker threads, and
  the Chrome trace keeps per-thread tracks via ``tid``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from sheeprl_tpu.obs import flight_recorder as _flight_recorder
from sheeprl_tpu.utils.metric import HistogramMetric

# (name, ts_us, dur_us, tid, depth) — kept as a flat tuple to stay allocation-light.
_Event = Tuple[str, float, float, int, int]

_ACTIVE: Optional["SpanTracer"] = None


def get_active() -> Optional["SpanTracer"]:
    return _ACTIVE


def set_active(tracer: Optional["SpanTracer"]) -> Optional["SpanTracer"]:
    """Install ``tracer`` as the process-global tracer; returns the previous one."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = tracer
    return prev


def maybe_begin(name: str) -> None:
    """Fast-path hook for ``utils.timer``: no-op unless a tracer is active."""
    if _ACTIVE is not None:
        _ACTIVE.begin(name)


def maybe_end(name: str) -> None:
    if _ACTIVE is not None:
        _ACTIVE.end(name)


class _SpanContext:
    """Re-usable context manager handed out by ``SpanTracer.span`` / module ``span``."""

    __slots__ = ("_name", "_tracer")

    def __init__(self, name: str, tracer: Optional["SpanTracer"]):
        self._name = name
        self._tracer = tracer

    def __enter__(self):
        tracer = self._tracer if self._tracer is not None else _ACTIVE
        if tracer is not None:
            tracer.begin(self._name)
        return self

    def __exit__(self, *exc):
        tracer = self._tracer if self._tracer is not None else _ACTIVE
        if tracer is not None:
            tracer.end(self._name)
        return False


def span(name: str) -> _SpanContext:
    """``with span("Time/phase"):`` — records on whichever tracer is active at entry."""
    return _SpanContext(name, None)


def trace_span(name: str) -> Callable:
    """Decorator form: the wrapped call becomes one span (no-op when tracing is off)."""

    def deco(fn: Callable) -> Callable:
        def wrapper(*args, **kwargs):
            if _ACTIVE is None:
                return fn(*args, **kwargs)
            _ACTIVE.begin(name)
            try:
                return fn(*args, **kwargs)
            finally:
                _ACTIVE.end(name)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn
        return wrapper

    return deco


class SpanTracer:
    """Collects nested spans into (a) a bounded Chrome-trace event list and (b) per-name
    latency histograms.

    ``rank`` becomes the Chrome-trace ``pid`` so multi-host traces merge into one
    Perfetto timeline with one process track per host.
    """

    def __init__(self, rank: int = 0, max_events: int = 100_000):
        self.rank = int(rank)
        self.max_events = int(max_events)
        self.dropped_events = 0
        self._events: List[_Event] = []
        self._histograms: Dict[str, HistogramMetric] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        # One origin for all ranks' clocks is not required: Perfetto aligns tracks per
        # pid; within a process perf_counter is monotonic and free of NTP jumps.
        self._origin = time.perf_counter()

    # ------------------------------------------------------------------ recording
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def begin(self, name: str) -> None:
        self._stack().append((name, time.perf_counter()))

    def end(self, name: str) -> None:
        now = time.perf_counter()
        stack = self._stack()
        if not stack:
            return  # unbalanced end: tracer was activated mid-span; drop silently
        # Unwind to the matching name so a timer disabled/enabled mid-block can't
        # permanently skew nesting depth.
        while stack:
            top_name, start = stack.pop()
            if top_name == name:
                break
        else:
            return
        dur_us = (now - start) * 1e6
        ts_us = (start - self._origin) * 1e6
        depth = len(stack)
        tid = threading.get_ident()
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = HistogramMetric()
            hist.update(dur_us / 1e3)  # histograms in milliseconds
            if len(self._events) < self.max_events:
                self._events.append((name, ts_us, dur_us, tid, depth))
            else:
                self.dropped_events += 1
        # Span closures also feed the flight recorder's bounded event ring (one
        # global load when no recorder is armed) — the dump's timeline context.
        _flight_recorder.record_span(name, dur_us / 1e3, depth)

    # ------------------------------------------------------------------ export
    def percentiles(self, reset: bool = True) -> Dict[str, Dict[str, float]]:
        """Per-span ``{name: {p50, p95, p99, mean, count}}`` in milliseconds."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, hist in self._histograms.items():
                v = hist.compute()
                if v:
                    out[name] = v
                if reset:
                    hist.reset()
        return out

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome Trace Event Format dict — loadable by Perfetto / chrome://tracing."""
        with self._lock:
            events = list(self._events)
        tids = sorted({e[3] for e in events})
        trace_events: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.rank,
                "args": {"name": f"rank{self.rank}"},
            }
        ]
        for tid in tids:
            trace_events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self.rank,
                    "tid": tid,
                    "args": {"name": f"thread-{tid}"},
                }
            )
        for name, ts_us, dur_us, tid, depth in events:
            trace_events.append(
                {
                    "name": name,
                    "cat": "sheeprl_tpu",
                    "ph": "X",
                    "ts": round(ts_us, 3),
                    "dur": round(dur_us, 3),
                    "pid": self.rank,
                    "tid": tid,
                    "args": {"depth": depth},
                }
            )
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._histograms.clear()
            self.dropped_events = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)
