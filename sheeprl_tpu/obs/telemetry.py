"""Device/host memory telemetry → ``Memory/*`` metrics.

Polls ``jax.local_devices()[i].memory_stats()`` (TPU/GPU HBM: ``bytes_in_use``,
``peak_bytes_in_use``) on a wall-clock interval.  CPU backends return ``None`` from
``memory_stats()``; the poller degrades to host RSS via ``resource.getrusage`` so a
CPU run still gets a ``Memory/*`` signal instead of silence.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

# memory_stats() key → metric suffix; only these are logged (the full dict has ~15
# allocator internals that would drown the dashboard).
_DEVICE_KEYS = {
    "bytes_in_use": "bytes_in_use",
    "peak_bytes_in_use": "peak_bytes_in_use",
    "bytes_limit": "bytes_limit",
}


def _host_rss_bytes() -> Dict[str, float]:
    try:
        import resource
        import sys

        usage = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS.
        scale = 1 if sys.platform == "darwin" else 1024
        return {"Memory/host_peak_rss_bytes": float(usage.ru_maxrss) * scale}
    except Exception:
        return {}


class DeviceTelemetry:
    """Interval-gated poller; ``poll()`` returns ``{}`` between intervals so callers can
    merge it into the metric flush unconditionally."""

    def __init__(self, interval_s: float = 10.0, devices: Optional[Sequence[Any]] = None):
        self.interval_s = float(interval_s)
        self._devices = list(devices) if devices is not None else None
        self._last_poll = float("-inf")
        self.last: Dict[str, float] = {}

    def devices(self) -> Sequence[Any]:
        if self._devices is None:
            import jax

            self._devices = list(jax.local_devices())
        return self._devices

    def poll(self, force: bool = False) -> Dict[str, float]:
        now = time.monotonic()
        if not force and now - self._last_poll < self.interval_s:
            return {}
        self._last_poll = now
        out: Dict[str, float] = {}
        in_use_total = 0.0
        peak_max = 0.0
        saw_device_stats = False
        for i, dev in enumerate(self.devices()):
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            saw_device_stats = True
            for key, suffix in _DEVICE_KEYS.items():
                if key in stats:
                    out[f"Memory/{suffix}/dev{i}"] = float(stats[key])
            in_use_total += float(stats.get("bytes_in_use", 0.0))
            peak_max = max(peak_max, float(stats.get("peak_bytes_in_use", 0.0)))
        if saw_device_stats:
            out["Memory/bytes_in_use"] = in_use_total
            out["Memory/peak_bytes_in_use"] = peak_max
        out.update(_host_rss_bytes())
        self.last = out
        return out
