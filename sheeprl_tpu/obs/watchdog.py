"""Recompilation watchdog → ``Compile/*`` metrics.

A silently recompiling jitted train step is the single worst throughput bug on TPU: one
leaked python scalar in a carry (or a shape that varies with episode length) turns a
30µs cache hit into a multi-second XLA compile *every update*.  The watchdog counts
backend compiles through ``jax.monitoring``'s ``backend_compile`` duration event,
splits them at ``mark_warm()`` (end of the first update = expected warmup compiles),
and flags every post-warmup compile as a recompile.
"""

from __future__ import annotations

import threading
from typing import Dict

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompileWarning(UserWarning):
    """Raised (via ``warnings.warn``) when a jitted function recompiles after warmup."""


class RecompileError(RuntimeError):
    """Hard-error form of :class:`RecompileWarning`, raised instead of warning when
    runtime strict mode (``analysis.strict=True``) is enabled."""


class RecompileWatchdog:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._post_warmup = 0
        self._unseen = 0  # post-warmup compiles not yet drained by poll_new()
        self._compile_seconds = 0.0  # cumulative backend-compile wall clock
        self._unseen_seconds = 0.0  # compile seconds not yet drained (goodput ledger)
        self._warm = False
        self._active = True

        def _listener(event: str, duration_secs: float, **kwargs) -> None:
            if not self._active or event != _BACKEND_COMPILE_EVENT:
                return
            with self._lock:
                self._total += 1
                self._compile_seconds += float(duration_secs or 0.0)
                self._unseen_seconds += float(duration_secs or 0.0)
                if self._warm:
                    self._post_warmup += 1
                    self._unseen += 1

        self._listener = _listener
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)

    def mark_warm(self) -> None:
        """Everything compiled so far was warmup; anything after this is a recompile."""
        with self._lock:
            self._warm = True

    @property
    def total_compiles(self) -> int:
        return self._total

    @property
    def recompiles(self) -> int:
        return self._post_warmup

    def poll_new(self) -> int:
        """Post-warmup recompiles since the last poll (drains the unseen counter)."""
        with self._lock:
            n = self._unseen
            self._unseen = 0
        return n

    @property
    def compile_seconds(self) -> float:
        return self._compile_seconds

    def drain_compile_seconds(self) -> float:
        """Backend-compile seconds since the last drain (goodput ledger input)."""
        with self._lock:
            s = self._unseen_seconds
            self._unseen_seconds = 0.0
        return s

    def metrics(self) -> Dict[str, float]:
        return {
            "Compile/total_compiles": float(self._total),
            "Compile/recompiles": float(self._post_warmup),
            "Compile/compile_seconds": float(self._compile_seconds),
        }

    def close(self) -> None:
        self._active = False
        # Best-effort listener removal through whatever the installed JAX exposes
        # publicly; no private jax._src import, so a JAX upgrade can only degrade
        # this to the no-op fallback (the _active flag already neutralises the
        # listener either way).
        try:
            from jax import monitoring as _m

            for name in (
                "unregister_event_duration_secs_listener",
                "unregister_event_duration_listener_by_callback",
                "_unregister_event_duration_listener_by_callback",
            ):
                unregister = getattr(_m, name, None)
                if callable(unregister):
                    unregister(self._listener)
                    break
        except Exception:
            pass
