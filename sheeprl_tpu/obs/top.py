"""``python -m sheeprl_tpu.obs.top`` — live per-process fleet status.

Renders the :class:`~sheeprl_tpu.obs.fleet.FleetAggregator` snapshot as a
``top``-style table: one row per process slot with throughput (grad/env steps
per second, derived aggregator-side from cumulative counters), queue depth,
param staleness, respawn count, and serve SLO burn vs ``serve.slo_ms``.

Usage::

    python -m sheeprl_tpu.obs.top <fleet_dir> [--once] [--json] [--interval S]

``<fleet_dir>`` is the directory the aggregator writes (default
``<run_dir>/fleet`` under the launcher, or ``obs.fleet.dir``).  ``--once``
prints a single frame and exits non-zero when the snapshot has no process rows,
so CI can assert the plane actually carried telemetry.  Falls back to deriving
a snapshot from the tail of ``timeline.jsonl`` when ``snapshot.json`` is
missing (e.g. the aggregator died before its first atomic write).

Stdlib-only on purpose: ``top`` must work on a machine that observes the fleet
without being able to import JAX.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

_COLUMNS = (
    ("SLOT", 9),
    ("ROLE", 8),
    ("GEN", 4),
    ("PID", 8),
    ("ALIVE", 6),
    ("AGE_S", 7),
    ("GRAD/S", 8),
    ("ENV/S", 9),
    ("QDEPTH", 7),
    ("STALE", 6),
    ("RESPAWN", 8),
    ("SLO%", 6),
    ("P99MS", 8),
    ("MFU%", 6),
    ("GOODPUT", 8),
)


def load_snapshot(fleet_dir: str) -> Optional[Dict[str, Any]]:
    """Read ``snapshot.json``; rebuild a minimal one from the timeline tail if
    the snapshot is missing or unreadable."""
    snap_path = os.path.join(fleet_dir, "snapshot.json")
    try:
        with open(snap_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        pass
    # Rotation-aware tail rebuild: the aggregator size-caps the timeline by
    # renaming it to ``timeline.jsonl.1`` and starting fresh, so read the
    # rotated generation first — rows in the live file are strictly newer and
    # overwrite the same slot keys.
    lines: List[str] = []
    for name in ("timeline.jsonl.1", "timeline.jsonl"):
        try:
            with open(os.path.join(fleet_dir, name)) as f:
                lines.extend(f.readlines())
        except OSError:
            continue
    if not lines:
        return None
    procs: Dict[str, Any] = {}
    for line in lines:
        try:
            row = json.loads(line)
        except ValueError:
            continue
        key = f"{row.get('role', '?')}{int(row.get('actor_id', 0))}"
        procs[key] = {
            "role": row.get("role"),
            "actor_id": row.get("actor_id"),
            "generation": row.get("generation"),
            "host": row.get("host"),
            "pid": row.get("pid"),
            "trace_id": row.get("trace_id"),
            "wall_clock": row.get("wall_clock"),
            "alive": False,  # no live aggregator to vouch for it
            "metrics": row.get("metrics") or {},
        }
    if not procs:
        return None
    return {"fleet_dir": fleet_dir, "written": None, "processes": procs, "rebuilt_from_timeline": True}


def _first(metrics: Dict[str, Any], *names: str) -> Optional[float]:
    for name in names:
        if name in metrics:
            try:
                return float(metrics[name])
            except (TypeError, ValueError):
                continue
    return None


def _fmt(value: Optional[float], width: int, digits: int = 1) -> str:
    if value is None:
        return "-".rjust(width)
    if abs(value) >= 1000:
        return f"{value:,.0f}".rjust(width)
    return f"{value:.{digits}f}".rjust(width)


def format_top(snapshot: Dict[str, Any], now: Optional[float] = None) -> str:
    """Render the snapshot as a fixed-width table (pure function: tests call it
    directly, the CLI loop just reprints it)."""
    now = time.time() if now is None else now
    lines: List[str] = []
    trace_id = snapshot.get("trace_id") or "-"
    written = snapshot.get("written")
    age = f"{now - written:.1f}s ago" if isinstance(written, (int, float)) else "unknown"
    lines.append(f"fleet {snapshot.get('fleet_dir', '?')}  trace_id={trace_id}  snapshot {age}")
    header = " ".join(name.ljust(width) if i < 2 else name.rjust(width) for i, (name, width) in enumerate(_COLUMNS))
    lines.append(header)
    lines.append("-" * len(header))
    procs = snapshot.get("processes") or {}
    for key in sorted(procs, key=lambda k: ({"learner": 0, "actor": 1, "front": 2, "serve": 3}.get(procs[k].get("role"), 9), k)):
        proc = procs[key]
        metrics = proc.get("metrics") or {}
        wall = proc.get("wall_clock")
        age_s = (now - wall) if isinstance(wall, (int, float)) else None
        slo_burn = _first(metrics, "Serve/slo_burn")
        mfu = _first(metrics, "Perf/mfu")
        cells = [
            key.ljust(_COLUMNS[0][1]),
            str(proc.get("role", "?")).ljust(_COLUMNS[1][1]),
            str(proc.get("generation", 0)).rjust(_COLUMNS[2][1]),
            str(proc.get("pid", "-")).rjust(_COLUMNS[3][1]),
            ("yes" if proc.get("alive") else ("done" if proc.get("done") else "DEAD")).rjust(_COLUMNS[4][1]),
            _fmt(age_s, _COLUMNS[5][1]),
            _fmt(_first(metrics, "grad_steps_per_s"), _COLUMNS[6][1]),
            _fmt(_first(metrics, "env_steps_per_s"), _COLUMNS[7][1]),
            _fmt(
                _first(metrics, "Sebulba/queue_depth", "Serve/queue_depth", "Fleet/pending"),
                _COLUMNS[8][1],
                0,
            ),
            _fmt(_first(metrics, "Sebulba/param_staleness_steps"), _COLUMNS[9][1], 0),
            str(proc.get("respawns", "-")).rjust(_COLUMNS[10][1]),
            _fmt(None if slo_burn is None else slo_burn * 100.0, _COLUMNS[11][1]),
            _fmt(
                _first(metrics, "Serve/latency_p99_ms", "Fleet/latency_p99_ms"),
                _COLUMNS[12][1],
            ),
            _fmt(None if mfu is None else mfu * 100.0, _COLUMNS[13][1]),
            _fmt(_first(metrics, "Perf/goodput"), _COLUMNS[14][1], 2),
        ]
        lines.append(" ".join(cells))
    if not procs:
        lines.append("(no processes reported yet)")
    # Fleet-front detail: routed share per replica, reroutes, scale history,
    # canary agreement — the router's own gauges, one line per front slot.
    for key in sorted(procs):
        proc = procs[key]
        if proc.get("role") != "front":
            continue
        metrics = proc.get("metrics") or {}
        shares = {
            name.split("/", 2)[2]: value
            for name, value in metrics.items()
            if isinstance(name, str) and name.startswith("Fleet/share/")
        }
        bits = [f"front {key}:"]
        if shares:
            bits.append(
                "share["
                + " ".join(
                    f"{replica}={float(share) * 100.0:.0f}%"
                    for replica, share in sorted(shares.items())
                )
                + "]"
            )
        reroutes = _first(metrics, "Fleet/reroutes")
        if reroutes is not None:
            bits.append(f"reroutes={reroutes:.0f}")
        admitted = _first(metrics, "Fleet/replicas_admitted")
        retired = _first(metrics, "Fleet/replicas_retired")
        if admitted is not None or retired is not None:
            bits.append(f"replicas +{admitted or 0:.0f}/-{retired or 0:.0f}")
        live = _first(metrics, "Fleet/live_replicas")
        if live is not None:
            bits.append(f"live={live:.0f}")
        agreement = _first(metrics, "Fleet/canary_agreement")
        if agreement is not None:
            bits.append(f"canary_agreement={agreement:.3f}")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sheeprl_tpu.obs.top", description="live fleet telemetry view"
    )
    parser.add_argument("fleet_dir", help="aggregator output dir (contains snapshot.json / timeline.jsonl)")
    parser.add_argument("--once", action="store_true", help="print one frame and exit (rc 2 when empty)")
    parser.add_argument("--json", action="store_true", help="print the raw snapshot JSON instead of the table")
    parser.add_argument("--interval", type=float, default=2.0, help="refresh period in seconds")
    args = parser.parse_args(argv)

    def frame() -> Optional[Dict[str, Any]]:
        return load_snapshot(args.fleet_dir)

    if args.once:
        snapshot = frame()
        if snapshot is None or not snapshot.get("processes"):
            print(f"no fleet telemetry under {args.fleet_dir}", file=sys.stderr)
            return 2
        print(json.dumps(snapshot, indent=1) if args.json else format_top(snapshot))
        return 0

    try:
        while True:
            snapshot = frame()
            out = (
                json.dumps(snapshot, indent=1)
                if args.json and snapshot is not None
                else format_top(snapshot or {"fleet_dir": args.fleet_dir, "processes": {}})
            )
            sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
