"""Performance attribution plane: cost-model MFU, goodput ledger, regression watchdog.

Three parts, surfaced through :class:`PerfPlane` (owned by ``TrainingMonitor``)
and a handful of free functions used from the lowering seams:

1. **Cost-model registry** — every jitted hot path registers its XLA
   ``cost_analysis()`` FLOPs + bytes once, at first call, via
   :func:`instrument` (training dispatches) or :func:`register_compiled`
   (serve batch buckets, which already hold ``Compiled`` objects).  The
   registration uses ``Lowered.cost_analysis()`` — a cheap abstract re-trace,
   no compile, no device transfer — so it is safe under
   ``jax.transfer_guard("disallow")`` and buffer donation.  After that, the
   wrapper only bumps a per-name call counter: the existing step timers turn
   call deltas into zero-extra-sync ``Perf/{mfu,hbm_bw_util,
   achieved_flops_per_sec}`` gauges at every log flush.

2. **Goodput ledger** — classifies every second of wall clock from signals the
   monitor already drains (the ``Time/*`` timer registry, the recompile
   watchdog's compile seconds, checkpoint phases) into
   compute / env / transport / recompile / checkpoint / downtime / other.
   Fractions always sum to 1.0; ``Perf/goodput`` = compute + env (useful work).

3. **Regression watchdog** — an EWMA step-time detector that, on sustained
   post-warmup degradation beyond ``obs.perf.regress_pct``, fires ONE bounded
   auto-capture through the xprof window machinery, stamps a
   ``perf_regression`` flight-recorder event and exports a ``perf_anomalies``
   fleet gauge.

All state that outlives a run (the registry) is process-global and reset from
``cli.run_algorithm``'s ``finally`` block so multirun jobs do not bleed cost
models into each other.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, MutableMapping, Optional, Tuple

__all__ = [
    "PEAK_FLOPS",
    "PEAK_HBM_BW",
    "PERF_REPORT_ENV_VAR",
    "GoodputLedger",
    "PerfPlane",
    "StepTimeWatchdog",
    "analyze_compiled",
    "analyze_lowered",
    "instrument",
    "mfu_from_flops",
    "peak_flops",
    "peak_hbm_bw",
    "perf_enabled",
    "register_compiled",
    "register_cost_model",
    "registered_cost_models",
    "report_path",
    "reset",
]

PERF_REPORT_ENV_VAR = "SHEEPRL_TPU_PERF_REPORT"

# Peak dense bf16 FLOP/s per chip (public figures).  bench.py imports this
# table — keep it the single source of truth for both offline and in-run MFU.
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12 / 2,  # per-chip figure is per 2 cores; one jax device = 1 chip
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e's device_kind
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e/Trillium's device_kind
    "TPU v6e": 918e12,
}
_DEFAULT_PEAK_FLOPS = 275e12  # assume v4 when unknown
# A CPU backend has no published bf16 matrix peak; a nominal figure keeps the
# MFU gauge finite and nonzero in CI smokes without pretending to be accurate.
_CPU_PEAK_FLOPS = 5e11

# Peak HBM bandwidth, bytes/s per chip (public figures); CPUs get a nominal
# DDR-class figure for the same reason as above.
PEAK_HBM_BW = {
    "TPU v2": 700e9,
    "TPU v3": 900e9 / 2,
    "TPU v4": 1200e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}
_DEFAULT_PEAK_HBM_BW = 1200e9
_CPU_PEAK_HBM_BW = 50e9


def _lookup(table: Mapping[str, float], device: Any, default: float, cpu: float) -> float:
    kind = str(getattr(device, "device_kind", "") or "")
    for name, peak in table.items():
        if kind.startswith(name):
            return peak
    platform = str(getattr(device, "platform", "") or "")
    if platform == "cpu" or kind.lower() in ("cpu", "host"):
        return cpu
    return default


def peak_flops(device: Any = None) -> float:
    """Peak dense bf16 FLOP/s for ``device`` (default: ``jax.devices()[0]``)."""
    if device is None:
        device = _default_device()
    return _lookup(PEAK_FLOPS, device, _DEFAULT_PEAK_FLOPS, _CPU_PEAK_FLOPS)


def peak_hbm_bw(device: Any = None) -> float:
    """Peak HBM bytes/s for ``device`` (default: ``jax.devices()[0]``)."""
    if device is None:
        device = _default_device()
    return _lookup(PEAK_HBM_BW, device, _DEFAULT_PEAK_HBM_BW, _CPU_PEAK_HBM_BW)


def _default_device() -> Any:
    try:
        import jax

        return jax.devices()[0]
    except Exception:
        return None


def mfu_from_flops(flops_per_step: float, steps_per_sec: float, device: Any = None) -> float:
    """Model FLOPs utilization: achieved FLOP/s over the chip's peak."""
    peak = peak_flops(device)
    if peak <= 0:
        return 0.0
    return float(flops_per_step) * float(steps_per_sec) / peak


# --------------------------------------------------------------------------- config


def perf_enabled(cfg: Any) -> bool:
    """``obs.perf.enabled`` (default True once an ``obs.perf`` section is
    composed — like the flight recorder, the attribution plane runs regardless
    of ``obs.enabled``).  A cfg with no ``obs.perf`` section at all leaves the
    plane off, so a bare hand-rolled monitor stays a true no-op."""
    perf_cfg = _perf_cfg(cfg)
    if not perf_cfg:
        return False
    try:
        return bool(perf_cfg.get("enabled", True))
    except Exception:
        return True


def _perf_cfg(cfg: Any) -> Mapping[str, Any]:
    if cfg is None:
        return {}
    try:
        obs = cfg.get("obs") if hasattr(cfg, "get") else getattr(cfg, "obs", None)
        if not obs:
            return {}
        perf = obs.get("perf") if hasattr(obs, "get") else getattr(obs, "perf", None)
        return perf or {}
    except Exception:
        return {}


# --------------------------------------------------------------------- cost registry


class _Entry:
    """One registered hot path: XLA cost model + a hot-path call counter.

    ``calls`` is bumped without a lock — CPython's GIL makes the int increment
    effectively atomic, and a rare lost increment only perturbs one flush
    window's MFU, never the registry itself.
    """

    __slots__ = ("name", "flops", "bytes_accessed", "info", "calls", "attempted")

    def __init__(self, name: str) -> None:
        self.name = name
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.info: Dict[str, Any] = {}
        self.calls = 0
        self.attempted = False


_lock = threading.Lock()
_registry: Dict[str, _Entry] = {}


def _ensure_entry(name: str) -> _Entry:
    with _lock:
        entry = _registry.get(name)
        if entry is None:
            entry = _Entry(name)
            _registry[name] = entry
        return entry


def register_cost_model(name: str, flops: float, bytes_accessed: float = 0.0, **info: Any) -> None:
    """Record the XLA cost model for one jitted hot path (idempotent by name)."""
    entry = _ensure_entry(name)
    with _lock:
        entry.flops = float(flops or 0.0)
        entry.bytes_accessed = float(bytes_accessed or 0.0)
        entry.info.update(info)
        entry.attempted = True


def record_call(name: str, n: int = 1) -> None:
    """Bump the call counter for ``name`` (for paths not wrapped by instrument)."""
    _ensure_entry(name).calls += n


def registered_cost_models() -> Dict[str, Dict[str, Any]]:
    """Snapshot of the registry: ``{name: {flops, bytes_accessed, calls, ...}}``."""
    with _lock:
        return {
            name: {
                "flops": e.flops,
                "bytes_accessed": e.bytes_accessed,
                "calls": e.calls,
                **({"info": dict(e.info)} if e.info else {}),
            }
            for name, e in _registry.items()
        }


def reset() -> None:
    """Clear the process-global registry (between multirun jobs / in tests)."""
    with _lock:
        _registry.clear()


# ------------------------------------------------------------------- cost analysis


def _cost_dict(cost: Any) -> Dict[str, Any]:
    # Lowered.cost_analysis() returns a plain dict; Compiled.cost_analysis()
    # returns a list of per-executable dicts — normalize both shapes.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def analyze_lowered(lowered: Any) -> Tuple[float, float]:
    """``(flops, bytes_accessed)`` from a ``jax.stages.Lowered`` (no compile)."""
    cost = _cost_dict(lowered.cost_analysis())
    return float(cost.get("flops", 0.0) or 0.0), float(cost.get("bytes accessed", 0.0) or 0.0)


def analyze_compiled(compiled: Any) -> Tuple[float, float]:
    """``(flops, bytes_accessed)`` from a ``jax.stages.Compiled``."""
    cost = _cost_dict(compiled.cost_analysis())
    return float(cost.get("flops", 0.0) or 0.0), float(cost.get("bytes accessed", 0.0) or 0.0)


def _memory_info(compiled: Any) -> Dict[str, float]:
    try:
        stats = compiled.memory_analysis()
    except Exception:
        return {}
    info: Dict[str, float] = {}
    for attr in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(stats, attr, None)
        if value is not None:
            info[attr] = float(value)
    return info


def register_compiled(name: str, compiled: Any) -> None:
    """Register a cost model straight from a ``Compiled`` (serve batch buckets)."""
    try:
        flops, bytes_accessed = analyze_compiled(compiled)
        register_cost_model(name, flops, bytes_accessed, **_memory_info(compiled))
    except Exception:
        # Never let attribution kill a serving path; mark the attempt so the
        # report shows the bucket with a zero model instead of omitting it.
        register_cost_model(name, 0.0, 0.0)


def _unwrap_jit(fn: Any) -> Optional[Any]:
    """Follow ``__wrapped__`` (strict_guard et al.) down to a jitted callable."""
    target, hops = fn, 0
    while target is not None and hops < 8:
        if hasattr(target, "lower"):
            return target
        target = getattr(target, "__wrapped__", None)
        hops += 1
    return None


def instrument(cfg: Any, name: str, fn: Callable[..., Any]) -> Callable[..., Any]:
    """Wrap a jitted hot path: register its cost model once, count every call.

    Identity when ``obs.perf.enabled`` is off.  The first call re-lowers the
    underlying jitted function with the live arguments — an abstract trace
    (cheap, no compile, no transfers) — and records XLA's FLOPs/bytes estimate
    under ``name``.  Every call bumps the per-name counter the
    :class:`PerfPlane` turns into MFU at flush time.
    """
    if not perf_enabled(cfg):
        return fn
    entry = _ensure_entry(name)

    def instrumented(*args: Any, **kwargs: Any) -> Any:
        if not entry.attempted:
            entry.attempted = True
            target = _unwrap_jit(fn)
            if target is not None:
                try:
                    flops, bytes_accessed = analyze_lowered(target.lower(*args, **kwargs))
                    register_cost_model(name, flops, bytes_accessed)
                except Exception:
                    pass
        entry.calls += 1
        return fn(*args, **kwargs)

    instrumented.__name__ = f"perf_instrument[{name}]"
    instrumented.__qualname__ = instrumented.__name__
    instrumented.__wrapped__ = fn
    return instrumented


# ------------------------------------------------------------------ goodput ledger

# First-present candidate lists: Anakin times its dispatch block with BOTH
# ``Time/train_time`` and ``Time/phase_dispatch`` (same with-block), so only
# the first present key counts — summing them would double-book compute.
_COMPUTE_KEYS = ("Time/phase_dispatch", "Time/train_time", "Time/phase_train")
_ENV_KEYS = ("Time/phase_env_step", "Time/env_interaction_time", "Time/env_interaction", "Time/env_time")
_TRANSPORT_KEYS = ("Time/block_send", "Time/block_recv", "Time/queue_wait", "Time/phase_transport")
_CHECKPOINT_KEYS = ("Time/phase_checkpoint", "Time/checkpoint_time", "Time/phase_ckpt")

GOODPUT_CATEGORIES = ("compute", "env", "transport", "recompile", "checkpoint", "downtime", "other")


def _first_present(timers: Mapping[str, float], keys: Tuple[str, ...]) -> float:
    for key in keys:
        if key in timers:
            try:
                return max(0.0, float(timers[key]))
            except (TypeError, ValueError):
                return 0.0
    return 0.0


class GoodputLedger:
    """Classify wall clock into the goodput taxonomy; fractions sum to 1.0.

    ``classify`` takes one flush window's drained timers plus out-of-band
    seconds (recompiles from the compile-event watchdog, downtime from the
    supervisor) and returns per-category fractions of ``elapsed_s``.  When the
    classified seconds exceed the wall clock (overlapping timers), every
    category is scaled down proportionally so the sum stays exactly 1.0.
    Cumulative seconds accumulate for the end-of-run report.
    """

    def __init__(self) -> None:
        self.totals: Dict[str, float] = {c: 0.0 for c in GOODPUT_CATEGORIES}
        self.elapsed_total = 0.0

    def classify(
        self,
        timers: Mapping[str, float],
        elapsed_s: float,
        recompile_s: float = 0.0,
        downtime_s: float = 0.0,
    ) -> Dict[str, float]:
        seconds = {
            "compute": _first_present(timers, _COMPUTE_KEYS),
            "env": _first_present(timers, _ENV_KEYS),
            "transport": sum(_first_present(timers, (k,)) for k in _TRANSPORT_KEYS),
            "recompile": max(0.0, float(recompile_s or 0.0)),
            "checkpoint": _first_present(timers, _CHECKPOINT_KEYS),
            "downtime": max(0.0, float(downtime_s or 0.0)),
        }
        classified = sum(seconds.values())
        elapsed = float(elapsed_s or 0.0)
        if elapsed <= 0.0:
            elapsed = classified
        if elapsed <= 0.0:
            # Nothing happened this window: call it all "other" so fractions
            # still sum to 1.0 and downstream means stay well-defined.
            fractions = {c: 0.0 for c in GOODPUT_CATEGORIES}
            fractions["other"] = 1.0
            return fractions
        if classified > elapsed:
            scale = elapsed / classified
            seconds = {c: s * scale for c, s in seconds.items()}
            classified = elapsed
        seconds["other"] = elapsed - classified
        for category, value in seconds.items():
            self.totals[category] += value
        self.elapsed_total += elapsed
        return {c: seconds[c] / elapsed for c in GOODPUT_CATEGORIES}

    def fractions(self) -> Dict[str, float]:
        """Cumulative fractions over every classified window (sum to 1.0)."""
        if self.elapsed_total <= 0.0:
            out = {c: 0.0 for c in GOODPUT_CATEGORIES}
            out["other"] = 1.0
            return out
        return {c: self.totals[c] / self.elapsed_total for c in GOODPUT_CATEGORIES}

    def goodput(self) -> float:
        """Useful-work fraction: device compute + env stepping."""
        fractions = self.fractions()
        return fractions["compute"] + fractions["env"]


# -------------------------------------------------------------- regression watchdog


class StepTimeWatchdog:
    """EWMA step-time regression detector with a bounded capture budget.

    ``observe(dt)`` returns an event dict exactly once per *sustained*
    degradation episode (EWMA above ``baseline * (1 + regress_pct)`` for
    ``sustain_steps`` consecutive observations), then stays silent until the
    EWMA recovers below the threshold — no retrigger flapping.  The event's
    ``capture`` flag is True at most ``max_captures`` times per run.
    """

    def __init__(
        self,
        regress_pct: float = 0.25,
        warmup_steps: int = 20,
        sustain_steps: int = 5,
        alpha: float = 0.2,
        max_captures: int = 1,
    ) -> None:
        self.regress_pct = float(regress_pct)
        self.warmup_steps = max(1, int(warmup_steps))
        self.sustain_steps = max(1, int(sustain_steps))
        self.alpha = float(alpha)
        self.baseline: Optional[float] = None
        self.ewma: Optional[float] = None
        self.anomalies = 0
        self._observed = 0
        self._degraded_run = 0
        self._in_episode = False
        self._captures_left = max(0, int(max_captures))

    def observe(self, dt: float) -> Optional[Dict[str, float]]:
        dt = float(dt)
        if dt < 0.0:
            return None
        self._observed += 1
        if self.ewma is None:
            self.ewma = dt
        else:
            self.ewma = self.alpha * dt + (1.0 - self.alpha) * self.ewma
        if self._observed <= self.warmup_steps:
            self.baseline = self.ewma
            return None
        assert self.baseline is not None
        threshold = self.baseline * (1.0 + self.regress_pct)
        if self.ewma > threshold:
            self._degraded_run += 1
            if self._degraded_run >= self.sustain_steps and not self._in_episode:
                self._in_episode = True
                self.anomalies += 1
                capture = self._captures_left > 0
                if capture:
                    self._captures_left -= 1
                return {
                    "baseline_s": self.baseline,
                    "ewma_s": self.ewma,
                    "regress_pct": self.regress_pct,
                    "degradation": self.ewma / self.baseline - 1.0,
                    "capture": capture,
                }
        else:
            self._degraded_run = 0
            self._in_episode = False  # recovered: re-arm for the next episode
        return None


# ----------------------------------------------------------------------- PerfPlane


class PerfPlane:
    """Per-process attribution plane owned by the training monitor.

    ``observe_step()`` per update feeds the regression watchdog;
    ``flush(metrics)`` at every log flush folds ``Perf/*`` gauges into the
    outgoing metric dict (reading the ``Time/*`` timers that were just drained
    into it) and pushes MFU/goodput to the active fleet exporter;
    ``write_report(path)`` emits ``perf_report.json`` at close.
    """

    def __init__(self, cfg: Any = None, role: str = "learner") -> None:
        perf_cfg = _perf_cfg(cfg)
        self.enabled = perf_enabled(cfg)
        self.role = role
        self.regress_pct = float(perf_cfg.get("regress_pct", 0.25) or 0.25)
        self.capture_updates = int(perf_cfg.get("capture_updates", 3) or 3)
        self.watchdog = StepTimeWatchdog(
            regress_pct=self.regress_pct,
            warmup_steps=int(perf_cfg.get("warmup_steps", 20) or 20),
            sustain_steps=int(perf_cfg.get("sustain_steps", 5) or 5),
            alpha=float(perf_cfg.get("ewma_alpha", 0.2) or 0.2),
            max_captures=int(perf_cfg.get("max_captures", 1) or 1),
        )
        self.ledger = GoodputLedger()
        self._start = time.monotonic()
        self._last_flush = self._start
        self._last_step: Optional[float] = None
        self._last_calls: Dict[str, int] = {}
        self._flops_total = 0.0
        self._bytes_total = 0.0
        self._device = None
        self.anomaly_events: List[Dict[str, float]] = []

    # ------------------------------------------------------------------ stepping

    def observe_step(self) -> Optional[Dict[str, float]]:
        """Per-update heartbeat; returns a regression event when one fires."""
        if not self.enabled:
            return None
        now = time.monotonic()
        if self._last_step is None:
            self._last_step = now
            return None
        dt, self._last_step = now - self._last_step, now
        event = self.watchdog.observe(dt)
        if event is not None:
            self.anomaly_events.append(event)
        return event

    # ------------------------------------------------------------------- flushing

    def device(self) -> Any:
        if self._device is None:
            self._device = _default_device()
        return self._device

    def flush(
        self,
        metrics: MutableMapping[str, Any],
        recompile_s: float = 0.0,
        downtime_s: float = 0.0,
    ) -> None:
        """Fold ``Perf/*`` gauges into ``metrics`` (already holding the drained
        ``Time/*`` timers) and push them to the active fleet exporter."""
        if not self.enabled:
            return
        now = time.monotonic()
        elapsed, self._last_flush = now - self._last_flush, now
        snapshot = registered_cost_models()
        delta_flops = delta_bytes = 0.0
        for name, entry in snapshot.items():
            delta_calls = entry["calls"] - self._last_calls.get(name, 0)
            self._last_calls[name] = entry["calls"]
            if delta_calls > 0:
                delta_flops += delta_calls * entry["flops"]
                delta_bytes += delta_calls * entry["bytes_accessed"]
        self._flops_total += delta_flops
        self._bytes_total += delta_bytes
        if elapsed > 0.0 and delta_flops > 0.0:
            achieved = delta_flops / elapsed
            metrics["Perf/achieved_flops_per_sec"] = achieved
            metrics["Perf/mfu"] = achieved / peak_flops(self.device())
            bw = peak_hbm_bw(self.device())
            if bw > 0.0:
                metrics["Perf/hbm_bw_util"] = (delta_bytes / elapsed) / bw
        fractions = self.ledger.classify(
            metrics, elapsed, recompile_s=recompile_s, downtime_s=downtime_s
        )
        metrics["Perf/goodput"] = fractions["compute"] + fractions["env"]
        for category, fraction in fractions.items():
            metrics[f"Perf/goodput_{category}"] = fraction
        metrics["Perf/anomalies"] = float(self.watchdog.anomalies)
        self._push_fleet(metrics)

    def _push_fleet(self, metrics: Mapping[str, Any]) -> None:
        try:
            from sheeprl_tpu.obs import fleet as obs_fleet

            exporter = obs_fleet.get_active()
        except Exception:
            return
        if exporter is None:
            return
        for key in ("Perf/mfu", "Perf/goodput", "Perf/hbm_bw_util"):
            if key in metrics:
                exporter.gauge(key, float(metrics[key]))
        exporter.gauge("perf_anomalies", float(self.watchdog.anomalies))

    # -------------------------------------------------------------------- report

    def report(self) -> Dict[str, Any]:
        # Fold any call deltas since the last flush so the exit report is
        # complete even when the run ends mid-window.
        for name, entry in registered_cost_models().items():
            delta_calls = entry["calls"] - self._last_calls.get(name, 0)
            self._last_calls[name] = entry["calls"]
            if delta_calls > 0:
                self._flops_total += delta_calls * entry["flops"]
                self._bytes_total += delta_calls * entry["bytes_accessed"]
        elapsed = max(1e-9, time.monotonic() - self._start)
        device = self.device()
        peak = peak_flops(device)
        achieved = self._flops_total / elapsed
        fractions = self.ledger.fractions()
        return {
            "role": self.role,
            "device_kind": str(getattr(device, "device_kind", "") or ""),
            "peak_flops": peak,
            "peak_hbm_bw": peak_hbm_bw(device),
            "elapsed_s": elapsed,
            "total_flops": self._flops_total,
            "total_bytes_accessed": self._bytes_total,
            "achieved_flops_per_sec": achieved,
            "mfu": achieved / peak if peak > 0 else 0.0,
            "hbm_bw_util": (self._bytes_total / elapsed) / peak_hbm_bw(device)
            if peak_hbm_bw(device) > 0
            else 0.0,
            "goodput": fractions["compute"] + fractions["env"],
            "goodput_fractions": fractions,
            "anomalies": self.watchdog.anomalies,
            "anomaly_events": list(self.anomaly_events),
            "cost_models": registered_cost_models(),
        }

    def write_report(self, path: str) -> Optional[str]:
        """Atomically write ``perf_report.json``; best-effort, returns the path.

        Skipped when no cost model ever registered and no anomaly fired — a
        process with no instrumented hot path has nothing to attribute, and a
        fully disabled monitor must leave its log dir untouched."""
        if not self.enabled or not path:
            return None
        if not registered_cost_models() and not self.watchdog.anomalies:
            return None
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.report(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError:
            return None


def report_path(log_dir: Optional[str] = None) -> Optional[str]:
    """Resolve where ``perf_report.json`` goes: env override, then the run dir."""
    env = os.environ.get(PERF_REPORT_ENV_VAR)
    if env:
        return env
    if log_dir:
        return os.path.join(str(log_dir), "perf_report.json")
    return None
