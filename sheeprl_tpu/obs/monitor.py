"""``TrainingMonitor``: one object that wires the whole observability stack into an
algorithm loop with a ~3-line change.

    monitor = TrainingMonitor(cfg, log_dir)          # after get_logger(...)
    ...
    for update in ...:
        monitor.advance(policy_step)                 # top of every update
        ...
        monitor.log_metrics(logger, metrics, step)   # instead of logger.log_metrics
    ...
    monitor.close()                                  # before the loop's teardown

Per update, ``advance`` (a) rolls the ``jax.profiler.StepTraceAnnotation`` so XProf
traces show one slice per training update, (b) drives the programmatic XProf capture
window (``obs.capture_steps=[N, M]`` → ``<log_dir>/xprof``), (c) polls device/host
memory telemetry, and (d) after warmup arms the recompile watchdog and warns loudly on
every post-warmup jit cache miss.  The span tracer itself is fed by the ``timer``
context managers already present in every loop (see ``utils/timer.py``), so phase spans
(env interaction, h2d transfer, train step, logging) need no extra per-algo code.

``obs.enabled=false`` short-circuits every method on its first line: the monitor adds
one attribute check per update and nothing else.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional

from sheeprl_tpu.obs import flight_recorder as _flight_recorder
from sheeprl_tpu.obs import tracer as _tracer
from sheeprl_tpu.obs.telemetry import DeviceTelemetry
from sheeprl_tpu.obs.tracer import SpanTracer
from sheeprl_tpu.obs.watchdog import RecompileError, RecompileWarning, RecompileWatchdog

_UPDATE_SPAN = "Time/update"
_LOG_SPAN = "Time/log"


class TrainingMonitor:
    def __init__(self, cfg: Dict[str, Any], log_dir: str, rank: Optional[int] = None):
        obs_cfg = dict(cfg.get("obs", {}) or {})
        self.enabled: bool = bool(obs_cfg.get("enabled", False))
        # analysis.strict upgrades the recompile watchdog from warning to hard error
        # and arms NaN/Inf checks at the update boundary (sheeprl_tpu/analysis).
        from sheeprl_tpu.analysis.strict import strict_enabled

        self.strict: bool = strict_enabled(cfg)
        self.log_dir = log_dir
        self._updates = 0
        self._closed = False
        self.tracer: Optional[SpanTracer] = None
        self._telemetry: Optional[DeviceTelemetry] = None
        self._watchdog: Optional[RecompileWatchdog] = None
        # The flight recorder is INDEPENDENT of obs.enabled: crash forensics must
        # work on runs that never turned the tracer on.  It stays installed after
        # close() — cli.run_algorithm dumps it on crash and clears it afterwards.
        self.recorder = None
        if bool(obs_cfg.get("flight_recorder", True)):
            self.recorder = _flight_recorder.FlightRecorder(
                log_dir=log_dir,
                capacity=int(obs_cfg.get("flight_recorder_capacity", 4096)),
                keep_events=int(obs_cfg.get("flight_recorder_keep_events", 512)),
                algo=(cfg.get("algo", {}) or {}).get("name"),
                cfg=cfg,
            )
            _flight_recorder.install(self.recorder)
        # The performance-attribution plane (obs/perf.py) is likewise independent
        # of obs.enabled: MFU/goodput gauges and perf_report.json must exist on
        # runs that never turned the tracer on.
        from sheeprl_tpu.obs.perf import PerfPlane

        self.perf = PerfPlane(cfg)
        # Capture machinery lives in the common path (not behind obs.enabled) so
        # the perf watchdog's anomaly auto-capture can open an XProf window on an
        # otherwise-untraced run.
        self._capture = None
        self._capturing = False
        self._session = None
        self._annotation = None
        self._host_tracer_level = int(obs_cfg.get("host_tracer_level", 0))
        self._perf_capture_remaining = 0
        if not self.enabled:
            return

        if rank is None:
            import jax

            rank = jax.process_index()
        self.rank = int(rank)

        # Validate everything that can raise BEFORE taking side effects (installing the
        # global tracer, registering the jax.monitoring listener) so a bad config
        # cannot leak process-global state.
        capture = obs_cfg.get("capture_steps")
        if capture:
            start, end = int(capture[0]), int(capture[1])
            if start < 1 or end < start:
                raise ValueError(f"obs.capture_steps must be [start>=1, end>=start]; got {capture!r}")
            self._capture = (start, end)

        self._xprof = bool(obs_cfg.get("xprof_annotations", True))
        self._warmup_updates = max(int(obs_cfg.get("warmup_updates", 1)), 0)
        self._telemetry_latest: Dict[str, float] = {}

        self._trace = bool(obs_cfg.get("trace", True))
        self._prev_tracer = None
        if self._trace:
            self.tracer = SpanTracer(rank=self.rank, max_events=int(obs_cfg.get("max_events", 100_000)))
            self._prev_tracer = _tracer.set_active(self.tracer)

        if bool(obs_cfg.get("telemetry", True)):
            self._telemetry = DeviceTelemetry(interval_s=float(obs_cfg.get("telemetry_interval", 10.0)))

        if bool(obs_cfg.get("watchdog", True)):
            self._watchdog = RecompileWatchdog()

    # ------------------------------------------------------------------ per update
    def advance(self, policy_step: Optional[int] = None) -> None:
        """Call once at the top of every training update."""
        self._updates += 1
        # Perf regression watchdog runs in the common path: a sustained step-time
        # degradation fires one perf_regression event + one bounded auto-capture
        # even when the tracer stack is off.
        event = self.perf.observe_step()
        if event is not None:
            _flight_recorder.record_event(
                "perf_regression",
                update=self._updates - 1,
                baseline_s=event["baseline_s"],
                ewma_s=event["ewma_s"],
                degradation=event["degradation"],
                capture=bool(event.get("capture")),
            )
        if not self.enabled:
            self._perf_capture_tick(event)
            return
        if self.strict:
            # update boundary: surface any NaN/Inf the in-jit nan_scan callbacks saw
            from sheeprl_tpu.analysis.strict import raise_pending

            raise_pending()
        update = self._updates

        if self.tracer is not None:
            if update > 1:
                self.tracer.end(_UPDATE_SPAN)
            self.tracer.begin(_UPDATE_SPAN)

        # Close the previous update's StepTraceAnnotation BEFORE moving the capture
        # window, and open the next one AFTER: every annotation must nest strictly
        # inside the profiler session (TraceMe handles straddling a start_trace/
        # stop_trace boundary poorly — observed as a native crash when third-party
        # render threads are alive).
        if self._xprof and self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None

        self._perf_capture_tick(event)
        if self._capture is not None:
            start, end = self._capture
            if update == start and not self._capturing:
                self._start_capture()
            elif update == end + 1 and self._capturing:
                self._stop_capture()

        if self._xprof:
            import jax

            self._annotation = jax.profiler.StepTraceAnnotation("train", step_num=update)
            self._annotation.__enter__()

        if self._watchdog is not None:
            if update == self._warmup_updates + 1:
                self._watchdog.mark_warm()
            elif update > self._warmup_updates + 1:
                n = self._watchdog.poll_new()
                if n:
                    _flight_recorder.record_event(
                        "recompile",
                        update=update - 1,
                        count=n,
                        total=self._watchdog.total_compiles,
                    )
                    msg = (
                        f"{n} post-warmup XLA recompilation(s) detected at update {update - 1} "
                        f"(total={self._watchdog.total_compiles}): a jitted function's input "
                        "shapes/dtypes or captured constants are changing between updates, which "
                        "silently destroys throughput. Check Compile/recompiles and capture an "
                        "XProf window (obs.capture_steps) around this update."
                    )
                    if self.strict:
                        raise RecompileError(f"analysis.strict: {msg}")
                    warnings.warn(msg, RecompileWarning, stacklevel=2)

        if self._telemetry is not None:
            polled = self._telemetry.poll()
            if polled:
                self._telemetry_latest = polled

    def _perf_capture_tick(self, event: Optional[Dict[str, float]]) -> None:
        """Drive the watchdog's bounded auto-capture window (obs.perf.capture_updates)."""
        if event is not None and event.get("capture") and not self._capturing:
            self._perf_capture_remaining = max(1, self.perf.capture_updates)
            self._start_capture()
        elif self._perf_capture_remaining > 0:
            self._perf_capture_remaining -= 1
            if self._perf_capture_remaining <= 0 and self._capturing:
                self._stop_capture()

    # ------------------------------------------------------------------ metrics/logging
    def span(self, name: str):
        """Extra phase span, e.g. ``with monitor.span("Time/replay_ratio_wait"):``."""
        return _tracer._SpanContext(name, self.tracer)

    @staticmethod
    def phase(name: str):
        """Named wall-clock phase: ``with monitor.phase("env_step"):`` accumulates
        ``Time/phase_env_step`` seconds in the timer registry (and a span when the
        tracer is on).  :meth:`log_metrics` folds the registry into every flush, so
        any loop instrumented with phases gets the per-phase wall-clock breakdown
        the DreamerV3 loop pioneered — independent of ``obs.enabled``, at the cost
        of one ``perf_counter`` pair per block."""
        from sheeprl_tpu.utils.timer import timer

        return timer(f"Time/phase_{name}")

    def metrics(self) -> Dict[str, float]:
        """Span percentiles + memory/compile gauges, flattened for the logger."""
        if not self.enabled:
            return {}
        out: Dict[str, float] = {}
        if self.tracer is not None:
            for name, stats in self.tracer.percentiles(reset=True).items():
                for k, v in stats.items():
                    out[f"{name}/{k}"] = v
        out.update(self._telemetry_latest)
        if self._watchdog is not None:
            out.update(self._watchdog.metrics())
        return out

    def log_metrics(self, logger, metrics: Dict[str, float], step: int) -> None:
        """Merge the monitor's metrics and forward to the logger inside a log span.

        Runs three things regardless of ``obs.enabled``: (a) folds the named-timer
        registry into the flush, so every loop instrumented with ``monitor.phase``
        / ``with timer(...)`` reports the ``Time/phase_*`` wall-clock breakdown for
        free, (b) folds in the ``Fault/*`` counters (``sheeprl_tpu/fault``) — empty
        for a healthy run, the preemption/restart/fallback trail for a supervised
        one — and (c) records a ``metric_flush`` event (with a Health/Loss
        snapshot) on the flight recorder — the learning-dynamics trail a blackbox
        dump is read by.
        """
        from sheeprl_tpu.fault.counters import fault_metrics
        from sheeprl_tpu.utils.timer import timer as _timer

        metrics.update(_timer.to_dict(reset=True))
        metrics.update(fault_metrics())
        # Perf gauges fold in AFTER the timer drain (the goodput ledger reads the
        # Time/* keys straight out of the flush) and run regardless of obs.enabled.
        recompile_s = self._watchdog.drain_compile_seconds() if self._watchdog is not None else 0.0
        self.perf.flush(metrics, recompile_s=recompile_s)
        if _flight_recorder.get_active() is not None:
            snapshot = {
                k: metrics[k]
                for k in metrics
                if k.startswith(("Health/", "Loss/", "Compile/", "Rollout/", "Perf/"))
            }
            _flight_recorder.record_event(
                "metric_flush", step=step, n_metrics=len(metrics), values=snapshot
            )
        if not self.enabled:
            if logger is not None:
                logger.log_metrics(metrics, step)
            return
        metrics.update(self.metrics())
        if logger is None:
            return
        if self.tracer is not None:
            self.tracer.begin(_LOG_SPAN)
            try:
                logger.log_metrics(metrics, step)
            finally:
                self.tracer.end(_LOG_SPAN)
        else:
            logger.log_metrics(metrics, step)

    # ------------------------------------------------------------------ capture window
    def _start_capture(self) -> None:
        """Open an XProf profiler session writing to ``<log_dir>/xprof``.

        Uses the low-level ``ProfilerSession`` (what ``jax.profiler.start_trace``
        wraps) so the TSL *host* tracer level is controllable: at its default level
        the host tracer installs thread hooks that SEGFAULT when certain third-party
        threads are alive (observed with dm_control/glfw render threads + a
        SummaryWriter event thread).  ``obs.host_tracer_level=0`` (the default) skips
        host tracing entirely — device/XLA events, the part the span tracer cannot
        see, are still captured — and is the only level safe everywhere."""
        path = os.path.join(self.log_dir, "xprof")
        try:
            from jax._src.lib import xla_client

            opts = xla_client.profiler.ProfileOptions()
            opts.host_tracer_level = self._host_tracer_level
            opts.python_tracer_level = 0
            self._session = xla_client.profiler.ProfilerSession(opts)
            self._capture_path = path
            self._capturing = True
        except Exception as e:  # no private API / profiler already active: don't kill training
            self._session = None
            warnings.warn(f"obs.capture_steps: could not start XProf trace at {path}: {e}")

    def _stop_capture(self) -> None:
        if self._session is not None:
            try:
                self._session.stop_and_export(self._capture_path)
            except Exception as e:
                warnings.warn(f"obs.capture_steps: could not export XProf trace: {e}")
            self._session = None
        self._capturing = False

    # ------------------------------------------------------------------ teardown
    def trace_path(self) -> str:
        name = "trace.json" if self.rank == 0 else f"trace_rank{self.rank}.json"
        return os.path.join(self.log_dir, name)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.enabled:
            if self._annotation is not None:
                self._annotation.__exit__(None, None, None)
                self._annotation = None
            if self._capturing:
                self._stop_capture()
            if self._watchdog is not None:
                self._watchdog.close()
            if self.tracer is not None:
                self.tracer.end(_UPDATE_SPAN)
                try:
                    self.tracer.export_chrome_trace(self.trace_path())
                except OSError as e:
                    warnings.warn(f"could not export Chrome trace: {e}")
                _tracer.set_active(self._prev_tracer)
        elif self._capturing:
            # an anomaly auto-capture may be open on an otherwise-untraced run
            self._stop_capture()
        from sheeprl_tpu.obs.perf import report_path

        path = report_path(self.log_dir)
        if path:
            self.perf.write_report(path)
        # Strict runs drain outstanding in-jit nan_scan callbacks one last time
        # AFTER teardown: a NaN in the final update (no later advance() to surface
        # it) must still crash the run — and therefore trigger the blackbox dump —
        # instead of exiting zero.
        if self.strict:
            from sheeprl_tpu.analysis.strict import raise_pending

            raise_pending()
