// Native replay-sequence gather: the host-side hot path that feeds the TPU.
//
// Replaces the numpy fancy-index + swapaxes pair in
// SequentialReplayBuffer._gather_sequences (reference semantics
// data/buffers.py:439-526) with ONE memcpy pass that writes the time-major
// [T, B, feat] layout the training step consumes.  Two wins on the single-core
// bench host: half the memory passes (no separate transpose copy at device_put —
// the output is already contiguous in the target layout), and the call releases
// the GIL (plain ctypes foreign call), so the env/dispatch thread keeps running
// while the prefetch thread gathers.
//
// Layouts (all C-contiguous, element sizes in BYTES):
//   src:  [buffer_size, n_envs, feat...]   -> row block = feat_bytes
//   dst:  [n_samples*T*B, feat...] viewed as [n_samples, T, B, feat...]
//   starts[n_samples*B], env_idx[n_samples*B]: one sequence per (sample, b) pair,
//   laid out sample-major (b fastest), matching the numpy path's reshape.
//
// dst[(s, t, b)] = src[(starts[s*B+b] + t) % buffer_size, env_idx[s*B+b]]

#include <cstdint>
#include <cstring>

extern "C" {

void gather_seq(const uint8_t* src, uint8_t* dst, const int64_t* starts,
                const int64_t* env_idx, int64_t n_samples, int64_t T, int64_t B,
                int64_t buffer_size, int64_t n_envs, int64_t feat_bytes,
                int64_t start_offset) {
  const int64_t env_stride = feat_bytes;
  const int64_t row_stride = n_envs * feat_bytes;
  for (int64_t s = 0; s < n_samples; ++s) {
    const int64_t* seq_starts = starts + s * B;
    const int64_t* seq_envs = env_idx + s * B;
    uint8_t* dst_sample = dst + s * T * B * feat_bytes;
    for (int64_t b = 0; b < B; ++b) {
      const int64_t start = seq_starts[b] + start_offset;
      const uint8_t* src_env = src + seq_envs[b] * env_stride;
      uint8_t* dst_b = dst_sample + b * feat_bytes;
      for (int64_t t = 0; t < T; ++t) {
        const int64_t row = (start + t) % buffer_size;
        std::memcpy(dst_b + t * B * feat_bytes, src_env + row * row_stride,
                    static_cast<size_t>(feat_bytes));
      }
    }
  }
}

// Flat transition gather for the plain ReplayBuffer (T==1 fast path):
// dst[i] = src[rows[i], envs[i]]
void gather_rows(const uint8_t* src, uint8_t* dst, const int64_t* rows,
                 const int64_t* envs, int64_t n, int64_t n_envs,
                 int64_t feat_bytes) {
  const int64_t row_stride = n_envs * feat_bytes;
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(dst + i * feat_bytes, src + rows[i] * row_stride + envs[i] * feat_bytes,
                static_cast<size_t>(feat_bytes));
  }
}

}  // extern "C"
