"""Native (C++) runtime components.

The compute path is XLA/Pallas; this package holds the host-side native pieces —
currently the replay-sequence gather that feeds the device (``gather.cpp``).  The
shared library is compiled once on first use with the image's g++ and cached next
to the source; every consumer falls back to the numpy path if the toolchain or the
cached library is unavailable, so the framework never hard-depends on it.
Disable explicitly with ``SHEEPRL_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_HERE = Path(__file__).parent
_SRC = _HERE / "gather.cpp"
_LIB = _HERE / "_gather.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_I64P = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    # Per-process tmp name: concurrent first-use builds (e.g. a multi-host launch on a
    # fresh checkout) must not write into each other's output; os.replace is atomic.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC), "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The gather library, building it on first call; None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SHEEPRL_TPU_NATIVE", "1") == "0":
            return None
        if not _LIB.is_file() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
            lib.gather_seq.restype = None
            lib.gather_seq.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, _I64P, _I64P,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ]
            lib.gather_rows.restype = None
            lib.gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, _I64P, _I64P,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def gather_seq(
    src: np.ndarray,
    starts: np.ndarray,
    env_idx: np.ndarray,
    n_samples: int,
    seq_len: int,
    batch: int,
    start_offset: int = 0,
) -> Optional[np.ndarray]:
    """Gather ``[n_samples, T, B, *feat]`` sequences from a ``[size, n_envs, *feat]``
    C-contiguous buffer in one pass (time-major output, no transpose copy).
    ``starts``/``env_idx`` are ``[n_samples*B]`` int64, sample-major.  Returns None
    when the native path can't serve this array (not contiguous / lib missing)."""
    lib = load()
    if lib is None or not src.flags["C_CONTIGUOUS"] or src.size == 0:
        return None
    feat_bytes = int(src.itemsize * np.prod(src.shape[2:], dtype=np.int64))
    out = np.empty((n_samples, seq_len, batch) + src.shape[2:], dtype=src.dtype)
    lib.gather_seq(
        src.ctypes.data, out.ctypes.data,
        np.ascontiguousarray(starts, dtype=np.int64),
        np.ascontiguousarray(env_idx, dtype=np.int64),
        n_samples, seq_len, batch, src.shape[0], src.shape[1], feat_bytes,
        start_offset,
    )
    return out


def gather_rows(src: np.ndarray, rows: np.ndarray, envs: np.ndarray) -> Optional[np.ndarray]:
    """dst[i] = src[rows[i], envs[i]] for a ``[size, n_envs, *feat]`` buffer."""
    lib = load()
    if lib is None or not src.flags["C_CONTIGUOUS"] or src.size == 0:
        return None
    n = int(rows.shape[0])
    feat_bytes = int(src.itemsize * np.prod(src.shape[2:], dtype=np.int64))
    out = np.empty((n,) + src.shape[2:], dtype=src.dtype)
    lib.gather_rows(
        src.ctypes.data, out.ctypes.data,
        np.ascontiguousarray(rows, dtype=np.int64),
        np.ascontiguousarray(envs, dtype=np.int64),
        n, src.shape[1], feat_bytes,
    )
    return out
