"""sheeprl-tpu: a TPU-native deep reinforcement learning framework.

The capability surface of SheepRL (reference layout ``sheeprl/__init__.py``) —
14 algorithm entry points, a Hydra-style config CLI, replay buffers, gymnasium env
pipelines, checkpoint/resume, metrics, eval, model registry — rebuilt from scratch
on JAX/XLA: jitted ``lax.scan`` training steps, GSPMD data/tensor/sequence
parallelism over a device mesh, Pallas kernels and a native C++ host data path.
"""

__version__ = "0.2.0"
