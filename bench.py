"""Benchmark: DreamerV3 throughput on the flagship config — train-only AND end-to-end.

Phase 1 (train-only): the full jitted DreamerV3 train step (world model + actor +
critic + EMA + moments) on synthetic Atari-100K-shaped data — batch 16 × sequence 64 ×
64×64×3 pixels, model size S — matching the reference's headline benchmark config
(BASELINE.md: DreamerV3-S on Atari MsPacman-100K).  Also reports an MFU estimate from
the compiled step's XLA cost analysis and the chip's peak bf16 FLOP/s.

Phase 2 (end-to-end): the REAL training loop (env stepping + replay buffer + async
prefetch + training + logging) through the CLI on the deterministic dummy env, reporting
the loop's own ``Time/sps_train`` / ``Time/sps_env_interaction`` plus overall
policy-steps/s.  Set ``BENCH_E2E=0`` to skip.

Baseline (GPU-anchored, BASELINE.md "North-star anchor"): the reference reports 14 h on
1× RTX 3080 for Atari MsPacman-100K (README.md:46-53).  Its exp config
(``configs/exp/dreamer_v3_100k_ms_pacman.yaml``: ``total_steps=100000``,
``learning_starts=1024``, DV3 default ``replay_ratio: 1``) + the Ratio call at
``dreamer_v3.py:661-662`` (grad steps = ratio × (policy_step − prefill), where
policy_step already counts action-repeated frames) give ≈ 1.0 × (100000 − 1024) ≈
98,976 gradient steps in 14 h ⇒ **1.963 grad-steps/s end-to-end** on the 1-GPU
baseline, at the same batch 16 × seq 64 × size S this bench runs.  ``vs_baseline`` is
measured_e2e / 1.963 (e2e-vs-e2e at matched batch/seq/model; the e2e phase here also
runs replay_ratio=1); it falls back to the train-only rate over the same denominator
only if the e2e phase is skipped/failed, flagged by ``vs_baseline_kind``.  The north
star (BASELINE.json) asks ≥2× this rate; ``north_star_met`` states the verdict.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import tempfile
import time

import numpy as np

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

# Reference 1-GPU end-to-end rate derived from its published Atari MsPacman-100K
# wall-clock (docstring above): ~98,976 gradient steps / 14 h on 1× RTX 3080.
BASELINE_E2E_GRAD_STEPS_PER_SEC = 1.963

# Peak dense bf16 FLOP/s per chip: single source of truth is the perf
# attribution plane (``sheeprl_tpu/obs/perf.py``); re-exported here under the
# historical names so downstream scripts importing ``bench.PEAK_FLOPS`` /
# ``bench._peak_flops`` keep working.
from sheeprl_tpu.obs.perf import PEAK_FLOPS, peak_flops as _peak_flops  # noqa: E402


def bench_train_only(size: str = "S", batch: int = 16):
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

    import gymnasium as gym

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            f"algo=dreamer_v3_{size}",
            f"algo.per_rank_batch_size={batch}",
            "algo.per_rank_sequence_length=64",
        ]
    )
    cfg.algo.cnn_keys.encoder = ["rgb"]
    cfg.algo.mlp_keys.encoder = []

    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="bf16-mixed", seed=0)

    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)
    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, False, cfg, obs_space)
    train_step, init_opt_states = make_train_step(world_model, actor, critic, cfg, ["rgb"], [], {})
    opt_states = init_opt_states(params)
    moments = init_moments()

    T, B = cfg.algo.per_rank_sequence_length, cfg.algo.per_rank_batch_size
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 3, 64, 64), dtype=np.uint8)),
        "actions": jnp.asarray(rng.random((T, B, 6)).astype(np.float32)),
        "rewards": jnp.asarray(rng.random((T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }

    train_jit = jax.jit(train_step)
    key = jax.random.PRNGKey(0)
    update_target = jnp.asarray(True)

    # FLOPs of one compiled step (XLA's own estimate) for the MFU figure — the
    # same ``analyze_compiled`` the in-run perf plane uses, so this bench and
    # ``Perf/mfu`` agree by construction (pinned in tests/test_obs/test_perf.py).
    from sheeprl_tpu.obs import perf as obs_perf

    flops_per_step = 0.0
    try:
        compiled = train_jit.lower(params, opt_states, moments, data, key, update_target).compile()
        flops_per_step, _ = obs_perf.analyze_compiled(compiled)
    except Exception:
        pass

    # Warmup (compile + a few steps); device_get forces a full host-visible sync —
    # block_until_ready alone has proven unreliable on the axon transport.
    metrics = None
    for _ in range(5):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = train_jit(params, opt_states, moments, data, sub, update_target)
    jax.device_get(metrics)

    n_steps = int(os.environ.get("BENCH_STEPS", "30"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = train_jit(params, opt_states, moments, data, sub, update_target)
    jax.device_get(metrics)  # the last metrics depend on the whole step chain
    elapsed = time.perf_counter() - t0

    gsps = n_steps / elapsed
    mfu = 0.0
    if flops_per_step > 0:
        mfu = obs_perf.mfu_from_flops(flops_per_step, gsps, jax.devices()[0])
    return gsps, mfu


def bench_e2e(replay_ratio: int = 1, total_steps: int | None = None, prefix: str = ""):
    """Real training loop (env + buffer + prefetch + train) on the dummy env.

    ``replay_ratio=4`` is the second bench point the round-3 profile predicted would
    amortise the tunnel's acting round trip over a 4×-larger gradient block
    (``PROFILE_r03.md``): the prediction was ``e2e_sps_train / train_only ≈ 0.72``
    at R=4 vs the measured 0.40 at R=1.
    """
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    from sheeprl_tpu.cli import run

    tmp = tempfile.mkdtemp(prefix="bench_e2e_")
    if total_steps is None:
        total_steps = int(os.environ.get("BENCH_E2E_STEPS", "768"))
    t0 = time.perf_counter()
    try:
        run(
            [
                "exp=dreamer_v3_dummy",
                "algo=dreamer_v3_S",
                "env=discrete_dummy",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]",
                "env.screen_size=64",
                "env.num_envs=4",
                "env.sync_env=True",
                "env.capture_video=False",
                f"algo.total_steps={total_steps}",
                "algo.learning_starts=256",
                f"algo.replay_ratio={replay_ratio}",
                "algo.per_rank_batch_size=16",
                "algo.per_rank_sequence_length=64",
                "algo.run_test=False",
                "buffer.size=100000",
                "buffer.memmap=False",
                "buffer.checkpoint=False",
                "buffer.device=True",  # HBM-resident replay: index-only sampling
                "checkpoint.every=0",
                "checkpoint.save_last=False",
                # Window of 16 iterations per log: the deferred-metrics design syncs
                # only at the log cadence; log_every=1 would force a drain per
                # iteration and measure the sync overhead instead of the loop.
                "metric.log_every=64",
                f"log_root={tmp}",
            ]
        )
        elapsed = time.perf_counter() - t0
        out = {f"{prefix}e2e_policy_steps_per_sec": round(total_steps / elapsed, 3)}
        runs = sorted(glob.glob(os.path.join(tmp, "**", "version_*"), recursive=True))
        if runs:
            ea = EventAccumulator(runs[-1])
            ea.Reload()
            for tag, key in (
                ("Time/sps_train", f"{prefix}e2e_sps_train"),
                ("Time/sps_env_interaction", f"{prefix}e2e_sps_env_interaction"),
            ):
                if tag in ea.Tags()["scalars"]:
                    vals = [s.value for s in ea.Scalars(tag)]
                    # steady-state: the first samples are dominated by the one-off
                    # jit compile (~60 s on the TPU), not by training throughput
                    steady = vals[2:] if len(vals) > 4 else vals
                    out[key] = round(float(np.mean(steady)), 3)
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_droq_utd20() -> dict:
    """DroQ UTD-20 grad-steps/s over the device-ring fused-block path
    (``buffer.device=True`` semantics: HBM transition ring + ONE donated dispatch
    for the 20 critic updates + actor update, in-jit index sampling).  Rides
    ``benchmarks/replay_bench.py`` at DroQ walker-ish shapes so future
    BENCH_*.json track the ISSUE-5 dispatch-fusion win.  Set ``BENCH_DROQ=0`` to
    skip."""
    import argparse
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import replay_bench
    finally:
        sys.path.pop(0)
    args = argparse.Namespace(
        batch=128, hidden=256, obs_dim=17, act_dim=6, utd=20,
        blocks=int(os.environ.get("BENCH_DROQ_BLOCKS", "8")),
    )
    rates = replay_bench.bench_sac_family("droq", args)
    return {
        "metric": "droq_utd20_grad_steps_per_sec",
        "value": round(rates["device_ring"], 3),
        "unit": f"grad_steps/s (device ring + fused block, batch {args.batch} x obs "
        f"{args.obs_dim} x hidden {args.hidden}, UTD {args.utd}, 1 chip)",
        "host_block_grad_steps_per_sec": round(rates["host_block"], 3),
        "host_per_step_grad_steps_per_sec": round(rates["host_per_step"], 3),
        "speedup_vs_host_per_step": round(rates["device_ring"] / rates["host_per_step"], 3),
    }


def bench_anakin() -> list:
    """Anakin fused-scan rows (``benchmarks/anakin_bench.py``): on-device jax
    CartPole env-steps/s vs the host ``SyncVectorEnv`` path, the fused PPO
    collect+update grad-steps/s, the K-member POPULATION dispatch
    (``anakin_population_steps_per_sec`` + per-member efficiency; ISSUE-8) and
    the persistent-compilation-cache cold-vs-warm row
    (``anakin_compile_seconds``).  Set ``BENCH_ANAKIN=0`` to skip; member count
    via ``BENCH_ANAKIN_MEMBERS``, compile row via ``BENCH_ANAKIN_COMPILE=0``."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import anakin_bench
    finally:
        sys.path.pop(0)
    argv = [
        "--num-envs", os.environ.get("BENCH_ANAKIN_ENVS", "1024"),
        "--iters", os.environ.get("BENCH_ANAKIN_ITERS", "8"),
    ]
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        anakin_bench.main(argv)
    return [json.loads(line) for line in buf.getvalue().splitlines() if line.strip()]


def bench_fault() -> list:
    """Checkpoint fault-tolerance cost rows (ISSUE-10): wall-clock of one
    integrity-checked ``CheckpointManager.save`` (fsync + sha256 manifest) and of
    the matching verified restore path (``latest_valid`` discovery + checksum
    verify + deserialize) on a PPO-sized state pytree.  Lower is better — these
    bound the preemption grace window and the supervisor's resume latency.  Set
    ``BENCH_FAULT=0`` to skip."""
    from sheeprl_tpu.checkpoint.manager import CheckpointManager

    rng = np.random.default_rng(0)
    # ~64 MB of params/opt-state shaped like a mid-size host-loop checkpoint.
    state = {
        "params": {f"layer_{i}": rng.standard_normal((1024, 1024)).astype(np.float32) for i in range(8)},
        "opt_state": {f"mu_{i}": rng.standard_normal((1024, 1024)).astype(np.float32) for i in range(8)},
        "policy_step": 1024,
        "update": 16,
    }
    tmp = tempfile.mkdtemp(prefix="bench_fault_")
    try:
        manager = CheckpointManager(os.path.join(tmp, "checkpoints"), keep_last=3)
        reps = int(os.environ.get("BENCH_FAULT_REPS", "3"))
        save_times, restore_times = [], []
        for rep in range(reps):
            t0 = time.perf_counter()
            manager.save((rep + 1) * 100, state)
            save_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            latest = CheckpointManager.latest_valid(os.path.join(tmp, "checkpoints"))
            CheckpointManager.load(latest, fallback=True)
            restore_times.append(time.perf_counter() - t0)
        mb = sum(a.nbytes for tree in (state["params"], state["opt_state"]) for a in tree.values()) / 2**20
        return [
            {
                "metric": "checkpoint_save_seconds",
                "value": round(float(np.median(save_times)), 4),
                "unit": f"seconds (fsync'd integrity-manifest save, {mb:.0f} MB state, median of {reps})",
            },
            {
                "metric": "resume_restore_seconds",
                "value": round(float(np.median(restore_times)), 4),
                "unit": f"seconds (latest_valid + checksum verify + load, {mb:.0f} MB state, median of {reps})",
            },
        ]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_sebulba() -> list:
    """Sebulba multi-process topology rows (``benchmarks/sebulba_bench.py``):
    2-actor acting throughput vs 1 actor and the thread-decoupled baseline,
    plus the learner's grad-steps/s while blocks stream over the transport.
    Spawns 4 short subprocess runs: steady-state trace rates for the sebulba
    variants, a two-budget wall delta for the thread baseline (startup and
    compile cancel either way).  Set ``BENCH_SEBULBA=0`` to skip."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import sebulba_bench
    finally:
        sys.path.pop(0)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        sebulba_bench.main([])
    return [json.loads(line) for line in buf.getvalue().splitlines() if line.strip()]


def bench_serve() -> list:
    """Serve-tier rows (``benchmarks/serve_bench.py``): continuous-batching
    replies/s vs the naive one-request-per-dispatch baseline at 32 closed-loop
    clients, the batched p99 latency, and warm-vs-cold replica startup through
    the persistent compile cache.  Spawns 4 short server subprocesses.  Set
    ``BENCH_SERVE=0`` to skip; client/request counts via ``BENCH_SERVE_CLIENTS``
    / ``BENCH_SERVE_REQUESTS``."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)
    import contextlib
    import io

    argv = [
        "--clients", os.environ.get("BENCH_SERVE_CLIENTS", "32"),
        "--requests", os.environ.get("BENCH_SERVE_REQUESTS", "100"),
    ]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        serve_bench.main(argv)
    return [json.loads(line) for line in buf.getvalue().splitlines() if line.strip()]


def bench_precision() -> list:
    """Precision-tier rows (``benchmarks/precision_bench.py``): bf16 fused-PPO
    env-steps/s vs f32, int8 serve replies/s vs f32, and the int8 parity stamp's
    greedy action agreement.  Set ``BENCH_PRECISION=0`` to skip; scale via
    ``BENCH_PRECISION_ENVS`` / ``BENCH_PRECISION_ITERS`` /
    ``BENCH_PRECISION_CLIENTS``."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import precision_bench
    finally:
        sys.path.pop(0)
    import contextlib
    import io

    argv = [
        "--num-envs", os.environ.get("BENCH_PRECISION_ENVS", "32"),
        "--iters", os.environ.get("BENCH_PRECISION_ITERS", "10"),
        "--clients", os.environ.get("BENCH_PRECISION_CLIENTS", "4"),
    ]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        precision_bench.main(argv)
    # the in-process servers print "[serve] ..." progress lines: keep JSON rows only
    return [
        json.loads(line) for line in buf.getvalue().splitlines() if line.strip().startswith("{")
    ]


def bench_perf_overhead() -> list:
    """Perf-attribution plane cost rows (``benchmarks/perf_overhead_bench.py``):
    steady-state overhead of ``obs.perf`` instrumentation + ledger (must stay
    <=2%), plus the plane's own ``perf_mfu`` and ``goodput_fraction`` on the
    bench workload (direction-pinned in ``bench_compare.py``).  Set
    ``BENCH_PERF=0`` to skip."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import perf_overhead_bench
    finally:
        sys.path.pop(0)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        perf_overhead_bench.main([])
    return [json.loads(line) for line in buf.getvalue().splitlines() if line.strip()]


def bench_ir_audit() -> dict:
    """Wall-clock of the full ``jaxlint-ir`` audit (``sheeprl_tpu/analysis/ir``):
    AOT-lower + compile + rule-check every entry point's jitted update and both
    Anakin dispatches against ``irbudgets.json``.  The CI ir-audit job runs this
    on every PR, so its runtime is a first-class budget: the row must stay under
    ~120 s on one CPU core.  Runs in a SUBPROCESS on the CPU backend (the audit
    pins JAX_PLATFORMS=cpu; this process may hold a TPU).  Set ``BENCH_IR=0`` to
    skip."""
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "SHEEPRL_TPU_QUIET": "1"}
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis.ir", "-q"],
        capture_output=True,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=int(os.environ.get("BENCH_IR_TIMEOUT", "900")),
    )
    elapsed = time.perf_counter() - t0
    return {
        "metric": "ir_audit_seconds",
        "value": round(elapsed, 2),
        "unit": "seconds (full jaxlint-ir audit: 15 programs lowered+compiled+checked, 1 CPU core)",
        "exit_code": proc.returncode,
        "findings": proc.stdout.count("\n") if proc.returncode else 0,
        "budget_seconds": 120,
        "within_budget": bool(elapsed < 120),
    }


def main() -> None:
    # IR-audit wall-clock row (ISSUE-7): the static-analysis tier's own budget.
    if os.environ.get("BENCH_IR", "1") != "0":
        try:
            print(json.dumps(bench_ir_audit()))
        except Exception as exc:
            print(json.dumps({"metric": "ir_audit_seconds", "error": str(exc)[:200]}))
    # Anakin fused-scan rows first (ISSUE-6): the collector parses the LAST JSON
    # line as the headline metric, so auxiliary rows print before it.
    if os.environ.get("BENCH_ANAKIN", "1") != "0":
        try:
            for row in bench_anakin():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "anakin_cartpole_steps_per_sec", "error": str(exc)[:200]}))
    # Sebulba multi-process topology rows (ISSUE-13): BENCH_SEBULBA=0 skips
    # (it spawns a fleet of short subprocess runs, the longest bench section).
    if os.environ.get("BENCH_SEBULBA", "1") != "0":
        try:
            for row in bench_sebulba():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "sebulba_env_steps_per_sec", "error": str(exc)[:200]}))
    # Serve-tier rows (ISSUE-14): continuous batching vs naive + cold/warm start.
    if os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            for row in bench_serve():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "serve_throughput_rps", "error": str(exc)[:200]}))
    # Precision-tier rows (ISSUE-15): bf16 train + int8 serve A/B + parity stamp.
    if os.environ.get("BENCH_PRECISION", "1") != "0":
        try:
            for row in bench_precision():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "anakin_bf16_steps_per_sec", "error": str(exc)[:200]}))
    # Fault-tolerance cost rows (ISSUE-10): checkpoint save + verified restore.
    if os.environ.get("BENCH_FAULT", "1") != "0":
        try:
            for row in bench_fault():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "checkpoint_save_seconds", "error": str(exc)[:200]}))
    # Perf-attribution overhead rows (PR-19): instrument+ledger on vs off on a
    # ~1 ms/step jitted workload, plus the plane's own MFU/goodput figures.
    if os.environ.get("BENCH_PERF", "1") != "0":
        try:
            for row in bench_perf_overhead():
                print(json.dumps(row))
        except Exception as exc:
            print(json.dumps({"metric": "perf_overhead_pct", "error": str(exc)[:200]}))
    # DroQ UTD-20 fused-block row: same auxiliary-row contract.
    if os.environ.get("BENCH_DROQ", "1") != "0":
        try:
            print(json.dumps(bench_droq_utd20()))
        except Exception as exc:
            print(json.dumps({"metric": "droq_utd20_grad_steps_per_sec", "error": str(exc)[:200]}))
    gsps, mfu = bench_train_only()
    extras = {}
    if os.environ.get("BENCH_E2E", "1") != "0":
        try:
            extras = bench_e2e()
        except Exception as exc:  # the headline number must still print
            extras = {"e2e_error": str(exc)[:200]}
        # Second point at replay ratio 4: measures the RTT-amortisation claim
        # (PROFILE_r03.md predicted ~0.72× train-only; r1 measured 0.40×).
        if os.environ.get("BENCH_E2E_R4", "1") != "0":
            try:
                extras.update(bench_e2e(replay_ratio=4, total_steps=512, prefix="r4_"))
                if "r4_e2e_sps_train" in extras and gsps > 0:
                    extras["r4_e2e_over_train_only"] = round(extras["r4_e2e_sps_train"] / gsps, 4)
            except Exception as exc:
                extras["r4_e2e_error"] = str(exc)[:200]
    # Honest comparison: reference published only an end-to-end wall-clock, so compare
    # e2e-to-e2e; the train-only rate has no published counterpart.
    if "e2e_sps_train" in extras:
        vs_baseline = extras["e2e_sps_train"] / BASELINE_E2E_GRAD_STEPS_PER_SEC
        vs_kind = (
            "e2e_sps_train / reference_GPU_e2e(1.963 = 98976 grad steps / 14h, "
            "MsPacman-100K on 1x RTX 3080, batch 16 x seq 64, size S, replay_ratio 1)"
        )
    else:
        vs_baseline = gsps / BASELINE_E2E_GRAD_STEPS_PER_SEC
        vs_kind = (
            "train_only / reference_GPU_e2e(1.963) — e2e phase unavailable"
        )
    print(
        json.dumps(
            {
                "metric": "dreamer_v3_S_grad_steps_per_sec",
                "value": round(gsps, 4),
                "unit": "grad_steps/s (batch 16 x seq 64, 64x64x3 obs, 1 chip)",
                "vs_baseline": round(vs_baseline, 4),
                "vs_baseline_kind": vs_kind,
                # only an e2e measurement can answer the (e2e-defined) north star
                "north_star_met": bool(vs_baseline >= 2.0) if "e2e_sps_train" in extras else None,
                "north_star": "BASELINE.json: >=2x the reference 1-GPU grad-steps/s at matched batch/seq",
                "mfu": round(mfu, 4),
                **extras,
            }
        )
    )


if __name__ == "__main__":
    main()
