"""Benchmark: DreamerV3 gradient-steps/sec on the flagship config.

Runs the full jitted DreamerV3 train step (world model + actor + critic + EMA + moments)
on synthetic Atari-100K-shaped data — batch 16 × sequence 64 × 64×64×3 pixels, model
size S — matching the reference's headline benchmark config
(BASELINE.md: DreamerV3-S on Atari MsPacman-100K).

Baseline: the reference reports 14 h on 1× RTX 3080 for Atari-100K
(README.md:46-53).  100K frames at action-repeat 4 → 25K policy steps; replay ratio 0.5
→ ~12.5K gradient steps ⇒ ≈0.25 grad-steps/s end-to-end. Train-only throughput is
higher; we conservatively estimate the reference's pure train-step rate at ~1.0
grad-steps/s on its GPU (no absolute number is published — BASELINE.md notes the cell
is empty).  ``vs_baseline`` is measured/1.0.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

os.environ.setdefault("SHEEPRL_TPU_QUIET", "1")

BASELINE_GRAD_STEPS_PER_SEC = 1.0  # estimated reference 1-GPU train-only rate (see above)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=64",
        ]
    )
    cfg.algo.cnn_keys.encoder = ["rgb"]
    cfg.algo.mlp_keys.encoder = []

    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="bf16-mixed", seed=0)

    import gymnasium as gym

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent

    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)
    world_model, actor, critic, params, _ = build_agent(ctx, actions_dim, False, cfg, obs_space)
    train_step, init_opt_states = make_train_step(world_model, actor, critic, cfg, ["rgb"], [], {})
    opt_states = init_opt_states(params)
    moments = init_moments()

    T, B = cfg.algo.per_rank_sequence_length, cfg.algo.per_rank_batch_size
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 3, 64, 64), dtype=np.uint8)),
        "actions": jnp.asarray(rng.random((T, B, 6)).astype(np.float32)),
        "rewards": jnp.asarray(rng.random((T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }

    train_jit = jax.jit(train_step)
    key = jax.random.PRNGKey(0)
    update_target = jnp.asarray(True)

    # Warmup (compile + a few steps); device_get forces a full host-visible sync —
    # block_until_ready alone has proven unreliable on the axon transport.
    metrics = None
    for _ in range(5):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = train_jit(params, opt_states, moments, data, sub, update_target)
    jax.device_get(metrics)

    n_steps = int(os.environ.get("BENCH_STEPS", "30"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = train_jit(params, opt_states, moments, data, sub, update_target)
    jax.device_get(metrics)  # the last metrics depend on the whole step chain
    elapsed = time.perf_counter() - t0

    gsps = n_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "dreamer_v3_S_grad_steps_per_sec",
                "value": round(gsps, 4),
                "unit": "grad_steps/s (batch 16 x seq 64, 64x64x3 obs, 1 chip)",
                "vs_baseline": round(gsps / BASELINE_GRAD_STEPS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
