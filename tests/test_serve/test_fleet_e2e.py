"""Slow end-to-end chaos test: a REAL fleet (supervisor -> front + 2 replica
processes), session-affine clients in flight, one replica SIGKILLed — every
accepted request must still be answered.  The CI fleet smoke drives the same
scenario with the shell harness; this is the in-repo repro."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sheeprl_tpu.serve.client import FleetClient

pytestmark = pytest.mark.slow

MODEL = "fleet_e2e_ppo"

TINY_PPO = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
    "env.num_envs=1",
    "env.capture_video=False",
]


@pytest.fixture(scope="module")
def registry(tmp_path_factory):
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import compose, save_config
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.model_manager import LocalModelManager
    from sheeprl_tpu.utils.policy import build_policy

    tmp = tmp_path_factory.mktemp("fleet_e2e")
    cfg = compose(config_name="config", overrides=TINY_PPO)
    env = make_env(cfg, 0, 0, None, "fleet_e2e")()
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    policy, params = build_policy(ctx, cfg, env.observation_space, env.action_space)
    env.close()

    ckpt = CheckpointManager(tmp / "run" / "checkpoints").save(0, {"params": params})
    save_config(cfg, tmp / "run" / "config.yaml")
    mm = LocalModelManager(registry_dir=tmp / "registry")
    mm.register_model(str(ckpt), MODEL)
    return tmp / "registry", policy.obs_template


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while not predicate():
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        time.sleep(0.2)


def test_fleet_survives_a_sigkilled_replica_with_zero_lost_replies(registry, tmp_path):
    registry_dir, obs_template = registry
    fleet_dir = tmp_path / "fleet"
    summary_path = tmp_path / "supervisor_summary.json"
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    for var in ("SHEEPRL_TPU_FLEET", "SHEEPRL_TPU_FLEET_SUMMARY", "SHEEPRL_TPU_SUPERVISE_SUMMARY"):
        env.pop(var, None)
    sup = subprocess.Popen(
        [
            sys.executable, "-m", "sheeprl_tpu.supervise", "--serve",
            f"serve.policies=[{MODEL}:1]",
            f"model_manager.registry_dir={registry_dir}",
            "serve.max_batch_size=4",
            "serve.max_batch_delay_ms=2.0",
            "serve.log_every_s=0",
            "serve.fleet.enabled=True",
            f"serve.fleet.dir={fleet_dir}",
            "serve.fleet.min_replicas=2",
            "serve.fleet.max_replicas=2",
            "serve.fleet.probe_interval_s=0.2",
            "serve.fleet.status_interval_s=0.2",
            f"fault.summary_path={summary_path}",
            f"compile_cache.dir={tmp_path / 'xla_cache'}",
        ],
        env=env,
    )
    try:
        front_ready = fleet_dir / "front_ready.json"
        records_dir = fleet_dir / "replicas"
        _wait_for(front_ready.is_file, 300, "front ready file")
        port = json.loads(front_ready.read_text())["port"]
        endpoint = ("127.0.0.1", port)

        def two_replicas_admitted():
            try:
                with FleetClient([endpoint], timeout_s=5.0) as probe:
                    pong = probe.ping(timeout=5.0)
            except (ConnectionError, TimeoutError, OSError):
                return False
            replicas = (pong.get("fleet") or {}).get("replicas") or {}
            return sum(1 for r in replicas.values() if r.get("alive")) >= 2 and pong["policies"]

        _wait_for(two_replicas_admitted, 300, "two admitted replicas")

        obs = {k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()}
        clients, per_client = 3, 30
        replies = [0] * clients
        errors = []

        def worker(idx):
            try:
                with FleetClient([endpoint], timeout_s=60.0, session=f"chaos{idx}") as c:
                    for _ in range(per_client):
                        _, meta = c.act(obs, MODEL, timeout=60)
                        assert meta["replica"]
                        replies[idx] += 1
            except Exception as e:  # noqa: BLE001 - every act MUST succeed
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(clients)]
        for t in threads:
            t.start()
        _wait_for(lambda: sum(replies) >= 20 or errors, 120, "clients to get going")

        # prefer a victim with a request in flight (deterministic reroute)
        victim_pid = None
        deadline = time.monotonic() + 10.0
        while victim_pid is None and time.monotonic() < deadline and sum(replies) < clients * per_client:
            try:
                with FleetClient([endpoint], timeout_s=5.0) as probe:
                    fleet_view = probe.ping(timeout=5.0)["fleet"]["replicas"]
            except (ConnectionError, TimeoutError, OSError):
                continue
            busy = [n for n, r in fleet_view.items() if r.get("inflight", 0) > 0 and not r.get("canary")]
            for record_file in sorted(records_dir.glob("*.json")):
                rec = json.loads(record_file.read_text())
                if rec["name"] in busy:
                    victim_pid = rec["pid"]
                    break
        if victim_pid is None:  # fall back to any live replica
            recs = [json.loads(p.read_text()) for p in sorted(records_dir.glob("*.json"))]
            victim_pid = next(r["pid"] for r in recs if not r["canary"])
        os.kill(victim_pid, signal.SIGKILL)

        for t in threads:
            t.join(timeout=300)
        assert not errors, errors[0]
        assert sum(replies) == clients * per_client  # zero lost replies

        sup.send_signal(signal.SIGTERM)
        assert sup.wait(timeout=120) == 0  # orderly fleet drain
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait(timeout=30)

    front_summary = json.loads((fleet_dir / "front_summary.json").read_text())
    assert front_summary["accepted"] == front_summary["replied"]
    assert front_summary["errors"] == 0 and front_summary["dropped"] == 0
    sup_summary = json.loads(summary_path.read_text())
    assert sup_summary["mode"] == "fleet" and sup_summary["outcome"] == "preempted"
    # the SIGKILL was classified as a crash (the respawn may still be inside
    # its backoff window when the fleet is torn down — that's fine, the zero-
    # loss assertion above already proved the reroute)
    kinds = [e["kind"] for e in sup_summary["events"]]
    assert "crash" in kinds and kinds.count("spawn") >= 3
