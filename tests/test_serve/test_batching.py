"""serve/batching.py: ladder construction, bucket fit, continuous-batch collection, padding."""

import queue
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.batching import bucket_ladder, collect_batch, pad_obs_batch, pick_bucket


def test_bucket_ladder_powers_of_two_always_include_max():
    assert bucket_ladder(1) == [1]
    assert bucket_ladder(8) == [1, 2, 4, 8]
    assert bucket_ladder(32) == [1, 2, 4, 8, 16, 32]
    # non-power-of-two max still tops the ladder
    assert bucket_ladder(12) == [1, 2, 4, 8, 12]


def test_bucket_ladder_explicit_is_validated():
    assert bucket_ladder(16, explicit=[4, 16, 1]) == [1, 4, 16]
    with pytest.raises(ValueError, match="must top out at serve.max_batch_size=16"):
        bucket_ladder(16, explicit=[1, 8])
    with pytest.raises(ValueError, match=">= 1"):
        bucket_ladder(16, explicit=[0, 16])
    with pytest.raises(ValueError, match=">= 1"):
        bucket_ladder(0)


def test_pick_bucket_smallest_fit():
    ladder = [1, 2, 4, 8]
    assert pick_bucket(ladder, 1) == 1
    assert pick_bucket(ladder, 3) == 4
    assert pick_bucket(ladder, 8) == 8
    with pytest.raises(ValueError, match="exceeds the ladder maximum"):
        pick_bucket(ladder, 9)


def test_collect_batch_idle_returns_empty():
    q = queue.Queue()
    t0 = time.monotonic()
    assert collect_batch(q, max_batch=4, delay_s=10.0, first_timeout_s=0.05) == []
    # the idle poll honors first_timeout_s, NOT the (long) batch deadline
    assert time.monotonic() - t0 < 1.0


def test_collect_batch_dispatches_when_full():
    q = queue.Queue()
    for i in range(8):
        q.put(i)
    # a full bucket dispatches immediately — the deadline never comes into play
    assert collect_batch(q, max_batch=4, delay_s=60.0) == [0, 1, 2, 3]
    assert collect_batch(q, max_batch=4, delay_s=60.0) == [4, 5, 6, 7]
    # a leftover smaller than the bucket ships at the (short) deadline
    q.put(8)
    assert collect_batch(q, max_batch=4, delay_s=0.05) == [8]


def test_collect_batch_dispatches_partial_at_deadline():
    q = queue.Queue()
    q.put("a")

    def late_put():
        time.sleep(0.02)
        q.put("b")
        time.sleep(0.3)
        q.put("too_late")

    t = threading.Thread(target=late_put, daemon=True)
    t.start()
    batch = collect_batch(q, max_batch=8, delay_s=0.1)
    t.join()
    # the first item opened the batch + deadline clock; "b" arrived inside the
    # window, "too_late" did not — a partial batch ships at the deadline.
    assert batch == ["a", "b"]
    assert q.get_nowait() == "too_late"


def test_pad_obs_batch_zero_pads_and_casts():
    template = {"state": ((3,), "float32")}
    obs_list = [
        {"state": np.array([1.0, 2.0, 3.0], dtype=np.float64)},  # cast down
        {"state": np.array([4, 5, 6], dtype=np.int32)},  # cast up
    ]
    out = pad_obs_batch(obs_list, template, bucket=4)
    assert out["state"].shape == (4, 3) and out["state"].dtype == np.float32
    np.testing.assert_array_equal(out["state"][0], [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(out["state"][1], [4.0, 5.0, 6.0])
    np.testing.assert_array_equal(out["state"][2:], 0.0)


def test_pad_obs_batch_validates_requests():
    template = {"state": ((3,), "float32")}
    good = {"state": np.zeros(3, dtype=np.float32)}
    with pytest.raises(ValueError, match="do not fit bucket 1"):
        pad_obs_batch([good, good], template, bucket=1)
    with pytest.raises(KeyError, match="missing obs key 'state'"):
        pad_obs_batch([{"wrong": np.zeros(3)}], template, bucket=2)
    with pytest.raises(ValueError, match=r"request shape \(4,\) != policy shape \(3,\)"):
        pad_obs_batch([{"state": np.zeros(4, dtype=np.float32)}], template, bucket=2)
