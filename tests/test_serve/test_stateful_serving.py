"""Stateful (recurrent) policy serving: the replica keeps per-session act
state device-resident (``serve/state_cache.py``) so a session-affine client
just sends observations — no state round-trips — and dispatches stay
recompile-free across sessions, resets and batch shapes."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.client import PolicyClient

MODEL = "serve_test_rppo"

TINY_RECURRENT = [
    "exp=ppo_recurrent",
    "env=jax_cartpole",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
    "algo.rnn.lstm.hidden_size=8",
    "env.num_envs=1",
    "env.capture_video=False",
]


@pytest.fixture(scope="module")
def registry(tmp_path_factory):
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import compose, save_config
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.model_manager import LocalModelManager
    from sheeprl_tpu.utils.policy import build_policy

    tmp = tmp_path_factory.mktemp("rppo_registry")
    cfg = compose(config_name="config", overrides=TINY_RECURRENT)
    env = make_env(cfg, 0, 0, None, "rppo_test")()
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    policy, params = build_policy(ctx, cfg, env.observation_space, env.action_space)
    env.close()

    ckpt = CheckpointManager(tmp / "run" / "checkpoints").save(0, {"params": params})
    save_config(cfg, tmp / "run" / "config.yaml")
    mm = LocalModelManager(registry_dir=tmp / "registry")
    mm.register_model(str(ckpt), MODEL)
    return tmp / "registry", policy.obs_template


def test_recurrent_policy_serves_sessions_without_recompiles(registry):
    registry_dir, obs_template = registry
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.serve.server import PolicyServer

    cfg = compose(
        config_name="serve_cli",
        overrides=[
            f"serve.policies=[{MODEL}:1]",
            f"model_manager.registry_dir={registry_dir}",
            "serve.host=127.0.0.1",
            "serve.port=0",
            "serve.max_batch_size=4",
            "serve.max_batch_delay_ms=2.0",
            "serve.session_capacity=8",
            "serve.log_every_s=0",
            "analysis.strict=True",  # any dispatch-time recompile raises
        ],
    )
    server = PolicyServer(cfg)
    ep = server.endpoints[f"{MODEL}:1"]
    assert ep.policy.stateful is True
    assert ep.state_cache is not None  # warmed at startup alongside the ladder

    rc_box = {}
    thread = threading.Thread(target=lambda: rc_box.update(rc=server.run()), daemon=True)
    thread.start()
    deadline = time.monotonic() + 30.0
    while server.listener is None:
        assert time.monotonic() < deadline
        time.sleep(0.01)

    obs = {k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()}
    try:
        with PolicyClient("127.0.0.1", server.listener.port) as client:
            # three interleaved sessions plus session-less traffic, mixed into
            # shared batches; all buckets and the reset path get exercised
            for step in range(6):
                for session in ("alice", "bob", "carol"):
                    action, meta = client.act(obs, MODEL, session=session)
                    assert action.shape == (len(ep.policy.action_dims),)
                    assert meta["bucket"] in ep.ladder
            client.act(obs, MODEL)  # stateless rider on the scratch row
            client.act(obs, MODEL, session="alice", reset=True)  # episode restart
    finally:
        server.shutdown()
        thread.join(timeout=30)

    assert rc_box.get("rc") == 0
    summary = server.summary()
    assert summary["accepted"] == summary["replied"] == 6 * 3 + 2
    assert summary["recompiles"] == 0  # sessions/resets never re-trace
    sessions = summary["policies"][f"{MODEL}:1"]["sessions"]
    assert sessions == {"capacity": 8, "sessions": 3, "evictions": 0}
