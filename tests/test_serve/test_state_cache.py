"""serve/state_cache.py: session -> device-row mapping for stateful policies."""

import numpy as np
import pytest

from sheeprl_tpu.serve.state_cache import SessionStateCache


def _zero_state(n):
    import jax.numpy as jnp

    return {"h": jnp.zeros((n, 3), jnp.float32), "prev": jnp.zeros((n, 2), jnp.float32)}


@pytest.fixture
def cache():
    return SessionStateCache(_zero_state, capacity=3)


def test_new_session_starts_fresh_and_then_continues(cache):
    idx, is_first = cache.assign(["alice"], [False])
    assert is_first[0, 0] == 1.0  # never seen: episode start regardless of reset
    row = int(idx[0])
    assert 0 <= row < cache.capacity

    idx2, is_first2 = cache.assign(["alice"], [False])
    assert int(idx2[0]) == row  # same session -> same device row
    assert is_first2[0, 0] == 0.0  # continuing the episode

    idx3, is_first3 = cache.assign(["alice"], [True])  # explicit episode restart
    assert int(idx3[0]) == row
    assert is_first3[0, 0] == 1.0


def test_sessionless_requests_ride_the_scratch_row(cache):
    idx, is_first = cache.assign([None, "bob", None], [False, False, False])
    assert int(idx[0]) == int(idx[2]) == cache.scratch
    assert is_first[0, 0] == is_first[2, 0] == 1.0
    assert int(idx[1]) != cache.scratch
    assert len(cache) == 1  # scratch traffic never occupies a session slot


def test_lru_eviction_and_returning_session_restarts(cache):
    for name in ("s0", "s1", "s2"):
        cache.assign([name], [False])
    cache.assign(["s0"], [False])  # refresh s0: s1 becomes the LRU
    idx_new, _ = cache.assign(["s3"], [False])  # full: evicts s1
    assert cache.evictions == 1
    assert len(cache) == 3

    # the evicted session coming back gets a fresh episode, not s3's state
    idx_back, is_first = cache.assign(["s1"], [False])
    assert is_first[0, 0] == 1.0
    assert cache.evictions == 2  # s1's return evicted the next LRU (s2)
    # the refreshed session was protected throughout
    idx_s0, is_first_s0 = cache.assign(["s0"], [False])
    assert is_first_s0[0, 0] == 0.0


def test_drop_frees_the_slot(cache):
    idx, _ = cache.assign(["alice"], [False])
    cache.drop("alice")
    assert len(cache) == 0
    idx2, is_first = cache.assign(["alice"], [False])
    assert is_first[0, 0] == 1.0  # dropped session restarts
    cache.drop("ghost")  # unknown session: no-op


def test_gather_scatter_roundtrip_and_padding_isolation(cache):
    idx, _ = cache.assign(["alice", "bob"], [False, False])
    # pad to a bucket of 4 the way the server does: scratch rows
    idx_p = np.full((4,), cache.scratch, np.int32)
    idx_p[:2] = idx
    rows = cache.gather(idx_p)
    assert rows["h"].shape == (4, 3)

    new_rows = {
        "h": np.arange(12, dtype=np.float32).reshape(4, 3),
        "prev": np.ones((4, 2), np.float32),
    }
    cache.scatter(idx_p, new_rows)
    # real sessions persisted their rows...
    got = np.asarray(cache.gather(idx_p)["h"])
    np.testing.assert_array_equal(got[:2], new_rows["h"][:2])
    # ...and padding rows only touched scratch — session slots are untouched
    storage_h = np.asarray(cache.storage["h"])
    untouched = [r for r in range(cache.capacity) if r not in set(int(i) for i in idx)]
    for r in untouched:
        np.testing.assert_array_equal(storage_h[r], np.zeros(3, np.float32))


def test_warmup_traces_every_bucket_and_stats(cache):
    cache.warmup([1, 2, 4, 4])
    cache.assign(["alice"], [False])
    stats = cache.stats()
    assert stats == {"capacity": 3, "sessions": 1, "evictions": 0}
