"""FleetFront + FleetClient against fake replicas: least-loaded routing,
draining bounce, death reroute (zero accepted-request loss), session affinity
reassignment, canary shadow accounting, park/admit.

The fakes speak the serve wire protocol over the real framed transport but
never import JAX — this pins the ROUTER's contract, not the server's (which
``test_server.py`` owns)."""

import json
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.distributed.transport import ChannelClosed, FramingError, Listener
from sheeprl_tpu.serve.client import FleetClient, PolicyClient
from sheeprl_tpu.serve.fleet.front import FleetFront

OBS = {"state": np.zeros(2, dtype=np.float32)}


class FakeReplica:
    """A protocol-faithful policy-server stand-in: pong with load stats, echo a
    fixed action row per act.  ``mode="draining"`` bounces every act (but pongs
    healthy — the race the front's instant reroute exists for); ``hold.set()``
    accepts acts without replying (in-flight fodder for kill tests)."""

    def __init__(self, action=(0,), mode="echo"):
        self.listener = Listener(host="127.0.0.1", port=0)
        self.port = self.listener.port
        self.action = np.asarray(action)
        self.mode = mode
        self.served = []  # (policy, session, reset) per act received
        self.hold = threading.Event()
        self.channels = []
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                ch = self.listener.accept(timeout=0.2)
            except TimeoutError:
                continue
            except OSError:
                return
            self.channels.append(ch)
            threading.Thread(target=self._serve, args=(ch,), daemon=True).start()

    def _serve(self, ch):
        while not self._stop.is_set():
            try:
                kind, meta, payload = ch.recv(timeout=0.2)
            except TimeoutError:
                continue
            except (ChannelClosed, FramingError, OSError):
                return
            try:
                if kind == "ping":
                    ch.send("pong", policies=["m:1"], aliases=["m:1"], draining=False,
                            queue_depth=0, p99_ms=1.0)
                elif kind == "act":
                    fid = meta.get("req_id")
                    self.served.append(
                        (meta.get("policy"), meta.get("session"), bool(meta.get("reset")))
                    )
                    if self.mode == "draining":
                        ch.send("draining", req_id=fid)
                    elif self.hold.is_set():
                        pass  # accepted, never answered: in-flight until the kill
                    else:
                        ch.send("act_result", req_id=fid, payload={"action": self.action},
                                queue_ms=0.1, infer_ms=0.2, batch_fill=1.0, bucket=1,
                                p99_ms=1.0)
            except (ChannelClosed, OSError):
                return

    def acts(self):
        return [row for row in self.served]

    def kill(self):
        """SIGKILL equivalent: listener and every channel die abruptly."""
        self._stop.set()
        self.listener.close()
        for ch in self.channels:
            try:
                ch.close()
            except Exception:
                pass


def _start_front(endpoints, extra=()):
    from sheeprl_tpu.config.core import compose

    cfg = compose(
        config_name="serve_cli",
        overrides=[
            "serve.fleet.enabled=True",
            f"serve.fleet.replicas=[{','.join(endpoints)}]",
            "serve.fleet.host=127.0.0.1",
            "serve.fleet.port=0",
            "serve.fleet.probe_interval_s=0.1",
            "serve.fleet.status_interval_s=0.1",
            "serve.fleet.park_timeout_s=3.0",
            "serve.drain_timeout_s=5.0",
            *extra,
        ],
    )
    front = FleetFront(cfg)
    rc_box = {}
    thread = threading.Thread(target=lambda: rc_box.update(rc=front.run()), daemon=True)
    thread.start()
    deadline = time.monotonic() + 10.0
    while front.listener is None:
        assert time.monotonic() < deadline, "front never started listening"
        time.sleep(0.01)
    return front, thread, rc_box


def _stop_front(front, thread, rc_box):
    front.shutdown()
    thread.join(timeout=30)
    assert not thread.is_alive()
    assert rc_box.get("rc") == 0  # clean stop, not the preemption exit


def _endpoint(fake):
    return f"127.0.0.1:{fake.port}"


def test_routing_and_reply_stamps_across_two_replicas():
    a, b = FakeReplica(), FakeReplica()
    front, thread, rc_box = _start_front([_endpoint(a), _endpoint(b)])
    try:
        with PolicyClient("127.0.0.1", front.listener.port) as client:
            pong = client.ping()
            assert set(pong["fleet"]["replicas"]) == {"static0", "static1"}
            for _ in range(6):
                action, meta = client.act(OBS, "m:1")
                np.testing.assert_array_equal(action, [0])
                assert meta["replica"] in ("static0", "static1")
                assert meta["front_ms"] >= 0
                assert meta["bucket"] == 1  # the replica's stamps ride through
    finally:
        _stop_front(front, thread, rc_box)
    summary = front.summary()
    assert summary["accepted"] == summary["replied"] == 6
    assert summary["errors"] == summary["dropped"] == 0
    assert len(a.acts()) + len(b.acts()) == 6


def test_draining_reply_bounces_to_a_live_replica():
    # static0 pongs healthy but bounces every act — the front must reroute the
    # bounced request instantly and stop routing there.
    a, b = FakeReplica(mode="draining"), FakeReplica(action=(7,))
    front, thread, rc_box = _start_front([_endpoint(a), _endpoint(b)])
    try:
        with PolicyClient("127.0.0.1", front.listener.port) as client:
            for _ in range(3):
                action, meta = client.act(OBS, "m:1")
                np.testing.assert_array_equal(action, [7])
                assert meta["replica"] == "static1"
    finally:
        _stop_front(front, thread, rc_box)
    assert front.rerouted >= 1  # the bounce
    assert front.summary()["accepted"] == front.summary()["replied"] == 3


def test_replica_death_reroutes_in_flight_with_zero_loss():
    a, b = FakeReplica(), FakeReplica()
    a.hold.set()  # static0 swallows acts: they stay in flight
    front, thread, rc_box = _start_front([_endpoint(a), _endpoint(b)])
    results = {}
    try:
        def blocked_client():
            with PolicyClient("127.0.0.1", front.listener.port) as client:
                results["blocked"] = client.act(OBS, "m:1", timeout=30)

        t = threading.Thread(target=blocked_client, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not a.acts():  # the first act landed on static0 (name tiebreak)
            assert time.monotonic() < deadline
            time.sleep(0.01)

        # a second client routes around the loaded replica
        with PolicyClient("127.0.0.1", front.listener.port) as client:
            _, meta = client.act(OBS, "m:1", timeout=10)
            assert meta["replica"] == "static1"

        a.kill()  # no drain, no goodbye: the held request must be rerouted
        t.join(timeout=30)
        assert not t.is_alive(), "in-flight request was lost with its replica"
        assert results["blocked"][1]["replica"] == "static1"
    finally:
        _stop_front(front, thread, rc_box)
    assert front.rerouted >= 1
    summary = front.summary()
    assert summary["accepted"] == summary["replied"] == 2
    assert summary["errors"] == summary["dropped"] == 0


def test_session_affinity_sticks_and_reassigns_on_death():
    a, b = FakeReplica(), FakeReplica()
    front, thread, rc_box = _start_front([_endpoint(a), _endpoint(b)])
    try:
        with PolicyClient("127.0.0.1", front.listener.port) as client:
            owners = set()
            for _ in range(5):
                _, meta = client.act(OBS, "m:1", session="alice")
                owners.add(meta["replica"])
            assert len(owners) == 1  # affine: one owner while it lives
            owner = owners.pop()
            served = a if owner == "static0" else b
            assert all(s == "alice" for _, s, _ in served.acts())

            # reset rides the meta to the replica
            _, _ = client.act(OBS, "m:1", session="alice", reset=True)
            assert served.acts()[-1][2] is True

            served.kill()
            survivor = "static1" if owner == "static0" else "static0"
            for _ in range(3):
                _, meta = client.act(OBS, "m:1", session="alice", timeout=10)
                assert meta["replica"] == survivor  # reassigned, still affine
    finally:
        _stop_front(front, thread, rc_box)
    assert front.summary()["errors"] == 0


@pytest.mark.parametrize("canary_action,expect_promote", [((0,), True), ((9,), False)])
def test_canary_split_shadows_and_agreement_gate(canary_action, expect_promote):
    incumbent = FakeReplica(action=(0,))
    canary = FakeReplica(action=canary_action)
    front, thread, rc_box = _start_front(
        [_endpoint(incumbent), f"canary@{_endpoint(canary)}"],
        extra=["serve.fleet.canary.spec=m:2", "serve.fleet.canary.fraction=0.5"],
    )
    try:
        with PolicyClient("127.0.0.1", front.listener.port) as client:
            actions = [client.act(OBS, "m:1")[0][0] for _ in range(4)]
        # error diffusion: acts 2 and 4 hit the canary, the client saw its answers
        assert actions == [0, canary_action[0], 0, canary_action[0]]
        # every canary-routed act was shadowed on the incumbent
        deadline = time.monotonic() + 10.0
        while front.canary.compared < 2:
            assert time.monotonic() < deadline, front.canary.summary()
            time.sleep(0.01)
    finally:
        _stop_front(front, thread, rc_box)
    assert len(canary.acts()) == 2
    assert len(incumbent.acts()) == 4  # 2 direct + 2 shadows
    stamp = front.summary()["canary"]
    assert stamp["spec"] == "m:2" and stamp["routed"] == stamp["compared"] == 2
    assert stamp["agreement"] == (1.0 if expect_promote else 0.0)
    assert stamp["promote"] is expect_promote
    # the canary never takes normal traffic: the 2 direct acts went incumbent-side
    assert front.summary()["accepted"] == front.summary()["replied"] == 4


def test_requests_park_until_a_replica_is_admitted(tmp_path):
    front, thread, rc_box = _start_front([], extra=[f"serve.fleet.dir={tmp_path}"])
    result = {}
    try:
        def patient_client():
            with PolicyClient("127.0.0.1", front.listener.port) as client:
                result["reply"] = client.act(OBS, "m:1", timeout=30)

        t = threading.Thread(target=patient_client, daemon=True)
        t.start()
        time.sleep(0.3)  # the act is parked: no replica exists yet
        assert "reply" not in result

        fake = FakeReplica(action=(3,))
        record = {"name": "replica0", "host": "127.0.0.1", "port": fake.port,
                  "canary": False, "generation": 0, "pid": 4242}
        records_dir = tmp_path / "replicas"
        records_dir.mkdir(exist_ok=True)
        (records_dir / "replica0.json").write_text(json.dumps(record))

        t.join(timeout=30)  # discovery admits the record, the parked act flushes
        assert not t.is_alive(), "parked request never routed"
        np.testing.assert_array_equal(result["reply"][0], [3])
        assert result["reply"][1]["replica"] == "replica0"

        # the periodic status file catches up with the admission
        deadline = time.monotonic() + 5.0
        while True:
            status = json.loads((tmp_path / "front_status.json").read_text())
            if "replica0" in status["replicas"] or time.monotonic() > deadline:
                break
            time.sleep(0.05)
        assert status["replicas"]["replica0"]["queue_depth"] == 0
    finally:
        _stop_front(front, thread, rc_box)
    assert front.replicas_admitted == 1
    assert front.summary()["accepted"] == front.summary()["replied"] == 1


# ------------------------------------------------------------- FleetClient
def test_fleet_client_fails_over_to_a_live_endpoint():
    dead = Listener(host="127.0.0.1", port=0)
    dead_port = dead.port
    dead.close()
    live = FakeReplica(action=(5,))
    with FleetClient(
        [("127.0.0.1", dead_port), ("127.0.0.1", live.port)],
        timeout_s=2.0, backoff_s=0.01, backoff_max_s=0.02,
    ) as fc:
        action, _ = fc.act(OBS, "m:1")
        np.testing.assert_array_equal(action, [5])
        assert fc.failovers >= 1 and fc.retries >= 1
        assert fc.ping()["policies"] == ["m:1"]


def test_fleet_client_rotates_off_a_draining_endpoint():
    draining = FakeReplica(mode="draining")
    live = FakeReplica(action=(8,))
    with FleetClient(
        [_endpoint(draining), _endpoint(live)], backoff_s=0.01, backoff_max_s=0.02
    ) as fc:
        action, _ = fc.act(OBS, "m:1")
        np.testing.assert_array_equal(action, [8])
        assert fc.failovers == 1


def test_fleet_client_bounded_retries_then_raises():
    dead = Listener(host="127.0.0.1", port=0)
    dead_port = dead.port
    dead.close()
    with FleetClient(
        [("127.0.0.1", dead_port)], timeout_s=1.0, max_attempts=3,
        backoff_s=0.01, backoff_max_s=0.02,
    ) as fc:
        with pytest.raises(ConnectionError, match="after 3 attempts"):
            fc.act(OBS, "m:1")
        assert fc.retries == 3
