"""FleetManager process-lifecycle policy with fake child processes: death
classification (crash backoff / preemption / retirement), ready -> record
publication, and autoscale decisions.  No real subprocesses, no JAX."""

import json
import signal
import time

import pytest

from sheeprl_tpu.fault.preemption import RESUMABLE_EXIT_CODE
from sheeprl_tpu.serve.fleet.manager import FleetManager


class FakeProc:
    def __init__(self, rc=None, pid=4242):
        self.returncode = rc  # None = still running
        self.pid = pid
        self.signals = []

    def poll(self):
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(sig)

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        if self.returncode is None:
            self.returncode = -9


@pytest.fixture
def manager(tmp_path):
    from sheeprl_tpu.config.core import compose

    overrides = [
        "serve.fleet.enabled=True",
        f"serve.fleet.dir={tmp_path}",
        "serve.fleet.min_replicas=1",
        "serve.fleet.max_replicas=2",
        "serve.fleet.scale_up_queue_depth=4.0",
        "serve.fleet.scale_up_after_s=0.0",
        "serve.fleet.scale_down_after_s=0.0",
        "serve.fleet.scale_cooldown_s=0.0",
        "fault.max_retries=2",
        "fault.backoff_s=2.0",
        "fault.backoff_max_s=60.0",
    ]
    cfg = compose(config_name="serve_cli", overrides=overrides)
    mgr = FleetManager(overrides, cfg)
    mgr.spawned = []

    def fake_spawn(slot):
        mgr.spawned.append(slot.name)
        slot.proc = FakeProc()
        slot.ready_recorded = False

    mgr._spawn = fake_spawn
    return mgr


def _running_replica(mgr, name="replica0", index=0):
    slot = mgr._make_slot(name, index, "replica")
    slot.proc = FakeProc()
    return slot


def test_crash_consumes_retry_budget_with_exponential_backoff(manager):
    slot = _running_replica(manager)
    slot.record_path.write_text("{}")  # the front's admission record

    slot.proc.returncode = 1
    t0 = time.monotonic()
    assert manager._reap() is None
    assert (slot.retries, slot.consecutive, slot.generation) == (1, 1, 1)
    assert not slot.record_path.exists()  # the dead replica is de-published
    assert slot.proc is None
    assert slot.next_spawn_at - t0 == pytest.approx(2.0, abs=0.5)  # base backoff
    manager._respawn_due()
    assert manager.spawned == []  # backoff holds the respawn

    slot.proc = FakeProc(rc=1)
    assert manager._reap() is None
    assert (slot.retries, slot.consecutive) == (2, 2)
    assert slot.next_spawn_at - time.monotonic() == pytest.approx(4.0, abs=0.5)  # doubled

    # third crash exceeds fault.max_retries=2; the lone replica slot is
    # abandoned, so the whole fleet gives up
    slot.proc = FakeProc(rc=1)
    assert manager._reap() == 1
    assert slot.abandoned is True
    assert manager.summary["outcome"] == "retry_budget"


def test_preemption_respawns_immediately_and_resets_the_backoff_clock(manager):
    slot = _running_replica(manager)
    slot.proc.returncode = 1
    assert manager._reap() is None
    assert slot.consecutive == 1

    slot.proc = FakeProc(rc=RESUMABLE_EXIT_CODE)
    assert manager._reap() is None
    assert slot.preemptions == 1
    assert slot.consecutive == 0  # a clean drain proves the binary healthy
    assert slot.retries == 1  # preemptions never consume the crash budget
    assert slot.next_spawn_at == 0.0
    manager._respawn_due()
    assert manager.spawned == ["replica0"]  # respawned with no delay
    assert slot.generation == 2


def test_scaled_down_slot_retires_instead_of_respawning(manager):
    slot = _running_replica(manager)
    slot.desired = False  # the autoscaler's drain request
    slot.proc.returncode = RESUMABLE_EXIT_CODE
    assert manager._reap() is None
    assert "replica0" not in manager.slots
    manager._respawn_due()
    assert manager.spawned == []


def test_front_clean_exit_stops_the_fleet(manager):
    front = manager._make_slot("front", 0, "front")
    front.proc = FakeProc(rc=0)
    assert manager._reap() == 0
    assert manager.summary["outcome"] == "clean"


def test_ready_file_becomes_the_admission_record(manager):
    slot = _running_replica(manager)
    slot.ready_file.write_text(json.dumps({"host": "127.0.0.1", "port": 7001}))
    manager._check_ready()
    assert slot.ready_recorded is True
    record = json.loads(slot.record_path.read_text())
    assert record == {
        "name": "replica0",
        "host": "127.0.0.1",
        "port": 7001,
        "canary": False,
        "generation": 0,
        "pid": slot.proc.pid,
    }


def test_autoscaler_spawns_on_load_and_drains_the_highest_index_on_idle(manager, tmp_path):
    slot = _running_replica(manager)
    slot.ready_recorded = True

    (tmp_path / "front_status.json").write_text(json.dumps({"pending": 50.0}))
    deadline = time.monotonic() + 5.0
    while manager.summary["scale_ups"] == 0 and time.monotonic() < deadline:
        manager._autoscale()
        time.sleep(0.02)
    assert manager.summary["scale_ups"] == 1
    assert manager.spawned == ["replica1"]
    assert "replica1" in manager.slots

    # hot forever at max_replicas=2: never a third
    manager.slots["replica1"].ready_recorded = True
    for _ in range(5):
        manager._autoscale()
        time.sleep(0.02)
    assert manager.summary["scale_ups"] == 1

    (tmp_path / "front_status.json").write_text(json.dumps({"pending": 0.0}))
    deadline = time.monotonic() + 5.0
    while manager.summary["scale_downs"] == 0 and time.monotonic() < deadline:
        manager._autoscale()
        time.sleep(0.02)
    assert manager.summary["scale_downs"] == 1
    victim = manager.slots["replica1"]
    assert victim.desired is False  # drained, not respawned
    assert victim.proc.signals == [signal.SIGTERM]

    # idle forever at min_replicas=1: the last replica is never drained
    for _ in range(5):
        manager._autoscale()
        time.sleep(0.02)
    assert manager.summary["scale_downs"] == 1
    assert manager.slots["replica0"].desired is True
