"""PolicyServer end-to-end: registry load → AOT ladder → continuous batching →
drain.  One tiny untrained PPO policy (serving cost is weight-agnostic) is
checkpointed + registered once per module; each test spins an in-process server
thread against it."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.distributed.transport import ChannelClosed
from sheeprl_tpu.fault.preemption import clear_preemption, request_preemption
from sheeprl_tpu.serve.client import PolicyClient, ServerDraining

MODEL = "serve_test_ppo"

TINY_PPO = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.encoder.mlp_features_dim=8",
    "env.num_envs=1",
    "env.capture_video=False",
]


@pytest.fixture(scope="module")
def registry(tmp_path_factory):
    """``(registry_dir, obs_template)``: two registered versions of the same tiny
    PPO checkpoint, v1 transitioned to the ``production`` stage."""
    import jax

    from sheeprl_tpu.checkpoint.manager import CheckpointManager
    from sheeprl_tpu.config.core import compose, save_config
    from sheeprl_tpu.parallel.mesh import MeshContext, build_mesh
    from sheeprl_tpu.utils.env import make_env
    from sheeprl_tpu.utils.model_manager import LocalModelManager
    from sheeprl_tpu.utils.policy import build_policy

    tmp = tmp_path_factory.mktemp("serve_registry")
    cfg = compose(config_name="config", overrides=TINY_PPO)
    env = make_env(cfg, 0, 0, None, "serve_test")()
    ctx = MeshContext(mesh=build_mesh(devices=jax.devices()[:1]), precision="fp32", seed=0)
    policy, params = build_policy(ctx, cfg, env.observation_space, env.action_space)
    env.close()

    ckpt = CheckpointManager(tmp / "run" / "checkpoints").save(0, {"params": params})
    save_config(cfg, tmp / "run" / "config.yaml")
    mm = LocalModelManager(registry_dir=tmp / "registry")
    mm.register_model(str(ckpt), MODEL)
    mm.register_model(str(ckpt), MODEL)
    mm.transition_model(MODEL, 1, "production")
    return tmp / "registry", policy.obs_template


def _zero_obs(obs_template):
    return {k: np.zeros(shape, dtype=np.dtype(dtype)) for k, (shape, dtype) in obs_template.items()}


def _start_server(registry_dir, policies, max_batch=4, delay_ms=2.0, extra=()):
    """Compose serve_cli, build the server (precompiles the ladder), run it in a
    thread; returns ``(server, thread, rc_box)`` once the listener is up."""
    from sheeprl_tpu.config.core import compose
    from sheeprl_tpu.serve.server import PolicyServer

    cfg = compose(
        config_name="serve_cli",
        overrides=[
            f"serve.policies=[{','.join(policies)}]",
            f"model_manager.registry_dir={registry_dir}",
            "serve.host=127.0.0.1",
            "serve.port=0",
            f"serve.max_batch_size={max_batch}",
            f"serve.max_batch_delay_ms={delay_ms}",
            "serve.log_every_s=0",
            "analysis.strict=True",
            *extra,
        ],
    )
    server = PolicyServer(cfg)
    rc_box = {}
    thread = threading.Thread(target=lambda: rc_box.update(rc=server.run()), daemon=True)
    thread.start()
    deadline = time.monotonic() + 30.0
    while server.listener is None:
        assert time.monotonic() < deadline, "server never started listening"
        time.sleep(0.01)
    return server, thread, rc_box


def test_e2e_four_clients_all_replied_zero_recompiles(registry):
    registry_dir, obs_template = registry
    server, thread, rc_box = _start_server(registry_dir, [f"{MODEL}:1"])
    obs = _zero_obs(obs_template)
    clients, requests = 4, 10
    metas = [[] for _ in range(clients)]
    errors = []

    def worker(idx):
        try:
            with PolicyClient("127.0.0.1", server.listener.port) as client:
                n_heads = len(server.endpoints[f"{MODEL}:1"].policy.action_dims)
                for _ in range(requests):
                    action, meta = client.act(obs, MODEL)
                    assert action.shape == (n_heads,)  # one row: [heads] action indices
                    metas[idx].append(meta)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
    finally:
        server.shutdown()
        thread.join(timeout=30)

    assert rc_box.get("rc") == 0  # clean shutdown, not the preemption exit code
    summary = server.summary()
    assert summary["accepted"] == summary["replied"] == clients * requests
    assert summary["dropped"] == 0
    # the AOT ladder makes post-warmup compilation impossible (analysis.strict=True
    # would have raised RecompileError inside a dispatch otherwise)
    assert summary["recompiles"] == 0
    # every reply carries the SLO stamps
    for meta in (m for per_client in metas for m in per_client):
        assert meta["bucket"] in server.endpoints[f"{MODEL}:1"].ladder
        assert meta["queue_ms"] >= 0 and meta["infer_ms"] > 0
        assert 0 < meta["batch_fill"] <= 1.0
        assert meta["p99_ms"] > 0


def test_multi_policy_routing_and_unknown_policy(registry):
    registry_dir, obs_template = registry
    server, thread, _ = _start_server(registry_dir, [f"{MODEL}:1", f"{MODEL}:2"])
    obs = _zero_obs(obs_template)
    try:
        with PolicyClient("127.0.0.1", server.listener.port) as client:
            pong = client.ping()
            assert pong["policies"] == [f"{MODEL}:1", f"{MODEL}:2"]
            # v1 was transitioned to "production": the stage alias routes to it
            assert f"{MODEL}:production" in pong["aliases"]

            for _ in range(3):
                client.act(obs, f"{MODEL}:2")
            client.act(obs, MODEL)  # bare name -> first-loaded version (v1)
            client.act(obs, f"{MODEL}:production")

            with pytest.raises(RuntimeError, match="no policy routed as 'ghost'"):
                client.act(obs, "ghost")
    finally:
        server.shutdown()
        thread.join(timeout=30)

    per_policy = server.summary()["policies"]
    assert per_policy[f"{MODEL}:2"]["accepted"] == per_policy[f"{MODEL}:2"]["replied"] == 3
    assert per_policy[f"{MODEL}:1"]["accepted"] == per_policy[f"{MODEL}:1"]["replied"] == 2


def test_int8_serving_parity_stamp_and_zero_recompiles(registry):
    """serve.precision=int8: the ladder compiles against the quantized params,
    the parity stamp (vs an f32 reference reload) lands in pong/summary with
    high greedy agreement, and dispatches stay recompile-free under strict."""
    registry_dir, obs_template = registry
    server, thread, rc_box = _start_server(
        registry_dir, [f"{MODEL}:1"], extra=["serve.precision=int8"]
    )
    obs = _zero_obs(obs_template)
    try:
        import jax

        from sheeprl_tpu.precision import Int8Weight

        assert server.precision == "int8"
        ep = server.endpoints[f"{MODEL}:1"]
        assert ep.policy.precision == "int8"
        kernels = [
            leaf
            for leaf in jax.tree.leaves(
                ep.policy.params, is_leaf=lambda x: isinstance(x, Int8Weight)
            )
            if isinstance(leaf, Int8Weight)
        ]
        assert kernels, "no 2-D kernel was quantized"

        stamp = server.parity[f"{MODEL}:1"]
        assert stamp["precision"] == "int8" and stamp["reference"] == "f32"
        assert stamp["action_agreement"] >= 0.99

        with PolicyClient("127.0.0.1", server.listener.port) as client:
            pong = client.ping()
            assert pong["precision"] == "int8"
            assert pong["parity"][f"{MODEL}:1"]["action_agreement"] >= 0.99
            for _ in range(5):
                action, meta = client.act(obs, MODEL)
                assert meta["bucket"] in ep.ladder
    finally:
        server.shutdown()
        thread.join(timeout=30)

    assert rc_box.get("rc") == 0
    summary = server.summary()
    assert summary["precision"] == "int8"
    assert summary["parity"][f"{MODEL}:1"]["action_agreement"] >= 0.99
    assert summary["accepted"] == summary["replied"] == 5
    assert summary["recompiles"] == 0


def test_preemption_drains_and_replies_to_everything_accepted(registry):
    registry_dir, obs_template = registry
    server, thread, rc_box = _start_server(registry_dir, [f"{MODEL}:1"])
    obs = _zero_obs(obs_template)
    replies = [0, 0, 0]

    def streamer(idx):
        # closed-loop until the replica drains out from under us: a "draining"
        # reply or a closed channel are BOTH clean endings — never a lost reply.
        try:
            with PolicyClient("127.0.0.1", server.listener.port) as client:
                while True:
                    client.act(obs, MODEL, timeout=30)
                    replies[idx] += 1
        except (ServerDraining, ChannelClosed, ConnectionError, TimeoutError, OSError):
            pass

    try:
        threads = [threading.Thread(target=streamer, args=(i,), daemon=True) for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 20.0
        while sum(replies) < 30 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sum(replies) >= 30, "clients never got going"

        request_preemption("chaos: simulated SIGTERM")
        thread.join(timeout=30)
        assert not thread.is_alive()
        for t in threads:
            t.join(timeout=30)
    finally:
        clear_preemption()
        server.shutdown()

    assert rc_box.get("rc") == 75  # RESUMABLE_EXIT_CODE: the supervisor respawns
    summary = server.summary(preempted=True)
    assert summary["preempted"] is True
    # the drain contract: every accepted request was answered before exit
    assert summary["accepted"] == summary["replied"]
    assert summary["dropped"] == 0
    assert summary["replied"] >= sum(replies)


# -------------------------------------------- stats counters (jaxlint JL008 fix)
def test_endpoint_accepted_counter_is_lock_guarded():
    """``accepted`` is bumped by one reader thread per client connection — a bare
    ``+=`` loses updates under contention.  Pin the lock's existence and the
    guarded-increment contract; the e2e suites pin accepted == replied."""
    import inspect

    from sheeprl_tpu.serve.server import PolicyServer, _Endpoint

    ep = _Endpoint("m", 1, policy=None, compiled=None, ladder=[], queue_depth=4, seed=0)
    assert hasattr(ep, "stats_lock")

    n_threads, n_each = 8, 200

    def bump():
        for _ in range(n_each):
            with ep.stats_lock:
                ep.accepted += 1

    threads = [threading.Thread(target=bump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ep.accepted == n_threads * n_each

    # the reader path actually uses the guards (regression against silently
    # dropping the `with` blocks in a refactor)
    src = inspect.getsource(PolicyServer._handle)
    assert "with ep.stats_lock:" in src
    assert "with self._stats_lock:" in src
