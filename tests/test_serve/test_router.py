"""serve/router.py: ``name[:selector]`` spec grammar + registry resolution."""

import pytest

from sheeprl_tpu.serve.router import (
    parse_spec,
    resolve_policy,
    resolve_registry_checkpoint,
    resolve_version,
)
from sheeprl_tpu.utils.model_manager import LocalModelManager


def _versions(*entries):
    return [{"version": v, "stage": s, "path": f"/reg/m/v{v}"} for v, s in entries]


def test_parse_spec_grammar():
    assert parse_spec("cartpole_ppo") == ("cartpole_ppo", None)
    assert parse_spec("cartpole_ppo:latest") == ("cartpole_ppo", "latest")
    assert parse_spec("cartpole_ppo:3") == ("cartpole_ppo", 3)
    assert parse_spec("cartpole_ppo:production") == ("cartpole_ppo", "production")
    assert parse_spec(" padded : 2 ") == ("padded", 2)
    assert parse_spec("name:") == ("name", None)  # trailing colon == bare name
    with pytest.raises(ValueError, match="empty policy name"):
        parse_spec(":latest")


def test_resolve_version_latest_and_exact():
    vs = _versions((1, "None"), (3, "production"), (2, "staging"))
    assert resolve_version(vs, None)["version"] == 3
    assert resolve_version(vs, "latest")["version"] == 3
    assert resolve_version(vs, 2)["version"] == 2
    with pytest.raises(ValueError, match=r"no version 9 \(registered: \[1, 2, 3\]\)"):
        resolve_version(vs, 9)
    with pytest.raises(ValueError, match="no registered versions"):
        resolve_version([], None)


def test_resolve_version_stage_is_case_insensitive_and_picks_newest():
    vs = _versions((1, "Production"), (2, "staging"), (3, "PRODUCTION"))
    assert resolve_version(vs, "production")["version"] == 3
    assert resolve_version(vs, "STAGING")["version"] == 2
    with pytest.raises(ValueError, match="stages present"):
        resolve_version(vs, "archived")


def test_resolve_policy_against_registry(tmp_path):
    ckpt = tmp_path / "ckpt_1"
    ckpt.mkdir()
    (ckpt / "params.msgpack").write_bytes(b"p")
    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    mm.register_model(str(ckpt), "m")
    mm.register_model(str(ckpt), "m")
    mm.transition_model("m", 1, "production")

    assert resolve_policy(mm, "m")[1]["version"] == 2
    assert resolve_policy(mm, "m:latest")[1]["version"] == 2
    assert resolve_policy(mm, "m:1")[1]["version"] == 1
    assert resolve_policy(mm, "m:production")[1]["version"] == 1

    # unknown model: the error lists what IS registered
    with pytest.raises(ValueError, match=r"no registered model named 'ghost' \(registry has: \['m'\]\)"):
        resolve_policy(mm, "ghost")
    # unknown selector: the error carries the full spec for log greppability
    with pytest.raises(ValueError, match=r"cannot resolve 'm:7'"):
        resolve_policy(mm, "m:7")


def test_resolve_registry_checkpoint_for_eval(tmp_path):
    """The eval CLI's spec → payload-path resolution (same grammar, filesystem
    routing before any config composes)."""
    ckpt = tmp_path / "ckpt_1"
    ckpt.mkdir()
    (ckpt / "params.msgpack").write_bytes(b"p")
    mm = LocalModelManager(registry_dir=tmp_path / "registry")
    mm.register_model(str(ckpt), "m")

    overrides = [f"model_manager.registry_dir={tmp_path / 'registry'}"]
    name, version, payload = resolve_registry_checkpoint("m:1", overrides)
    assert (name, version) == ("m", 1)
    assert (payload / "params.msgpack").is_file()

    with pytest.raises(ValueError, match="no registry exists"):
        resolve_registry_checkpoint("m:1", [f"model_manager.registry_dir={tmp_path / 'nope'}"])
