"""serve/fleet primitives: least-loaded pick, session-affine ring, autoscaler
hysteresis, canary accounting.  All pure — no sockets, no processes."""

import math

import numpy as np
import pytest

from sheeprl_tpu.serve.fleet.autoscale import AutoscaleDecider
from sheeprl_tpu.serve.fleet.canary import CanaryTracker, rows_agree
from sheeprl_tpu.serve.fleet.routing import HashRing, ReplicaLoad, pick_replica, routable


# ------------------------------------------------------------- least-loaded
def test_pick_replica_least_loaded_and_exclusions():
    loads = {
        "a": ReplicaLoad(inflight=3),
        "b": ReplicaLoad(inflight=1, queue_depth=1.0),
        "c": ReplicaLoad(inflight=1, queue_depth=3.0),
    }
    assert pick_replica(loads) == "b"  # score = inflight + queue_depth
    assert pick_replica(loads, exclude=("b",)) == "a"
    assert pick_replica(loads, exclude=("a", "b", "c")) is None
    assert pick_replica({}) is None


def test_pick_replica_skips_draining_and_dead():
    loads = {
        "idle_but_draining": ReplicaLoad(inflight=0, draining=True),
        "idle_but_dead": ReplicaLoad(inflight=0, alive=False),
        "busy": ReplicaLoad(inflight=9),
    }
    assert not routable(loads["idle_but_draining"])
    assert not routable(loads["idle_but_dead"])
    assert pick_replica(loads) == "busy"
    loads["busy"].draining = True
    assert pick_replica(loads) is None


def test_pick_replica_ties_break_on_p99_then_name():
    loads = {
        "slow": ReplicaLoad(inflight=1, p99_ms=40.0),
        "fast": ReplicaLoad(inflight=1, p99_ms=5.0),
    }
    assert pick_replica(loads) == "fast"
    # NaN p99 (no reply stamp seen yet) sorts AFTER any measured p99...
    loads["unknown"] = ReplicaLoad(inflight=1, p99_ms=math.nan)
    assert pick_replica(loads) == "fast"
    # ...and two unknowns fall back to the name for determinism.
    only_nan = {"b": ReplicaLoad(p99_ms=math.nan), "a": ReplicaLoad(p99_ms=math.nan)}
    assert pick_replica(only_nan) == "a"


# ---------------------------------------------------------- consistent hash
def test_hash_ring_assignment_is_stable():
    ring = HashRing()
    for member in ("replica0", "replica1", "replica2"):
        ring.add(member)
    sessions = [f"client{i}" for i in range(200)]
    first = {s: ring.assign(s) for s in sessions}
    # stable across repeated lookups
    assert all(ring.assign(s) == first[s] for s in sessions)
    # stable across an independently-built ring (pure function of the labels)
    other = HashRing()
    for member in ("replica2", "replica0", "replica1"):  # insertion order irrelevant
        other.add(member)
    assert all(other.assign(s) == first[s] for s in sessions)
    # every member owns a share (vnodes keep it roughly balanced)
    owners = set(first.values())
    assert owners == {"replica0", "replica1", "replica2"}


def test_hash_ring_death_reassigns_only_the_dead_members_sessions():
    ring = HashRing()
    for member in ("replica0", "replica1", "replica2"):
        ring.add(member)
    sessions = [f"client{i}" for i in range(300)]
    before = {s: ring.assign(s) for s in sessions}
    ring.remove("replica1")
    assert "replica1" not in ring
    after = {s: ring.assign(s) for s in sessions}
    for s in sessions:
        if before[s] == "replica1":
            assert after[s] in ("replica0", "replica2")  # reassigned somewhere live
        else:
            assert after[s] == before[s]  # survivors keep every session


def test_hash_ring_add_steals_minimally_and_empty_ring():
    ring = HashRing()
    assert ring.assign("anyone") is None
    ring.add("replica0")
    ring.add("replica1")
    sessions = [f"client{i}" for i in range(300)]
    before = {s: ring.assign(s) for s in sessions}
    ring.add("replica2")
    after = {s: ring.assign(s) for s in sessions}
    moved = [s for s in sessions if after[s] != before[s]]
    # only sessions stolen BY the newcomer move — nobody shuffles between survivors
    assert all(after[s] == "replica2" for s in moved)
    assert 0 < len(moved) < len(sessions)
    assert ring.members() == ["replica0", "replica1", "replica2"]


# -------------------------------------------------------------- autoscaler
def test_autoscaler_scales_up_only_on_sustained_load():
    d = AutoscaleDecider(scale_up_queue_depth=4.0, scale_up_after_s=3.0, cooldown_s=5.0)
    assert d.decide(0.0, live=1, pending=8.0) is None  # hot, clock starts
    assert d.decide(2.0, live=1, pending=8.0) is None  # not sustained yet
    assert d.decide(3.5, live=1, pending=8.0) == "up"  # 3.5s >= 3.0s sustained
    # a spike that dips resets the clock — no flapping on bursty load
    d = AutoscaleDecider(scale_up_queue_depth=4.0, scale_up_after_s=3.0)
    assert d.decide(0.0, live=1, pending=8.0) is None
    assert d.decide(2.0, live=1, pending=1.0) is None  # dead zone: clock resets
    assert d.decide(3.5, live=1, pending=8.0) is None  # hot again, fresh clock
    assert d.decide(7.0, live=1, pending=8.0) == "up"


def test_autoscaler_scales_down_on_sustained_idle_and_respects_bounds():
    d = AutoscaleDecider(min_replicas=1, max_replicas=2, scale_down_after_s=10.0, cooldown_s=0.0)
    assert d.decide(0.0, live=2, pending=0.0) is None
    assert d.decide(9.0, live=2, pending=0.0) is None
    assert d.decide(10.5, live=2, pending=0.0) == "down"
    # at the floor: idle forever never drops below min_replicas
    d = AutoscaleDecider(min_replicas=1, scale_down_after_s=1.0, cooldown_s=0.0)
    assert d.decide(0.0, live=1, pending=0.0) is None
    assert d.decide(100.0, live=1, pending=0.0) is None
    # at the ceiling: hot forever never grows past max_replicas
    d = AutoscaleDecider(max_replicas=2, scale_up_after_s=1.0, cooldown_s=0.0)
    assert d.decide(0.0, live=2, pending=99.0) is None
    assert d.decide(100.0, live=2, pending=99.0) is None


def test_autoscaler_cooldown_blocks_back_to_back_decisions():
    d = AutoscaleDecider(
        max_replicas=4, scale_up_queue_depth=4.0, scale_up_after_s=1.0, cooldown_s=5.0
    )
    assert d.decide(0.0, live=1, pending=50.0) is None
    assert d.decide(1.5, live=1, pending=50.0) == "up"
    # still hot, but the fresh replica needs time to absorb load first
    assert d.decide(2.0, live=2, pending=50.0) is None
    assert d.decide(4.0, live=2, pending=50.0) is None
    assert d.decide(8.0, live=2, pending=50.0) == "up"  # cooldown over, load sustained


# ------------------------------------------------------------------ canary
def test_canary_error_diffusion_routes_exact_fraction():
    tracker = CanaryTracker("m:2", fraction=0.25)
    taken = [tracker.take() for _ in range(100)]
    assert sum(taken) == 25  # exactly round(n * fraction), not approximately
    assert tracker.routed == 25
    # the pattern is maximally spread (every 4th request), not front-loaded
    assert taken[:8] == [False, False, False, True] * 2

    assert not any(CanaryTracker("m:2", fraction=0.0).take() for _ in range(10))


def test_canary_agreement_gate():
    tracker = CanaryTracker("m:2", fraction=0.5, min_agreement=0.99)
    assert math.isnan(tracker.agreement)
    assert tracker.promote is False  # no comparisons -> no promotion
    for _ in range(99):
        tracker.record(np.array([1, 0]), np.array([1, 0]))
    assert tracker.promote is True
    tracker.record(np.array([1, 0]), np.array([0, 1]))  # one disagreement at n=100
    assert tracker.agreement == pytest.approx(0.99)
    assert tracker.promote is True
    tracker.record(np.array([1, 0]), np.array([0, 1]))
    assert tracker.promote is False  # dipped below the gate: not promoted
    s = tracker.summary()
    assert s["compared"] == 101 and s["promote"] is False
    assert s["agreement"] == pytest.approx(99 / 101)


def test_rows_agree_matches_precision_parity_semantics():
    """The front's numpy-only re-implementation must agree with PR-15's
    ``action_agreement`` (which the router cannot import: it pulls in JAX)."""
    from sheeprl_tpu.precision.parity import action_agreement

    rng = np.random.default_rng(0)
    for _ in range(50):
        # discrete: multi-head action index rows
        a = rng.integers(0, 3, size=(2,))
        b = a.copy() if rng.random() < 0.5 else rng.integers(0, 3, size=(2,))
        assert rows_agree(a, b) == (action_agreement(a[None], b[None]) == 1.0)
        # continuous: per-component atol
        x = rng.normal(size=(4,)).astype(np.float32)
        y = x + rng.choice([0.0, 5e-3, 5e-2]) * rng.choice([-1.0, 1.0])
        assert rows_agree(x, y, atol=1e-2) == (
            action_agreement(x[None], y[None], continuous=True, atol=1e-2) == 1.0
        )
    # shape mismatch can never agree
    assert not rows_agree(np.zeros(2), np.zeros(3))
